"""Model zoo tests (shapes, dtypes, param counts, policy interaction)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import ResNet18, ResNet50
from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet


def count_params(tree):
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


class TestViT:
    def test_forward_shapes_both_poolings(self):
        import dataclasses

        from pytorch_distributed_tpu.models import ViT, ViTConfig

        for pooling in ("cls", "mean"):
            cfg = dataclasses.replace(ViTConfig.tiny(), pooling=pooling)
            m = ViT(cfg)
            v = m.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
            out = m.apply(v, jnp.ones((2, 32, 32, 3)))
            assert out.shape == (2, 10)
            assert bool(jnp.all(jnp.isfinite(out)))
        # cls pooling carries an extra token in the position table
        n_cls = ViT(ViTConfig.tiny()).init(
            jax.random.key(0), jnp.zeros((1, 32, 32, 3))
        )["params"]["pos_embedding"].shape[1]
        assert n_cls == ViTConfig.tiny().num_patches + 1

    def test_wrong_image_size_raises(self):
        import pytest

        from pytorch_distributed_tpu.models import ViT, ViTConfig

        with pytest.raises(ValueError, match="images"):
            ViT(ViTConfig.tiny()).init(
                jax.random.key(0), jnp.zeros((1, 64, 64, 3))
            )

    def test_tp_rules_shard_encoder(self):
        from pytorch_distributed_tpu.models import (
            ViT, ViTConfig, vit_partition_rules,
        )
        from pytorch_distributed_tpu.parallel import FSDP
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh

        make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        m = ViT(ViTConfig.tiny())
        params = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))[
            "params"
        ]
        strategy = FSDP(extra_rules=vit_partition_rules())
        from pytorch_distributed_tpu.parallel.strategies import (
            infer_tree_shardings,
        )

        sh = infer_tree_shardings(
            params, strategy.param_rules(), strategy.mesh
        )
        qkv = sh["block_0"]["query"]["kernel"].spec
        assert "tp" in (qkv[1],), qkv
        # and the sharded model still runs under the strategy end to end
        import optax

        from pytorch_distributed_tpu.train import (
            TrainState, build_train_step,
        )

        def loss_fn(params, batch_stats, batch, rng):
            logits = m.apply(
                {"params": params}, batch["image"], train=False
            )
            labels = jax.nn.one_hot(batch["label"], 10)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * labels, axis=-1)
            )
            return loss, {"metrics": {"loss": loss}}

        state = strategy.place(
            TrainState.create(
                apply_fn=m.apply, params=params, tx=optax.adam(1e-3)
            )
        )
        step = strategy.compile(build_train_step(loss_fn), state)
        rng = np.random.default_rng(0)
        batch = strategy.shard_batch(
            {
                "image": rng.normal(size=(8, 32, 32, 3)).astype(np.float32),
                "label": rng.integers(10, size=(8,)).astype(np.int32),
            }
        )
        losses = []
        for _ in range(6):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses  # it learns the batch


class TestResNet:
    def test_s2d_stem_exactly_matches_conv7(self):
        # the s2d stem's function space contains the 7x7/2 conv: rewriting
        # any 7x7 kernel via s2d_stem_kernel_from_conv7 must reproduce the
        # original conv's output exactly (same arithmetic, relaid out)
        from pytorch_distributed_tpu.models.resnet import (
            s2d_stem_kernel_from_conv7,
            space_to_depth,
        )

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)).astype(np.float32))
        k7 = jnp.asarray(rng.normal(size=(7, 7, 3, 8)).astype(np.float32))

        want = jax.lax.conv_general_dilated(
            x, k7, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        got = jax.lax.conv_general_dilated(
            space_to_depth(x, 2), jnp.asarray(s2d_stem_kernel_from_conv7(k7)),
            window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        assert want.shape == got.shape == (2, 16, 16, 8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_s2d_stem_resnet_runs_and_downsamples_like_imagenet(self):
        a = ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_classes=5,
                   width=8, stem="imagenet")
        b = ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_classes=5,
                   width=8, stem="s2d")
        x = jnp.zeros((2, 64, 64, 3))
        va = a.init(jax.random.key(0), x, train=False)
        vb = b.init(jax.random.key(0), x, train=False)
        oa = a.apply(va, x, train=False)
        ob = b.apply(vb, x, train=False)
        assert oa.shape == ob.shape == (2, 5)
        # same downsampling schedule: stem kernel sees the s2d grid
        assert vb["params"]["stem"]["kernel"].shape == (4, 4, 12, 8)

    @pytest.mark.slow
    def test_resnet18_param_count(self):
        # torch resnet18 (CIFAR stem, 10 classes) ~= 11.17M
        model = ResNet18(num_classes=10, stem="cifar")
        v = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
        n = count_params(v["params"])
        assert 11.0e6 < n < 11.4e6, n

    @pytest.mark.slow
    def test_resnet50_param_count(self):
        # torch resnet50 (1000 classes) ~= 25.56M
        model = ResNet50()
        v = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)), train=False)
        n = count_params(v["params"])
        assert 25.3e6 < n < 25.8e6, n

    @pytest.mark.slow
    def test_resnet_family_param_counts(self):
        # torchvision: resnet34 21.80M, resnet101 44.55M, resnet152 60.19M
        from pytorch_distributed_tpu.models import (
            ResNet34, ResNet101, ResNet152,
        )

        for ctor, lo, hi in [
            (ResNet34, 21.5e6, 22.1e6),
            (ResNet101, 44.2e6, 44.9e6),
            (ResNet152, 59.8e6, 60.6e6),
        ]:
            model = ctor()
            v = model.init(
                jax.random.key(0), jnp.zeros((1, 64, 64, 3)), train=False
            )
            n = count_params(v["params"])
            assert lo < n < hi, (ctor.__name__, n)
            logits = model.apply(
                v, jnp.zeros((2, 64, 64, 3)), train=False
            )
            assert logits.shape == (2, 1000)

    @pytest.mark.slow
    def test_forward_shapes_and_output_dtype(self):
        model = ResNet18(num_classes=10, stem="cifar")
        v = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
        x = jnp.zeros((4, 32, 32, 3))
        logits = model.apply(v, x, train=False)
        assert logits.shape == (4, 10)
        assert logits.dtype == jnp.float32  # policy output dtype

    @pytest.mark.slow
    def test_params_f32_compute_bf16(self):
        model = ResNet18(num_classes=10, stem="cifar")
        v = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
        kernels = jax.tree_util.tree_leaves(v["params"])
        assert all(k.dtype == jnp.float32 for k in kernels)

    @pytest.mark.slow  # r5 profile refit: autocast policy semantics pinned in test_runtime
    def test_autocast_full_precision(self):
        with ptd.autocast(enabled=False):
            model = ResNet18(num_classes=10, stem="cifar")
            v = model.init(
                jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False
            )
            logits = model.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
        assert logits.dtype == jnp.float32

    def test_train_mode_mutates_stats(self):
        model = ResNet(
            stage_sizes=[1], block_cls=BasicBlock, num_classes=4, width=8,
            stem="cifar",
        )
        v = model.init(jax.random.key(0), jnp.zeros((1, 8, 8, 3)), train=False)
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 3))
        _, mutated = model.apply(
            v, x, train=True, mutable=["batch_stats"]
        )
        before = jax.tree_util.tree_leaves(v["batch_stats"])
        after = jax.tree_util.tree_leaves(mutated["batch_stats"])
        assert any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(before, after)
        )

    def test_bad_stem_raises(self):
        with pytest.raises(ValueError, match="stem"):
            ResNet18(stem="nope").init(
                jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False
            )

    @pytest.mark.slow
    def test_imagenet_stem_downsamples(self):
        model = ResNet50(num_classes=10)
        v = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)), train=False)
        logits = model.apply(v, jnp.zeros((2, 64, 64, 3)), train=False)
        assert logits.shape == (2, 10)
