"""Staleness/ISA semantics of the build-on-first-use native builder.

The .so travels in three ways — runtime-built here (sidecar recorded),
`make -C native` ahead-of-time (no sidecar, possibly read-only image),
or a container migrated to a different-ISA host — and each has a
distinct correct behavior (utils/native_build.py docstrings).
"""

import os
import shutil

from pytorch_distributed_tpu.utils.native_build import (
    _arch_flags,
    build_native_library,
)


def _setup(tmp_path):
    src = tmp_path / "toy.cpp"
    src.write_text('extern "C" int toy() { return 42; }\n')
    return str(src), str(tmp_path / "libtoy.so")


def test_runtime_build_writes_sidecar_and_caches(tmp_path):
    src, so = _setup(tmp_path)
    p = build_native_library(src, so)
    assert os.path.exists(p)
    want = open(p + ".flags").read()
    assert "-O3" in want
    mt = os.path.getmtime(p)
    build_native_library(src, so)  # same flags: cached
    assert os.path.getmtime(p) == mt


def test_fresh_sidecarless_so_is_trusted(tmp_path):
    """make -C native output (no sidecar, maybe read-only dir) must NOT
    be rebuilt while fresh — the ahead-of-time path this module
    complements."""
    src, so = _setup(tmp_path)
    build_native_library(src, so)
    os.remove(so + ".flags")
    mt = os.path.getmtime(so)
    build_native_library(src, so)
    assert os.path.getmtime(so) == mt
    assert not os.path.exists(so + ".flags")  # still make-style


def test_flag_mismatch_rebuilds(tmp_path):
    """A sidecar recording different flags (container migrated to a
    different-ISA host) forces a rebuild instead of a SIGILL."""
    src, so = _setup(tmp_path)
    build_native_library(src, so)
    open(so + ".flags", "w").write("g++ -O3 -march=from-another-host")
    mt = os.path.getmtime(so)
    build_native_library(src, so)
    assert os.path.getmtime(so) > mt
    assert "from-another-host" not in open(so + ".flags").read()


def test_stale_source_rebuilds(tmp_path):
    src, so = _setup(tmp_path)
    build_native_library(src, so)
    os.utime(src, (os.path.getmtime(so) + 10,) * 2)
    mt = os.path.getmtime(so)
    build_native_library(src, so)
    assert os.path.getmtime(so) >= mt  # rebuilt (mtime advanced or equal
    # within fs resolution); the real assert is that it didn't raise
    assert open(so + ".flags").read()


def test_arch_flags_all_or_nothing():
    """Either no -march (unknown/partial host) or the full v3 set gated
    on the complete cpuinfo feature list — partial gates SIGILL."""
    flags = _arch_flags()
    assert flags in ([], ["-march=x86-64-v3"])
