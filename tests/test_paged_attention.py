"""Paged-attention decode (ops/paged_attention + engine wiring, round 12).

Contracts under test, on top of test_serve_paged.py's parity suite:

* the op: the ``gather`` impl is BIT-IDENTICAL per dtype to
  ``gather_pages``-style dense materialization + the unchanged
  ``dot_product_attention`` (the zero-tail argument made executable);
  the ``stream`` (lax.scan online-softmax) reference and the Pallas
  ``kernel`` (interpret off-TPU) match the dense path to explicit
  per-dtype tolerances — online softmax reorders reductions, so their
  parity is last-ulp-class, pinned, not assumed;
* null-page frame 0 is unobservable (garbage in frame 0 changes no
  output), ragged lengths (including 0) and the ``[W > 1]`` verify
  block's internal causal order mask inside the op, GQA maps kv heads
  flash-style, sliding windows compose;
* per-page writes land exactly where the page table says, and dropped
  rows (keep=False) never touch the pool — the scatter_kv invariant
  carried to the new write path;
* the engine: dense-mode vs paged-mode A/B runs emit identical
  streams while the paged run's analytic HBM bytes shrink; slot reuse
  across length buckets recompiles AT MOST once per bucket (a second
  wave of the same shape compiles nothing); CoW-shared pages attend
  correctly while BOTH sharers are live mid-decode; the ``[k+1]``
  paged verify stays bit-identical to solo generate; precompiling
  buckets is bitwise state-neutral; ``auto_page_size`` warns once on
  the odd-max_len 1-token-page degeneration.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.generation import generate
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.ops.attention import dot_product_attention
from pytorch_distributed_tpu.ops.paged_attention import (
    PagedKVQuant,
    paged_attention,
    paged_write,
    set_paged_attention_impl,
)
from pytorch_distributed_tpu.runtime import tracing
from pytorch_distributed_tpu.serve import (
    EngineConfig,
    Request,
    RequestStatus,
    ServeEngine,
    SpecConfig,
    auto_page_size,
)
from pytorch_distributed_tpu.serve.kv_slots import (
    reset_page_size_warnings,
)

pytestmark = pytest.mark.serve

IMPLS = ("gather", "stream", "kernel")


def _pool_case(rng, *, B=4, W=1, Hq=4, Hkv=2, D=16, ps=8, n=4,
               dtype=jnp.float32, max_length=None):
    """A random pool + tables + ragged lengths; frame 0 stays zero."""
    P1 = B * n + 1
    q = jnp.asarray(rng.standard_normal((B, W, Hq, D)), dtype)
    kp = jnp.asarray(rng.standard_normal((P1, ps, Hkv, D)), dtype)
    vp = jnp.asarray(rng.standard_normal((P1, ps, Hkv, D)), dtype)
    kp = kp.at[0].set(0.0)
    vp = vp.at[0].set(0.0)
    tables = jnp.asarray(
        np.arange(1, B * n + 1).reshape(B, n), jnp.int32
    )
    hi = max_length if max_length is not None else n * ps - W
    lengths = jnp.asarray(
        rng.integers(0, hi + 1, size=B), jnp.int32
    )
    return q, kp, vp, tables, lengths


def _dense_ref(q, kp, vp, tables, lengths, **kw):
    """The pre-paged path: materialize the tables densely, run the
    unchanged dot_product_attention with per-row offsets."""
    B, n = tables.shape
    ps = kp.shape[1]
    kd = jnp.take(kp, tables.reshape(-1), axis=0).reshape(
        B, n * ps, kp.shape[2], kp.shape[3]
    )
    vd = jnp.take(vp, tables.reshape(-1), axis=0).reshape(
        B, n * ps, vp.shape[2], vp.shape[3]
    )
    return dot_product_attention(
        q, kd, vd, causal=True, q_offset=lengths, **kw
    )


class TestPagedAttentionOp:
    def test_gather_impl_bit_exact_per_dtype(self):
        """The engine-default CPU impl: bitwise the dense path, both
        dtypes — this is what keeps solo-generate parity pinned."""
        for dtype in (jnp.float32, jnp.bfloat16):
            rng = np.random.default_rng(0)
            q, kp, vp, tables, lengths = _pool_case(
                rng, W=3, dtype=dtype
            )
            ref = _dense_ref(q, kp, vp, tables, lengths)
            out = paged_attention(
                q, kp, vp, page_tables=tables, lengths=lengths,
                impl="gather",
            )
            assert out.dtype == ref.dtype
            assert np.array_equal(
                np.asarray(out, np.float32), np.asarray(ref, np.float32)
            ), str(dtype)

    @pytest.mark.parametrize("impl", ["stream", "kernel"])
    def test_streaming_impls_match_dense_per_dtype(self, impl):
        """Online softmax reassociates the reductions: parity with the
        dense path is pinned per dtype at explicit tolerances (f32
        last-ulp-class; bf16 dominated by its 8-bit mantissa)."""
        for dtype, tol in ((jnp.float32, 3e-6), (jnp.bfloat16, 3e-2)):
            rng = np.random.default_rng(1)
            q, kp, vp, tables, lengths = _pool_case(
                rng, W=2, dtype=dtype
            )
            ref = np.asarray(
                _dense_ref(q, kp, vp, tables, lengths), np.float32
            )
            out = np.asarray(paged_attention(
                q, kp, vp, page_tables=tables, lengths=lengths,
                impl=impl,
            ), np.float32)
            assert np.max(np.abs(out - ref)) <= tol, str(dtype)

    @pytest.mark.parametrize("impl", IMPLS)
    def test_null_page_contents_unobservable(self, impl):
        """Unused table entries hold frame 0; poisoning frame 0 with
        huge finite garbage must change nothing the mask admits."""
        rng = np.random.default_rng(2)
        q, kp, vp, tables, lengths = _pool_case(rng, max_length=10)
        # tail table entries -> null page (lengths <= 10 < 2 pages)
        tables = tables.at[:, 2:].set(0)
        clean = paged_attention(
            q, kp, vp, page_tables=tables, lengths=lengths, impl=impl
        )
        dirty = paged_attention(
            q, kp.at[0].set(1e6), vp.at[0].set(-1e6),
            page_tables=tables, lengths=lengths, impl=impl,
        )
        assert np.array_equal(np.asarray(clean), np.asarray(dirty))

    @pytest.mark.parametrize("impl", IMPLS)
    def test_verify_block_causal_order_and_zero_length(self, impl):
        """W = k+1 queries: query j sees exactly positions <= len+j
        (the fused-verify contract), including rows of length 0."""
        rng = np.random.default_rng(3)
        q, kp, vp, tables, _ = _pool_case(rng, W=4, Hq=2, Hkv=1)
        lengths = jnp.asarray([0, 3, 8, 17], jnp.int32)
        ref = np.asarray(
            _dense_ref(q, kp, vp, tables, lengths), np.float32
        )
        out = np.asarray(paged_attention(
            q, kp, vp, page_tables=tables, lengths=lengths, impl=impl
        ), np.float32)
        tol = 0.0 if impl == "gather" else 3e-6
        assert np.max(np.abs(out - ref)) <= tol

    @pytest.mark.parametrize("impl", IMPLS)
    def test_gqa_and_window(self, impl):
        rng = np.random.default_rng(4)
        q, kp, vp, tables, lengths = _pool_case(rng, Hq=8, Hkv=2)
        ref = np.asarray(_dense_ref(
            q, kp, vp, tables, lengths, window=5
        ), np.float32)
        out = np.asarray(paged_attention(
            q, kp, vp, page_tables=tables, lengths=lengths, window=5,
            impl=impl,
        ), np.float32)
        tol = 0.0 if impl == "gather" else 3e-6
        assert np.max(np.abs(out - ref)) <= tol

    @pytest.mark.parametrize("impl", IMPLS)
    def test_int8_scale_pools(self, impl):
        """Quantized pools ride as payload+scale pairs; the dequant is
        decode_cache's exact formula, so the gather impl is bitwise the
        dense int8 path (the kernel impl falls back to gather — it
        takes fp pools only, by contract)."""
        rng = np.random.default_rng(5)
        q, kp, vp, tables, lengths = _pool_case(rng)
        k8 = jnp.asarray(
            rng.integers(-127, 128, size=kp.shape), jnp.int8
        )
        v8 = jnp.asarray(
            rng.integers(-127, 128, size=vp.shape), jnp.int8
        )
        ks = jnp.asarray(
            rng.uniform(0.01, 0.1, size=kp.shape[:3] + (1,)),
            jnp.float32,
        )
        vs = jnp.asarray(
            rng.uniform(0.01, 0.1, size=vp.shape[:3] + (1,)),
            jnp.float32,
        )
        kd = (k8.astype(jnp.float32) * ks).astype(jnp.float32)
        vd = (v8.astype(jnp.float32) * vs).astype(jnp.float32)
        ref = np.asarray(
            _dense_ref(q, kd, vd, tables, lengths), np.float32
        )
        out = np.asarray(paged_attention(
            q,
            PagedKVQuant(k8, ks, jnp.float32),
            PagedKVQuant(v8, vs, jnp.float32),
            page_tables=tables, lengths=lengths, impl=impl,
        ), np.float32)
        tol = 0.0 if impl in ("gather", "kernel") else 3e-6
        assert np.max(np.abs(out - ref)) <= tol

    def test_paged_write_placement_and_drop(self):
        rng = np.random.default_rng(6)
        ps, P1 = 4, 9
        pool = jnp.zeros((P1, ps, 2, 3), jnp.float32)
        tables = jnp.asarray(
            np.arange(1, 9).reshape(4, 2), jnp.int32
        )
        new = jnp.asarray(rng.standard_normal((4, 2, 2, 3)), jnp.float32)
        wp = jnp.asarray([0, 3, 30, 6], jnp.int32)
        keep = jnp.asarray([True, True, False, True])
        out = np.asarray(paged_write(pool, new, tables, wp, keep))
        # row 0: positions 0,1 -> frame tables[0,0] slots 0,1
        assert np.array_equal(out[1, 0], np.asarray(new[0, 0]))
        assert np.array_equal(out[1, 1], np.asarray(new[0, 1]))
        # row 1: positions 3,4 straddle the page boundary
        assert np.array_equal(out[3, 3], np.asarray(new[1, 0]))
        assert np.array_equal(out[4, 0], np.asarray(new[1, 1]))
        # row 2 dropped entirely even though its position (30) clamps
        # past its 2-page table — the mid-prefill-row contract (rows
        # beyond the bucket are always keep=False); row 3 lands in its
        # second page; null frame 0 never written
        written = {(1, 0), (1, 1), (3, 3), (4, 0), (8, 2), (8, 3)}
        for f in range(P1):
            for s in range(ps):
                if (f, s) not in written:
                    assert np.abs(out[f, s]).sum() == 0.0, (f, s)

    def test_validation(self):
        rng = np.random.default_rng(7)
        q, kp, vp, tables, lengths = _pool_case(rng)
        with pytest.raises(ValueError, match="kv heads"):
            paged_attention(
                q[:, :, :3], kp, vp, page_tables=tables,
                lengths=lengths,
            )
        with pytest.raises(ValueError, match="page_tables"):
            paged_attention(
                q, kp, vp, page_tables=tables[:2], lengths=lengths
            )
        with pytest.raises(ValueError, match="window"):
            paged_attention(
                q, kp, vp, page_tables=tables, lengths=lengths,
                window=0,
            )
        with pytest.raises(ValueError, match="impl"):
            set_paged_attention_impl("mosaic")


# -- engine wiring ----------------------------------------------------------


@pytest.fixture(scope="module")
def long_ctx():
    """A tiny model whose position table allows a LONG max_len with
    short live lengths — the regime paged attention exists for."""
    cfg = GPT2Config(
        vocab_size=97, n_positions=256, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _solo(model, params, req: Request):
    out = np.asarray(generate(
        model, params, jnp.asarray(req.prompt_ids[None]),
        max_new_tokens=req.max_new_tokens,
        temperature=req.temperature, top_k=req.top_k, top_p=req.top_p,
        rng=jax.random.PRNGKey(req.seed), eos_id=req.eos_id,
    ))[0, req.prompt_len:]
    return [int(x) for x in out]


def _workload(rng, n, p_rng=(3, 9), n_rng=(4, 12)):
    return [
        Request(
            rng.integers(1, 97, size=int(
                rng.integers(p_rng[0], p_rng[1] + 1)
            )).astype(np.int32),
            max_new_tokens=int(rng.integers(n_rng[0], n_rng[1] + 1)),
            temperature=(0.0 if i % 2 else 0.8),
            top_k=(None if i % 2 else 7), seed=i,
        )
        for i in range(n)
    ]


class TestPagedEngine:
    def test_dense_vs_paged_ab_parity_and_bytes(self, long_ctx):
        """Same seeded workload through decode_mode='dense' (the round
        11 gather programs) and 'paged': identical token streams, and
        the paged run's analytic decode HBM bytes/token shrink — the
        gather tax is a recorded fact, removed."""
        model, params = long_ctx
        streams, engines = [], []
        for mode in ("dense", "paged"):
            rng = np.random.default_rng(11)
            engine = ServeEngine(model, params, EngineConfig(
                num_slots=4, max_len=128, prefill_chunk=4, page_size=8,
                decode_mode=mode,
            ))
            hs = [engine.submit(r) for r in _workload(rng, 8)]
            engine.run_until_drained()
            assert all(
                h.status is RequestStatus.COMPLETED for h in hs
            )
            streams.append([h.tokens for h in hs])
            engines.append(engine)
        assert streams[0] == streams[1]
        dense_e, paged_e = engines
        assert dense_e._decode_tokens == paged_e._decode_tokens > 0
        # dense gathers [S, max_len] every tick; paged streams at most
        # the live bucket — live lengths (< 24) sit in 2-4 of 16 pages
        assert paged_e.decode_hbm_bytes < dense_e.decode_hbm_bytes / 3
        assert paged_e.decode_gather_bytes < dense_e.decode_gather_bytes
        assert (
            paged_e.decode_hbm_bytes_per_token
            < dense_e.decode_hbm_bytes_per_token / 3
        )
        # dense mode is exactly one program per kind
        assert dense_e.decode_buckets == {dense_e.pool.max_pages}
        assert dense_e.decode_compiles == 1

    def test_int8_kv_cache_dense_vs_paged_ab_parity(self):
        """kv_cache_quantize='int8' rides the paged path as payload +
        scale pools (PagedKVQuant): the per-page dequant is
        decode_cache's exact formula, so dense-mode and paged-mode
        engines emit identical streams on the same int8 cache."""
        cfg = GPT2Config(
            vocab_size=97, n_positions=96, hidden_size=32,
            num_layers=2, num_heads=2, dropout_rate=0.0,
            kv_cache_quantize="int8",
        )
        model = GPT2LMHead(cfg)
        params = model.init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        streams = []
        for mode in ("dense", "paged"):
            rng = np.random.default_rng(17)
            engine = ServeEngine(model, params, EngineConfig(
                num_slots=2, max_len=64, prefill_chunk=4, page_size=8,
                decode_mode=mode,
            ))
            hs = [engine.submit(r) for r in _workload(rng, 4)]
            engine.run_until_drained()
            assert all(
                h.status is RequestStatus.COMPLETED for h in hs
            )
            streams.append([h.tokens for h in hs])
        assert streams[0] == streams[1]

    def test_slot_reuse_recompiles_at_most_once_per_bucket(
        self, long_ctx
    ):
        """Lengths crossing page-bucket boundaries compile each bucket
        once; a second wave re-occupying the same buckets (slot reuse)
        compiles NOTHING new."""
        model, params = long_ctx
        engine = ServeEngine(model, params, EngineConfig(
            num_slots=2, max_len=128, prefill_chunk=4, page_size=4,
        ))
        rng = np.random.default_rng(12)

        def wave():
            reqs = [
                Request(
                    rng.integers(1, 97, size=5).astype(np.int32),
                    max_new_tokens=20,
                ),
                Request(
                    rng.integers(1, 97, size=9).astype(np.int32),
                    max_new_tokens=30,
                ),
            ]
            hs = [engine.submit(r) for r in reqs]
            engine.run_until_drained()
            assert all(
                h.status is RequestStatus.COMPLETED for h in hs
            )
            for r, h in zip(reqs, hs):
                assert h.tokens == _solo(model, params, r)

        wave()
        # lengths reached ~39 -> buckets {2, 4, 8, 16} of 32 possible
        assert len(engine.decode_buckets) >= 2
        assert engine.decode_compiles == len(engine.decode_buckets)
        compiles = (engine.decode_compiles, engine.prefill_compiles)
        wave()  # slot reuse over the same length profile
        assert (
            engine.decode_compiles, engine.prefill_compiles
        ) == compiles, "slot reuse recompiled an already-built bucket"
        assert all(
            v == 1 for v in engine._decode_bucket_compiles.values()
        )
        assert all(
            v == 1 for v in engine._prefill_bucket_compiles.values()
        )

    def test_cow_shared_pages_attend_correctly_mid_share(
        self, long_ctx
    ):
        """Two live requests decode over the SAME refcounted prompt
        pages simultaneously — the paged stream reads shared (read-only)
        frames for both rows, streams stay solo-exact, and the shared
        frames' bytes never change while both attend them."""
        from tests.test_serve_paged import _page_bytes

        model, params = long_ctx
        rng = np.random.default_rng(13)
        sys_p = rng.integers(1, 97, size=16).astype(np.int32)

        def mk(new, **kw):
            return Request(
                np.concatenate([
                    sys_p, rng.integers(1, 97, size=3).astype(np.int32)
                ]),
                max_new_tokens=new, **kw,
            )

        engine = ServeEngine(model, params, EngineConfig(
            num_slots=3, max_len=64, prefill_chunk=4, page_size=4,
        ))
        seed_req = mk(2)
        hs = engine.submit(seed_req)
        engine.run_until_drained()  # registers the 4-page system prefix
        assert hs.status is RequestStatus.COMPLETED
        r1, r2 = mk(12), mk(10, temperature=0.7, top_p=0.9, seed=5)
        h1, h2 = engine.submit(r1), engine.submit(r2)
        for _ in range(3):
            engine.step()
        # both rows live and decoding over the shared frames
        assert h1.status is RequestStatus.DECODING
        assert h2.status is RequestStatus.DECODING
        shared = list(
            engine.scheduler.by_slot[h1.slot]._lease.page_row[:4]
        )
        assert shared == list(
            engine.scheduler.by_slot[h2.slot]._lease.page_row[:4]
        )
        before = _page_bytes(engine.pool, shared)
        engine.run_until_drained()
        assert h1.tokens == _solo(model, params, r1)
        assert h2.tokens == _solo(model, params, r2)
        assert _page_bytes(engine.pool, shared) == before
        engine.pool.check_consistency()

    def test_spec_paged_verify_long_context_parity(self, long_ctx):
        """The [k+1] verify rides the paged primitive: greedy spec
        streams stay bit-identical to solo generate at a long max_len
        with multiple buckets occupied."""
        model, params = long_ctx
        dcfg = GPT2Config(
            vocab_size=97, n_positions=256, hidden_size=16,
            num_layers=1, num_heads=2, dropout_rate=0.0,
        )
        dmodel = GPT2LMHead(dcfg)
        dparams = dmodel.init(
            jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        engine = ServeEngine(
            model, params,
            EngineConfig(num_slots=2, max_len=128, prefill_chunk=4,
                         page_size=4),
            spec=SpecConfig(dmodel, dparams, num_draft_tokens=3),
        )
        rng = np.random.default_rng(14)
        reqs = [
            Request(rng.integers(1, 97, size=6).astype(np.int32),
                    max_new_tokens=24),
            Request(rng.integers(1, 97, size=10).astype(np.int32),
                    max_new_tokens=18),
        ]
        hs = [engine.submit(r) for r in reqs]
        engine.run_until_drained()
        for r, h in zip(reqs, hs):
            assert h.status is RequestStatus.COMPLETED
            assert h.tokens == _solo(model, params, r)
        assert engine.spec_verifies > 0
        assert len(engine.decode_buckets) >= 2
        assert engine.decode_compiles == len(engine.decode_buckets)
        engine.pool.check_consistency()
        engine.draft_pool.check_consistency()

    def test_precompile_buckets_is_state_neutral(self, long_ctx):
        """precompile_decode_buckets compiles every bucket via no-op
        dispatches: device rows and the pool stay bitwise intact."""
        model, params = long_ctx
        engine = ServeEngine(model, params, EngineConfig(
            num_slots=2, max_len=64, prefill_chunk=4, page_size=8,
        ))
        rng = np.random.default_rng(15)
        r = Request(rng.integers(1, 97, size=5).astype(np.int32),
                    max_new_tokens=4)
        h = engine.submit(r)
        engine.run_until_drained()
        before = (
            np.asarray(engine._toks).copy(),
            np.asarray(engine._lengths).copy(),
            np.asarray(engine._keys).copy(),
            [np.asarray(x).copy() for x in
             jax.tree_util.tree_leaves(engine.pool.cache)
             if x.ndim >= 2],
        )
        engine.precompile_decode_buckets()
        assert engine.decode_compiles == len(engine._buckets)
        assert np.array_equal(before[0], np.asarray(engine._toks))
        assert np.array_equal(before[1], np.asarray(engine._lengths))
        assert np.array_equal(before[2], np.asarray(engine._keys))
        after = [
            np.asarray(x) for x in
            jax.tree_util.tree_leaves(engine.pool.cache) if x.ndim >= 2
        ]
        for a, b in zip(before[3], after):
            assert np.array_equal(a, b)
        # ...and a request decoded afterwards is still solo-exact
        r2 = Request(rng.integers(1, 97, size=4).astype(np.int32),
                     max_new_tokens=5)
        h2 = engine.submit(r2)
        engine.run_until_drained()
        assert h2.tokens == _solo(model, params, r2)
        assert h.status is RequestStatus.COMPLETED

    def test_counters_ride_armed_tracing_only(self, long_ctx):
        """serve.decode_gather_bytes / decode_hbm_bytes_per_token land
        on an armed tracer's counter track and in snapshot gauges."""
        model, params = long_ctx
        rng = np.random.default_rng(16)
        with tracing.enabled() as t:
            engine = ServeEngine(model, params, EngineConfig(
                num_slots=2, max_len=64, prefill_chunk=4, page_size=8,
                telemetry_every=2,
            ))
            hs = [engine.submit(r) for r in _workload(rng, 3)]
            engine.run_until_drained()
        assert all(h.status is RequestStatus.COMPLETED for h in hs)
        names = {
            e["name"] for e in t._events if e.get("ph") == "C"
        }
        assert "serve.decode_gather_bytes" in names
        assert "serve.decode_hbm_bytes_per_token" in names
        assert engine.decode_hbm_bytes_per_token > 0
        # the default CPU impl ("gather") still pays a bucketed dense
        # slab; the counter records it honestly
        assert engine.decode_gather_bytes > 0

    def test_auto_page_size_warns_once_on_odd_max_len(self, caplog):
        reset_page_size_warnings()
        ns = logging.getLogger("pytorch_distributed_tpu")
        ns.addHandler(caplog.handler)
        try:
            with caplog.at_level(
                logging.WARNING, logger="pytorch_distributed_tpu"
            ):
                assert auto_page_size(63) == 1
                assert auto_page_size(63) == 1  # deduped
                assert auto_page_size(64) == 32  # healthy: silent
        finally:
            ns.removeHandler(caplog.handler)
        warns = [
            r for r in caplog.records
            if "1-token pages" in r.getMessage()
        ]
        assert len(warns) == 1
        reset_page_size_warnings()
