"""Chunked-vocab cross-entropy: exact parity with the full-logits loss."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_tpu.ops.lm_loss import (
    causal_lm_chunked_loss,
    chunked_softmax_cross_entropy,
)


def _full_ce(h, emb, labels, label_smoothing=0.0):
    logits = (h @ emb.T).astype(jnp.float32)
    if label_smoothing:
        v = logits.shape[-1]
        oh = jax.nn.one_hot(labels, v)
        oh = oh * (1.0 - label_smoothing) + label_smoothing / v
        return jnp.mean(optax.softmax_cross_entropy(logits, oh))
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    )


@pytest.mark.parametrize("chunk", [7, 64, 100, 4096])
def test_matches_full_loss(chunk):
    # vocab 100: chunk 7 exercises the non-dividing masked-pad path,
    # 100 the exact fit, 4096 the single-chunk clamp
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(33, 16)).astype(np.float32))
    emb = jnp.asarray(rng.normal(size=(100, 16)).astype(np.float32))
    labels = jnp.asarray(rng.integers(100, size=(33,)).astype(np.int32))
    want = float(_full_ce(h, emb, labels))
    got = float(
        chunked_softmax_cross_entropy(h, emb, labels, chunk_size=chunk)
    )
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_matches_full_loss_with_label_smoothing():
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.normal(size=(20, 8)).astype(np.float32))
    emb = jnp.asarray(rng.normal(size=(50, 8)).astype(np.float32))
    labels = jnp.asarray(rng.integers(50, size=(20,)).astype(np.int32))
    want = float(_full_ce(h, emb, labels, label_smoothing=0.1))
    got = float(
        chunked_softmax_cross_entropy(
            h, emb, labels, chunk_size=16, label_smoothing=0.1
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_gradients_match_full_loss():
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.normal(size=(12, 8)).astype(np.float32))
    emb = jnp.asarray(rng.normal(size=(40, 8)).astype(np.float32))
    labels = jnp.asarray(rng.integers(40, size=(12,)).astype(np.int32))
    gw = jax.grad(lambda h, e: _full_ce(h, e, labels), argnums=(0, 1))
    gc = jax.grad(
        lambda h, e: chunked_softmax_cross_entropy(
            h, e, labels, chunk_size=16
        ),
        argnums=(0, 1),
    )
    (dh_w, de_w), (dh_c, de_c) = gw(h, emb), gc(h, emb)
    np.testing.assert_allclose(np.asarray(dh_c), np.asarray(dh_w), rtol=2e-4,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(de_c), np.asarray(de_w), rtol=2e-4,
                               atol=1e-6)


@pytest.mark.slow
def test_gpt2_chunked_loss_fn_matches_full():
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    cfg = GPT2Config.tiny()
    model = GPT2LMHead(cfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(cfg.vocab_size, size=(2, 16)).astype(np.int32))
    params = model.init(jax.random.key(0), ids)["params"]
    full = causal_lm_loss_fn(model)
    chunked = causal_lm_loss_fn(model, vocab_chunk_size=37)
    key = jax.random.key(1)
    lf, _ = full(params, None, {"input_ids": ids}, key)
    lc, _ = chunked(params, None, {"input_ids": ids}, key)
    # both run the head matmul in bf16 with f32 accumulation; the chunked
    # sum order differs, so tolerance is bf16-matmul-level
    np.testing.assert_allclose(float(lc), float(lf), rtol=2e-3)


@pytest.mark.slow
def test_llama_chunked_loss_fn_matches_full():
    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(4)
    ids = jnp.asarray(rng.integers(cfg.vocab_size, size=(2, 12)).astype(np.int32))
    params = model.init(jax.random.key(0), ids)["params"]
    full = causal_lm_loss_fn(model)
    chunked = causal_lm_loss_fn(model, vocab_chunk_size=128)
    key = jax.random.key(1)
    lf, _ = full(params, None, {"input_ids": ids}, key)
    lc, _ = chunked(params, None, {"input_ids": ids}, key)
    np.testing.assert_allclose(float(lc), float(lf), rtol=2e-3)


def test_causal_shift_matches_manual():
    rng = np.random.default_rng(5)
    b, s, d, v = 2, 9, 8, 30
    hidden = jnp.asarray(rng.normal(size=(b, s, d)).astype(np.float32))
    emb = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
    ids = jnp.asarray(rng.integers(v, size=(b, s)).astype(np.int32))
    got = float(causal_lm_chunked_loss(hidden, emb, ids, chunk_size=8))
    want = float(
        _full_ce(
            hidden[:, :-1].reshape(-1, d), emb, ids[:, 1:].reshape(-1)
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.slow  # r5 profile refit: packed_eval_and_chunked_equivalence + gradients_match_full_loss stay fast
def test_packed_loss_equals_per_document_losses():
    """A packed row's masked loss must equal the token-weighted mean of
    each document trained alone — attention isolation + positions reset +
    boundary masking all have to hold simultaneously."""
    import numpy as np

    from pytorch_distributed_tpu.data import pack_documents
    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    docs = [
        list(rng.integers(1, cfg.vocab_size, size=n)) for n in (12, 20)
    ]
    packed = pack_documents(docs, 32)
    assert packed["input_ids"].shape[0] == 1  # both fit one row
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    loss_fn = causal_lm_loss_fn(model)
    packed_loss, _ = loss_fn(
        params, None,
        {
            "input_ids": jnp.asarray(packed["input_ids"]),
            "segment_ids": jnp.asarray(packed["segment_ids"]),
            "positions": jnp.asarray(packed["positions"]),
        },
        jax.random.key(1),
    )
    # reference: each doc alone (unpacked), token-weighted
    tot, n_tok = 0.0, 0
    for doc in docs:
        ids = jnp.asarray(np.asarray(doc, np.int32)[None, :])
        l, _ = loss_fn(params, None, {"input_ids": ids}, jax.random.key(1))
        tot += float(l) * (len(doc) - 1)
        n_tok += len(doc) - 1
    np.testing.assert_allclose(
        float(packed_loss), tot / n_tok, rtol=2e-5
    )


def test_packed_eval_and_chunked_equivalence():
    """Packed eval matches packed train loss, and the chunked-vocab path
    (the 8B memory configuration) reproduces the full-logits packed loss
    exactly."""
    import numpy as np

    from pytorch_distributed_tpu.data import pack_documents
    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )
    from pytorch_distributed_tpu.train import (
        causal_lm_eval_step,
        causal_lm_loss_fn,
    )

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(1)
    packed = pack_documents(
        [list(rng.integers(1, cfg.vocab_size, size=n)) for n in (10, 15)],
        32,
    )
    batch = {
        "input_ids": jnp.asarray(packed["input_ids"]),
        "segment_ids": jnp.asarray(packed["segment_ids"]),
        "positions": jnp.asarray(packed["positions"]),
    }
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]

    train_loss, _ = causal_lm_loss_fn(model)(
        params, None, batch, jax.random.key(0)
    )
    import types

    ev = causal_lm_eval_step(model)(
        types.SimpleNamespace(params=params), batch
    )
    np.testing.assert_allclose(
        float(ev["loss"]), float(train_loss), rtol=1e-5
    )
    # chunked-vocab path handles packed batches too (the real 8B config):
    # must equal the full-logits packed loss to f32 numerics
    chunked_loss, _ = causal_lm_loss_fn(model, vocab_chunk_size=64)(
        params, None, batch, jax.random.key(0)
    )
    # rtol spans XLA versions: chunking changes the logsumexp reduction
    # order, and this container's XLA:CPU lands ~8e-5 relative off the
    # full-logits path (still f32-reduction noise, not a logic bug)
    np.testing.assert_allclose(
        float(chunked_loss), float(train_loss), rtol=2e-4
    )
    ev_c = causal_lm_eval_step(model, vocab_chunk_size=64)(
        types.SimpleNamespace(params=params), batch
    )
    np.testing.assert_allclose(  # same reduction-order allowance as above
        float(ev_c["loss"]), float(train_loss), rtol=2e-4
    )


@pytest.mark.slow  # r5 profile refit: the llama packed==per-document pin stays fast; same semantics
def test_gpt2_packed_loss_equals_per_document_losses():
    """Same packed ≡ per-document invariant for GPT-2 (learned positions
    must reset per document via the positions table)."""
    import dataclasses

    from pytorch_distributed_tpu.data import pack_documents
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    # dropout off: packed and unpacked runs draw different mask shapes
    # from the same key, which is noise, not a packing discrepancy
    cfg = dataclasses.replace(GPT2Config.tiny(), dropout_rate=0.0)
    model = GPT2LMHead(cfg)
    rng = np.random.default_rng(2)
    docs = [
        list(rng.integers(1, cfg.vocab_size, size=n)) for n in (14, 17)
    ]
    packed = pack_documents(docs, 32)
    assert packed["input_ids"].shape[0] == 1
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    loss_fn = causal_lm_loss_fn(model)
    packed_loss, _ = loss_fn(
        params, None,
        {
            "input_ids": jnp.asarray(packed["input_ids"]),
            "segment_ids": jnp.asarray(packed["segment_ids"]),
            "positions": jnp.asarray(packed["positions"]),
        },
        jax.random.key(1),
    )
    tot, n_tok = 0.0, 0
    for doc in docs:
        ids = jnp.asarray(np.asarray(doc, np.int32)[None, :])
        l, _ = loss_fn(params, None, {"input_ids": ids}, jax.random.key(1))
        tot += float(l) * (len(doc) - 1)
        n_tok += len(doc) - 1
    np.testing.assert_allclose(float(packed_loss), tot / n_tok, rtol=2e-5)


def test_lm_projection_refuses_untied_embed_fallback():
    """ADVICE r5: the bare-'embed' tied fallback must not silently
    produce tied-embedding logits for untied models — refuse when a
    head-like leaf exists (NeoX's embed_out) or the tie flag says no."""
    from pytorch_distributed_tpu.train.losses import _lm_projection_weight

    emb = np.zeros((8, 4), np.float32)
    tied = {"embed": {"embedding": emb}}
    w, axis = _lm_projection_weight(tied, tied=True)
    assert w is emb and axis == 0
    # unknown tie flag, no competing head: the fallback stays usable
    w, axis = _lm_projection_weight(tied)
    assert w is emb and axis == 0
    # NeoX's embed_out IS a known untied head (Dense kernel [D, V]) —
    # resolved, not refused
    neoxish = {"embed": {"embedding": emb},
               "embed_out": {"kernel": np.zeros((4, 8), np.float32)}}
    w, axis = _lm_projection_weight(neoxish)
    assert w is neoxish["embed_out"]["kernel"] and axis == 1
    # an UNKNOWN head-like leaf still refuses the embed fallback...
    headish = {"embed": {"embedding": emb},
               "head": {"kernel": np.zeros((4, 8), np.float32)}}
    with pytest.raises(ValueError, match="head-like"):
        _lm_projection_weight(headish)
    # ...but an explicit tied=True is authoritative: an auxiliary head
    # leaf (e.g. a finetuning classifier) must not block the projection
    w, axis = _lm_projection_weight(headish, tied=True)
    assert w is emb and axis == 0
    # explicit untied flag: refuse even without a competing leaf
    with pytest.raises(ValueError, match="tie_word_embeddings=False"):
        _lm_projection_weight(tied, tied=False)
    # an untied model WITH its lm_head never hits the gate
    w, axis = _lm_projection_weight(
        {"embed": {"embedding": emb},
         "lm_head": {"kernel": np.zeros((4, 8), np.float32)}},
        tied=False,
    )
    assert axis == 1
