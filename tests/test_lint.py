"""ptdlint: the analyzer is itself tested, not trusted.

Three layers, all tier-1 fast (no jax needed except the one
MetricsWriter protocol check, which rides the already-imported runtime):

* fixtures corpus — every rule fires on its known-bad snippet at
  exactly the ``# expect:``-marked lines and stays silent on the
  known-good twin (tests/lint_fixtures/);
* the real tree — the default sweep is clean against the baseline, the
  lockstep rule passes runtime/hostring.py + parallel/ddp.py as-is and
  catches a rank-guarded collective injected into a copy;
* the framework — suppression comments, shrink-only baseline,
  content-addressed matching, CLI exit codes / --json / metrics record,
  and the faults-registry runtime warning the static rule pairs with.
"""

import contextlib
import json
import logging
import os
import re
import shutil
import subprocess
import sys

import pytest

from pytorch_distributed_tpu.analysis import (
    Analyzer,
    Baseline,
    BaselineEntry,
    Finding,
    default_rules,
)
from pytorch_distributed_tpu.analysis.core import ParsedModule
from pytorch_distributed_tpu.analysis.rules import (
    ALL_RULES,
    DonationAfterUse,
    EagerScatterHotPath,
    FaultSiteRegistry,
    LockstepCollectives,
    PrngKeyReuse,
)

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")
CLI = os.path.join(ROOT, "scripts", "ptd_lint.py")

RULE_IDS = tuple(cls.rule_id for cls in ALL_RULES)


@contextlib.contextmanager
def ptd_caplog(caplog, level="WARNING"):
    """Route the repo's namespace logger (propagate=False, own handler)
    into caplog, which only listens on the root logger."""
    ns = logging.getLogger("pytorch_distributed_tpu")
    ns.addHandler(caplog.handler)
    try:
        with caplog.at_level(level, logger="pytorch_distributed_tpu"):
            yield caplog
    finally:
        ns.removeHandler(caplog.handler)


def lint_paths(paths, root=ROOT, rules=None):
    return Analyzer(root, rules or default_rules()).run(paths)


def lint_source(source, relpath="pytorch_distributed_tpu/mod.py",
                rules=None):
    module = ParsedModule("/" + relpath, relpath, source)
    out = []
    for rule in rules or default_rules():
        if rule.applies_to(module):
            out.extend(
                f for f in rule.check(module)
                if not module.is_suppressed(f)
            )
    return out


def expected_lines(path):
    """The ``# expect: PTD00N`` markers baked into a bad fixture."""
    out = {}
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            m = re.search(r"#\s*expect:\s*(PTD\d+)", line)
            if m:
                out.setdefault(m.group(1), set()).add(i)
    return out


def _fixture_pairs():
    pairs = []
    for dirpath, _, names in os.walk(FIXTURES):
        for name in sorted(names):
            if name.endswith("_bad.py"):
                bad = os.path.join(dirpath, name)
                good = bad.replace("_bad.py", "_good.py")
                pairs.append((bad, good))
    return pairs


class TestFixturesCorpus:
    def test_corpus_covers_every_rule(self):
        covered = set()
        for bad, _ in _fixture_pairs():
            covered.update(expected_lines(bad))
        assert covered == set(RULE_IDS)

    @pytest.mark.parametrize(
        "bad,good", _fixture_pairs(),
        ids=[os.path.basename(b) for b, _ in _fixture_pairs()],
    )
    def test_bad_fires_good_silent(self, bad, good):
        expect = expected_lines(bad)
        assert expect, f"{bad} carries no # expect markers"
        found = lint_paths([bad])
        got = {}
        for f in found:
            got.setdefault(f.rule_id, set()).add(f.line)
        # exactly the marked lines — no misses, no extra noise
        assert got == expect, (
            f"{os.path.basename(bad)}: expected {expect}, got {got}"
        )
        silent = lint_paths([good])
        assert silent == [], (
            f"{os.path.basename(good)} must lint clean, got "
            f"{[(f.rule_id, f.line) for f in silent]}"
        )


class TestRealTree:
    def test_default_sweep_clean_against_baseline(self):
        """The acceptance gate, in-process: zero non-baselined findings
        over the package + scripts + bench + tests, zero stale baseline
        entries."""
        findings = Analyzer(
            ROOT, default_rules(), exclude=("tests/lint_fixtures",)
        ).run(["pytorch_distributed_tpu", "scripts", "bench.py", "tests"])
        # the analyzer itself doesn't apply the baseline; mirror the CLI
        new, grandfathered, stale = Baseline.load(
            os.path.join(ROOT, "ptdlint_baseline.json")
        ).apply(findings)
        assert new == [], [
            (f.rule_id, f.path, f.line, f.message) for f in new
        ]
        assert stale == [], [(e.rule, e.path) for e in stale]
        # every grandfathered entry is used AND justified (shrink-only)
        assert grandfathered, "baseline entries exist, so some must match"

    def test_fixture_corpus_is_excluded_from_sweep(self):
        a = Analyzer(ROOT, default_rules(),
                     exclude=("tests/lint_fixtures",))
        files = a.collect_files(["tests"])
        assert not any("lint_fixtures" in f for f in files)

    def test_hostring_and_ddp_lockstep_clean(self):
        """PTD001 regression pin on the two collective-bearing modules:
        hostring issues the collectives, ddp's sync callback drives
        them — both must stay rank-uniform as written today."""
        findings = lint_paths(
            [
                "pytorch_distributed_tpu/runtime/hostring.py",
                "pytorch_distributed_tpu/parallel/ddp.py",
            ],
            rules=[LockstepCollectives()],
        )
        assert findings == [], [(f.path, f.line) for f in findings]

    def test_elastic_world_lockstep_clean(self):
        """PTD001 pin on the elastic subsystem (r13): the membership
        view-change collectives (commit digest allgather + barrier) and
        the resize engine's re-shard broadcasts are uniform-by-
        construction — every rank issues the identical sequence, with
        rank-dependence confined to VALUES, never to call sites."""
        findings = lint_paths(
            [
                "pytorch_distributed_tpu/runtime/membership.py",
                "pytorch_distributed_tpu/train/elastic_world.py",
            ],
            rules=[LockstepCollectives()],
        )
        assert findings == [], [(f.path, f.line) for f in findings]

    def test_injected_view_change_rank_guard_is_caught(self, tmp_path):
        """A rank-gated view-commit collective smuggled into a copy of
        membership.py — the exact hazard the commit barrier exists to
        prevent — is flagged."""
        src = os.path.join(
            ROOT, "pytorch_distributed_tpu", "runtime", "membership.py"
        )
        target = tmp_path / "membership.py"
        shutil.copy(src, target)
        with open(target, "a") as f:
            f.write(
                "\n\ndef _leader_only_commit(ring, digest):\n"
                "    if ring.rank == 0:\n"
                "        rows = ring.all_gather(digest)\n"
                "        return rows\n"
            )
        findings = lint_paths(
            [str(target)], root=str(tmp_path),
            rules=[LockstepCollectives()],
        )
        assert [f.rule_id for f in findings] == ["PTD001"]
        assert "all_gather" in findings[0].message

    def test_injected_rank_guard_is_caught(self, tmp_path):
        """Injecting a rank-guarded collective into a copy of the real
        module is caught — the rule defends the file it patrols, not
        just synthetic fixtures."""
        src = os.path.join(
            ROOT, "pytorch_distributed_tpu", "runtime", "hostring.py"
        )
        target = tmp_path / "hostring.py"
        shutil.copy(src, target)
        with open(target, "a") as f:
            f.write(
                "\n\ndef _owner_only_flush(ring, vec):\n"
                "    if ring.rank == 0:\n"
                "        ring.broadcast(vec, src=0)\n"
            )
        findings = lint_paths(
            [str(target)], root=str(tmp_path),
            rules=[LockstepCollectives()],
        )
        assert [f.rule_id for f in findings] == ["PTD001"]
        assert "broadcast" in findings[0].message
        # and the uninjected copy is clean (the finding IS the injection)
        clean = tmp_path / "clean.py"
        shutil.copy(src, clean)
        assert lint_paths(
            [str(clean)], root=str(tmp_path),
            rules=[LockstepCollectives()],
        ) == []


class TestSuppression:
    SRC = (
        "from pytorch_distributed_tpu.runtime import tracing\n"
        "def f(xs):\n"
        "    tracing.instant('x', n=len(xs)){}\n"
    )

    def test_unsuppressed_fires(self):
        assert [f.rule_id for f in lint_source(self.SRC.format(""))] == [
            "PTD002"
        ]

    def test_trailing_comment_suppresses(self):
        src = self.SRC.format("  # ptdlint: disable=PTD002")
        assert lint_source(src) == []

    def test_comment_above_suppresses(self):
        src = (
            "from pytorch_distributed_tpu.runtime import tracing\n"
            "def f(xs):\n"
            "    # ptdlint: disable=PTD002\n"
            "    tracing.instant('x', n=len(xs))\n"
        )
        assert lint_source(src) == []

    def test_wrong_rule_id_does_not_suppress(self):
        src = self.SRC.format("  # ptdlint: disable=PTD001")
        assert [f.rule_id for f in lint_source(src)] == ["PTD002"]

    def test_disable_all(self):
        src = self.SRC.format("  # ptdlint: disable=all")
        assert lint_source(src) == []


class TestBaseline:
    def _finding(self, line_text="tracing.instant('x', n=len(xs))"):
        return Finding(
            rule_id="PTD002", path="pkg/mod.py", line=3,
            message="m", line_text=line_text,
        )

    def _entry(self, **kw):
        base = dict(
            rule="PTD002", path="pkg/mod.py",
            line_text="tracing.instant('x', n=len(xs))",
            justification="grandfathered for the test",
        )
        base.update(kw)
        return BaselineEntry(**base)

    def test_content_addressed_match_ignores_line_number(self):
        new, grandfathered, stale = Baseline([self._entry()]).apply(
            [self._finding()]
        )
        assert new == [] and len(grandfathered) == 1 and stale == []

    def test_one_entry_covers_identical_line_texts(self):
        f1, f2 = self._finding(), self._finding()
        new, grandfathered, _ = Baseline([self._entry()]).apply([f1, f2])
        assert new == [] and len(grandfathered) == 2

    def test_stale_entry_reported(self):
        new, _, stale = Baseline(
            [self._entry(line_text="gone_from_the_tree()")]
        ).apply([self._finding()])
        assert len(new) == 1 and len(stale) == 1

    def test_roundtrip_and_validation(self, tmp_path):
        p = tmp_path / "baseline.json"
        Baseline([self._entry()]).save(str(p))
        loaded = Baseline.load(str(p))
        assert [e.key() for e in loaded.entries] == [self._entry().key()]
        # an unjustified grandfather is refused at load
        doc = json.loads(p.read_text())
        doc["entries"][0]["justification"] = "  "
        p.write_text(json.dumps(doc))
        with pytest.raises(ValueError, match="justification"):
            Baseline.load(str(p))

    def test_missing_file_is_empty(self, tmp_path):
        assert Baseline.load(str(tmp_path / "nope.json")).entries == []

    def test_fill_me_placeholder_is_refused(self, tmp_path):
        """--write-baseline's placeholder does not count as a
        justification: committing the file unedited must fail loudly."""
        p = tmp_path / "baseline.json"
        Baseline([self._entry(
            justification="FILL-ME: one-line justification"
        )]).save(str(p))
        with pytest.raises(ValueError, match="FILL-ME"):
            Baseline.load(str(p))

    def test_parse_errors_are_never_grandfathered(self, tmp_path):
        """A baselined PTD000 would exempt the file from EVERY rule
        forever — refused at load, and ignored by apply even if an
        in-memory baseline carries one."""
        p = tmp_path / "baseline.json"
        Baseline([self._entry(
            rule="PTD000", line_text="def f(:"
        )]).save(str(p))
        with pytest.raises(ValueError, match="cannot be baselined"):
            Baseline.load(str(p))
        parse_finding = Finding(
            rule_id="PTD000", path="pkg/mod.py", line=1,
            message="file does not parse", line_text="def f(:",
        )
        new, grandfathered, _ = Baseline(
            [self._entry(rule="PTD000", line_text="def f(:")]
        ).apply([parse_finding])
        assert grandfathered == [] and new == [parse_finding]


def _run_cli(*args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, CLI, *args],
        capture_output=True, text=True, cwd=cwd,
    )


class TestCLI:
    def test_default_sweep_exits_zero(self):
        res = _run_cli("--json")
        assert res.returncode == 0, res.stdout + res.stderr
        doc = json.loads(res.stdout)
        assert doc["ok"] is True
        assert doc["findings"] == []
        assert doc["counts"]["stale_baseline"] == 0
        # the grandfathered entries are visible, not hidden
        assert doc["counts"]["baselined"] == len(doc["baselined"]) > 0

    def test_findings_exit_nonzero_with_json(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        shutil.copy(
            os.path.join(FIXTURES, "ptd001_bad.py"), pkg / "bad.py"
        )
        res = _run_cli(
            "--root", str(tmp_path), "--json",
            "--baseline", str(tmp_path / "baseline.json"), "pkg",
        )
        assert res.returncode == 1
        doc = json.loads(res.stdout)
        assert doc["ok"] is False
        assert doc["counts"]["rule.PTD001"] == doc["counts"]["new"] > 0
        for f in doc["findings"]:
            assert f["rule_id"] == "PTD001" and f["path"] == "pkg/bad.py"

    def test_stale_baseline_exits_nonzero(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "clean.py").write_text("x = 1\n")
        baseline = tmp_path / "baseline.json"
        Baseline([BaselineEntry(
            rule="PTD001", path="pkg/clean.py",
            line_text="never_there()", justification="stale on purpose",
        )]).save(str(baseline))
        res = _run_cli(
            "--root", str(tmp_path), "--baseline", str(baseline), "pkg",
        )
        assert res.returncode == 1
        assert "stale baseline" in res.stdout

    def test_parse_error_is_a_finding(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "broken.py").write_text("def f(:\n")
        res = _run_cli(
            "--root", str(tmp_path), "--json",
            "--baseline", str(tmp_path / "baseline.json"), "pkg",
        )
        assert res.returncode == 1
        doc = json.loads(res.stdout)
        assert doc["counts"]["parse_errors"] == 1
        assert doc["findings"][0]["rule_id"] == "PTD000"

    def test_rule_filter(self, tmp_path):
        res = _run_cli("--rules", "PTD999")
        assert res.returncode == 2
        res = _run_cli("--rules", "ptd001", "--json")
        assert res.returncode == 0  # subset of a clean sweep

    def test_write_baseline_refuses_scoped_runs(self, tmp_path):
        """A scoped regeneration would silently delete every
        out-of-scope entry (and its hand-written justification)."""
        baseline = str(tmp_path / "b.json")
        for scope in (("--rules", "PTD001"), ("tests",)):
            res = _run_cli("--baseline", baseline, "--write-baseline",
                           *scope)
            assert res.returncode == 2, res.stderr
            assert "scoped" in res.stderr
            assert not os.path.exists(baseline)

    def test_metrics_record_rides_the_jsonl_protocol(self, tmp_path):
        """--json output rides MetricsWriter (split='lint') so finding
        counts are trackable across PRs. In-process: the subprocess
        route would pay a fresh jax import for one record."""
        import importlib.util

        spec = importlib.util.spec_from_file_location("ptd_lint", CLI)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        path = str(tmp_path / "metrics.jsonl")
        mod._write_metrics(path, {
            "counts": {"new": 0, "baselined": 5, "stale_baseline": 0,
                       "parse_errors": 0},
        })
        from pytorch_distributed_tpu.train.metrics import read_metrics

        recs = [
            r for r in read_metrics(path) if r.get("split") == "lint"
        ]
        assert len(recs) == 1
        assert recs[0]["event"] == "ptdlint"
        assert recs[0]["baselined"] == 5 and recs[0]["new"] == 0


class TestRuleEnvelopes:
    """Targeted pins on the judgment calls inside individual rules."""

    def test_ptd001_taint_through_assignment(self):
        src = (
            "def f(ring, src):\n"
            "    is_src = ring.rank == src\n"
            "    owner = is_src and True\n"
            "    if owner:\n"
            "        ring.barrier()\n"
        )
        fs = lint_source(src, rules=[LockstepCollectives()])
        assert [f.rule_id for f in fs] == ["PTD001"]

    def test_ptd001_rank_guard_nested_under_else_is_judged(self):
        """A rank guard indented under `else:` is NOT an elif arm: its
        own missing branch is a real divergence even when the parent's
        arms happen to contain matching ops (ranks >= 2 here never
        issue the collective)."""
        src = (
            "def f(ring, rank, x):\n"
            "    if rank == 0:\n"
            "        ring.all_reduce(x)\n"
            "    else:\n"
            "        if rank == 1:\n"
            "            ring.all_reduce(x)\n"
        )
        fs = lint_source(src, rules=[LockstepCollectives()])
        assert [f.rule_id for f in fs] == ["PTD001"]
        # the same chain as a TRUE elif stays clean for P2P pairs
        src_elif = (
            "def f(ring, rank, x):\n"
            "    if rank == 0:\n"
            "        ring.send(x, dst=1)\n"
            "    elif rank == 1:\n"
            "        ring.recv(x, src=0)\n"
        )
        assert lint_source(src_elif, rules=[LockstepCollectives()]) == []

    def test_ptd001_early_return_is_implicit_else(self):
        src = (
            "def f(ring, rank, x):\n"
            "    if rank == 0:\n"
            "        return ring.all_reduce(x)\n"
            "    return ring.all_reduce(x)\n"
        )
        assert lint_source(src, rules=[LockstepCollectives()]) == []

    def test_ptd003_registry_parsed_from_faults_source(self):
        from pytorch_distributed_tpu.runtime import faults

        assert FaultSiteRegistry().registry == set(faults.KNOWN_SITES)

    def test_ptd003_covers_throttle_call_sites(self):
        """r15: the slowdown-injection poll (``faults.throttle``) is a
        registry-checked call form too — a typo'd site name would make a
        heterogeneity drill inject nothing and 'pass'."""
        src = (
            "from pytorch_distributed_tpu.runtime import faults\n"
            "def f():\n"
            "    return faults.throttle('elastic.slow_wrank')\n"
        )
        fs = lint_source(src, rules=[FaultSiteRegistry()])
        assert [f.rule_id for f in fs] == ["PTD003"]
        ok = src.replace("slow_wrank", "slow_rank")
        assert lint_source(ok, rules=[FaultSiteRegistry()]) == []

    def test_ptd004_respects_path_filter(self):
        src = "import jax.numpy as jnp\nx = jnp.zeros(4).at[0].set(1.0)\n"
        hot = lint_source(
            src, relpath="pytorch_distributed_tpu/serve/mod.py",
            rules=[EagerScatterHotPath()],
        )
        assert [f.rule_id for f in hot] == ["PTD004"]
        cold = lint_source(
            src, relpath="pytorch_distributed_tpu/models/mod.py",
            rules=[EagerScatterHotPath()],
        )
        assert cold == []
        # round 12: the paged-attention module (home of the per-page
        # KV write the decode tick runs) is a hot path too — the rule
        # keeps teeth on the new code, but not on the rest of ops/
        paged = lint_source(
            src, relpath="pytorch_distributed_tpu/ops/paged_attention.py",
            rules=[EagerScatterHotPath()],
        )
        assert [f.rule_id for f in paged] == ["PTD004"]
        other_ops = lint_source(
            src, relpath="pytorch_distributed_tpu/ops/quant.py",
            rules=[EagerScatterHotPath()],
        )
        assert other_ops == []

    def test_ptd004_real_paged_attention_module_is_clean(self):
        """The real per-page write helper is suppressed explicitly
        (inline disable naming the jitted-caller contract), like
        serve/kv_slots.scatter_kv before it — the module lints clean
        without a baseline entry."""
        fs = lint_paths(
            ["pytorch_distributed_tpu/ops/paged_attention.py"],
            rules=[EagerScatterHotPath()],
        )
        assert fs == []

    def test_ptd004_engine_jit_wrap_recognized(self):
        """The real engine pattern: methods jitted in __init__, row
        updates inside them — stays clean (the fix PR 3 shipped)."""
        fs = lint_paths(
            ["pytorch_distributed_tpu/serve/engine.py"],
            rules=[EagerScatterHotPath()],
        )
        assert fs == []

    def test_ptd005_branches_do_not_pair(self):
        src = (
            "import jax\n"
            "def f(key, g):\n"
            "    if g:\n"
            "        return jax.random.normal(key)\n"
            "    else:\n"
            "        return jax.random.uniform(key)\n"
        )
        assert lint_source(src, rules=[PrngKeyReuse()]) == []

    def test_ptd005_numpy_random_is_ignored(self):
        src = (
            "import numpy as np\n"
            "def f(xs):\n"
            "    a = np.random.permutation(xs)\n"
            "    b = np.random.permutation(xs)\n"
            "    return a, b\n"
        )
        assert lint_source(src, rules=[PrngKeyReuse()]) == []

    def test_ptd006_same_statement_rebind_is_clean(self):
        src = (
            "import jax\n"
            "step = jax.jit(lambda s, b: s, donate_argnums=(0,))\n"
            "def run(state, batch):\n"
            "    state = step(state, batch)\n"
            "    return state.sum()\n"
        )
        assert lint_source(src, rules=[DonationAfterUse()]) == []

    def test_ptd006_conditional_donation_counts(self):
        src = (
            "import jax\n"
            "step = jax.jit(lambda s, b: s,\n"
            "               donate_argnums=(0,) if True else ())\n"
            "def run(state, batch):\n"
            "    out = step(state, batch)\n"
            "    return out, state.sum()\n"
        )
        fs = lint_source(src, rules=[DonationAfterUse()])
        assert [f.rule_id for f in fs] == ["PTD006"]


class TestFaultsRegistryRuntime:
    """The runtime half of PTD003: a typo'd site name must be loud."""

    def test_unknown_site_warns_once_when_armed(self, caplog):
        from pytorch_distributed_tpu.runtime import faults

        faults._warned_unknown_sites.discard("step.typo")
        with faults.injected("step.nan:count=1"):
            with ptd_caplog(caplog):
                # the typo is the point here  # ptdlint: disable=PTD003
                assert faults.fires("step.typo") is False
                faults.check("step.typo")  # ptdlint: disable=PTD003
        warned = [
            r for r in caplog.records if "not in KNOWN_SITES" in r.message
        ]
        assert len(warned) == 1  # once per name, not per check
        assert "step.typo" in warned[0].getMessage()

    def test_unknown_site_silent_when_disarmed(self, caplog):
        from pytorch_distributed_tpu.runtime import faults

        faults._warned_unknown_sites.discard("step.other_typo")
        assert not faults.active()
        with ptd_caplog(caplog):
            # ptdlint: disable=PTD003
            assert faults.fires("step.other_typo") is False
        assert not any(
            "not in KNOWN_SITES" in r.message for r in caplog.records
        )

    def test_arming_unknown_site_still_raises(self):
        from pytorch_distributed_tpu.runtime import faults

        with pytest.raises(ValueError, match="unknown fault site"):
            faults.FaultPlan.parse("ckpt.writ_shard:count=1")
