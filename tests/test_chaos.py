"""Chaos suite: seeded fault injection proving the recovery paths.

The crash-consistency contract (ISSUE 2 / docs/DESIGN.md failure model):

* a save killed at any injected point leaves a restorable directory;
* restore falls back past corrupted checkpoints to the newest intact one
  with bit-exact state;
* an epoch over a folder with undecodable images completes, quarantining
  the rot, with numerics parity on the surviving samples;
* async checkpoint write errors surface on the next save()/wait() and do
  not wedge the checkpointer.

Everything here is deterministic (seeded injection, seeded data) and
CI-fast — this file IS the tier-1 chaos subset; whole-process kill-resume
drills live in scripts/chaos_drill.py.
"""

import json
import os
import shutil
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_tpu.data import (
    BadSampleBudgetExceeded,
    DataLoader,
    ArrayDataset,
)
from pytorch_distributed_tpu.data.image_folder import (
    FolderImagePipeline,
    ImageFolderDataset,
)
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh
from pytorch_distributed_tpu.train import (
    CheckpointCorrupted,
    Trainer,
    TrainerConfig,
    TrainingDiverged,
    TrainState,
    Watchdog,
    build_train_step,
    checkpoint_step,
    recover_stranded_checkpoints,
    restore_candidates,
    restore_checkpoint,
    resolve_tag,
    save_checkpoint,
    verify_checkpoint,
)
from pytorch_distributed_tpu.train.checkpoint import AsyncCheckpointer

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm_faults():
    """A test that dies mid-``injected`` must not leak an armed plan."""
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# the injector itself
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_unarmed_is_noop(self):
        assert not faults.active()
        faults.check("ckpt.write_shard", path="/nope")  # no raise
        assert not faults.fires("step.nan")
        assert faults.fire_count("ckpt.swing") == 0

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.FaultPlan.parse("ckpt.wrote_shard:count=1")
        with pytest.raises(ValueError, match="unknown option"):
            faults.FaultPlan.parse("ckpt.swing:frequency=1")
        with pytest.raises(ValueError, match="unknown mode"):
            faults.FaultPlan.parse("ckpt.swing:mode=explode")
        with pytest.raises(ValueError, match="empty fault spec"):
            faults.FaultPlan.parse(" ; ")

    def test_count_budget_and_after(self):
        with faults.injected("data.fetch:count=2,after=1"):
            fired = [
                n for n in range(6)
                if faults.fires("data.fetch", path=f"/s{n}")
            ]
            # first eligible check skipped (after=1), then two fires
            assert fired == [1, 2]
            assert faults.fire_count("data.fetch") == 2

    def test_match_filters_by_path(self):
        with faults.injected("ckpt.read_shard:match=special"):
            assert not faults.fires("ckpt.read_shard", path="/a/plain.npy")
            assert faults.fires("ckpt.read_shard", path="/a/special.npy")

    def test_probability_is_seed_deterministic(self):
        def stream(seed):
            with faults.injected("data.decode:p=0.5", seed=seed):
                return [faults.fires("data.decode") for _ in range(32)]

        a, b, c = stream(7), stream(7), stream(8)
        assert a == b
        assert a != c
        assert 0 < sum(a) < 32  # p=0.5 really is probabilistic

    def test_injected_restores_previous_plan(self):
        faults.configure("step.nan:count=1")
        with faults.injected("ckpt.swing"):
            assert faults.fire_count("ckpt.swing") == 0
            with pytest.raises(faults.InjectedFault):
                faults.check("ckpt.swing")
        assert faults.active()
        assert faults.fires("step.nan")  # the outer plan survived
        faults.clear()

    def test_env_arming(self):
        # the env hook runs at import; exercise the same code path it
        # calls (configure reading PTD_FAULTS_SEED) without re-importing
        os.environ[faults.ENV_SEED] = "3"
        try:
            plan = faults.configure("data.decode:p=0.5")
            assert plan.sites["data.decode"]._rng is not None
        finally:
            del os.environ[faults.ENV_SEED]
            faults.clear()

    def test_corrupting_modes(self, tmp_path):
        p = tmp_path / "blob.bin"
        p.write_bytes(bytes(range(100)))
        with faults.injected("ckpt.write_shard:mode=truncate,count=1"):
            faults.check("ckpt.write_shard", path=str(p))  # silent
        assert p.stat().st_size == 50
        p.write_bytes(bytes(range(100)))
        with faults.injected("ckpt.write_shard:mode=bitflip,count=1"):
            faults.check("ckpt.write_shard", path=str(p))
        data = p.read_bytes()
        assert len(data) == 100 and data[50] == (50 ^ 0xFF)


# ---------------------------------------------------------------------------
# checkpoint integrity + fallback restore
# ---------------------------------------------------------------------------


def linear_state(step=0, fill=1.0):
    s = TrainState.create(
        apply_fn=lambda p, x: x @ p["w"],
        params={"w": jnp.full((4, 2), fill, jnp.float32)},
        tx=optax.sgd(0.1),
    )
    return s.replace(step=jnp.asarray(step, jnp.int32))


def _shard_files(ckpt: str):
    return sorted(f for f in os.listdir(ckpt) if f.endswith(".npy"))


def _param_shard(ckpt: str):
    """Path of the params.w shard file."""
    for f in _shard_files(ckpt):
        if "params" in f:
            return os.path.join(ckpt, f)
    raise AssertionError(f"no params shard in {ckpt}")


class TestCheckpointIntegrity:
    def test_manifest_records_checksums_and_commit(self, tmp_path):
        save_checkpoint(str(tmp_path), linear_state(1))
        manifest = json.load(open(tmp_path / "latest" / "manifest.json"))
        assert manifest["version"] == 2  # additive fields, same version
        for entry in manifest["leaves"]:
            for shard in entry["shards"]:
                assert shard["bytes"] > 0
                assert "checksum" in shard and "checksum_algo" in shard
        commit = json.load(open(tmp_path / "latest" / "COMMIT"))
        assert commit["step"] == 1
        assert verify_checkpoint(str(tmp_path)) == []

    def test_verify_detects_truncation_bitflip_missing(self, tmp_path):
        save_checkpoint(str(tmp_path), linear_state(1))
        ckpt = str(tmp_path / "latest")
        shard = _param_shard(ckpt)
        good = open(shard, "rb").read()

        with open(shard, "r+b") as f:
            f.truncate(len(good) // 2)
        assert any("truncated" in p for p in verify_checkpoint(str(tmp_path)))

        with open(shard, "wb") as f:  # restore, then flip one byte
            f.write(good[:-1] + bytes([good[-1] ^ 1]))
        assert any("mismatch" in p for p in verify_checkpoint(str(tmp_path)))

        os.unlink(shard)
        assert any("missing" in p for p in verify_checkpoint(str(tmp_path)))

    def test_verify_detects_tampered_manifest(self, tmp_path):
        save_checkpoint(str(tmp_path), linear_state(1))
        mpath = tmp_path / "latest" / "manifest.json"
        manifest = json.load(open(mpath))
        manifest["step"] = 999  # rewrite changes bytes vs COMMIT record
        json.dump(manifest, open(mpath, "w"))
        assert any(
            "COMMIT" in p for p in verify_checkpoint(str(tmp_path))
        )

    def test_corrupt_manifest_reads_as_absent(self, tmp_path):
        """Satellite: resolve_tag/checkpoint_step keep scanning past a
        corrupt or truncated manifest instead of crashing."""
        save_checkpoint(str(tmp_path), linear_state(3), tag="step-3")
        bad = tmp_path / "step-9"
        bad.mkdir()
        (bad / "manifest.json").write_text('{"version": 2, "step": 9, ')
        assert checkpoint_step(str(tmp_path), "step-9") is None
        assert resolve_tag(str(tmp_path)) == "step-3"
        # the EXPLICIT-tag path too: a corrupt manifest is absent, not a
        # tag handed back for restore to die on
        assert resolve_tag(str(tmp_path), "step-9") is None
        assert restore_candidates(str(tmp_path)) == ["step-3"]

    def test_legacy_manifest_still_verifies(self, tmp_path):
        """A pre-integrity checkpoint (no bytes/checksum/COMMIT) must not
        be reported corrupt — MIGRATION.md: version-2 restores keep
        reading manifests with and without the new fields."""
        save_checkpoint(str(tmp_path), linear_state(4))
        ckpt = tmp_path / "latest"
        os.unlink(ckpt / "COMMIT")
        mpath = ckpt / "manifest.json"
        manifest = json.load(open(mpath))
        for entry in manifest["leaves"]:
            for shard in entry["shards"]:
                shard.pop("bytes"), shard.pop("checksum")
                shard.pop("checksum_algo")
        json.dump(manifest, open(mpath, "w"))
        assert verify_checkpoint(str(tmp_path)) == []
        restored = restore_checkpoint(str(tmp_path), linear_state())
        assert int(restored.step) == 4


class TestSaveCrash:
    def test_killed_mid_write_leaves_newest_intact_restorable(self, tmp_path):
        save_checkpoint(str(tmp_path), linear_state(2, fill=2.0), tag="step-2")
        with faults.injected("ckpt.write_shard:count=1,mode=raise"):
            with pytest.raises(faults.InjectedFault):
                save_checkpoint(str(tmp_path), linear_state(5, fill=5.0))
        # the aborted save left only a tmp (no COMMIT): not a candidate
        assert os.path.isdir(tmp_path / "latest.tmp")
        assert recover_stranded_checkpoints(str(tmp_path)) == []
        assert restore_candidates(str(tmp_path)) == ["step-2"]
        restored = restore_checkpoint(
            str(tmp_path), linear_state(), tag="step-2"
        )
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), np.full((4, 2), 2.0)
        )
        # and the NEXT (disarmed) save of the same tag goes through
        save_checkpoint(str(tmp_path), linear_state(6, fill=6.0))
        assert verify_checkpoint(str(tmp_path)) == []
        assert checkpoint_step(str(tmp_path)) == 6

    def test_swing_window_finishes_interrupted_commit(self, tmp_path):
        save_checkpoint(str(tmp_path), linear_state(1, fill=1.0))
        with faults.injected("ckpt.swing:count=1,mode=raise"):
            with pytest.raises(faults.InjectedFault):
                save_checkpoint(str(tmp_path), linear_state(9, fill=9.0))
        # the kill landed between final->old and tmp->final
        assert not os.path.exists(tmp_path / "latest")
        assert os.path.isdir(tmp_path / "latest.old")
        assert os.path.isdir(tmp_path / "latest.tmp")
        # the tmp is COMMIT-complete: recovery finishes the swing and the
        # NEWER state wins
        assert recover_stranded_checkpoints(str(tmp_path)) == ["latest"]
        assert verify_checkpoint(str(tmp_path)) == []
        restored = restore_checkpoint(str(tmp_path), linear_state())
        assert int(restored.step) == 9
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), np.full((4, 2), 9.0)
        )

    def test_swing_recovery_never_destroys_intact_old(self, tmp_path):
        """A COMMIT-complete tmp whose shards rotted AFTER checksumming
        must not be promoted — _swing deletes <tag>.old, so promoting it
        would destroy the only intact checkpoint (found in review)."""
        save_checkpoint(str(tmp_path), linear_state(3, fill=3.0))
        with faults.injected(
            "ckpt.write_shard:mode=bitflip,count=1,match=params;"
            "ckpt.swing:count=1,mode=raise"
        ):
            with pytest.raises(faults.InjectedFault):
                save_checkpoint(str(tmp_path), linear_state(9, fill=9.0))
        # tmp is COMMIT-complete but its params shard is corrupt; the
        # intact previous checkpoint survives only as latest.old
        assert os.path.isdir(tmp_path / "latest.tmp")
        assert os.path.isdir(tmp_path / "latest.old")
        assert recover_stranded_checkpoints(str(tmp_path)) == ["latest"]
        assert verify_checkpoint(str(tmp_path)) == []
        restored = restore_checkpoint(str(tmp_path), linear_state())
        assert int(restored.step) == 3  # the OLD one, not the rotten 9
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), np.full((4, 2), 3.0)
        )

    def test_swing_window_promotes_old_when_tmp_unusable(self, tmp_path):
        """Satellite: a stranded ``<tag>.old`` (tmp gone/incomplete) is
        detected and restored instead of being invisible to resolution."""
        save_checkpoint(str(tmp_path), linear_state(3, fill=3.0))
        os.replace(tmp_path / "latest", tmp_path / "latest.old")
        (tmp_path / "latest.tmp").mkdir()  # aborted write, no COMMIT
        assert resolve_tag(str(tmp_path)) is None  # invisible without...
        assert recover_stranded_checkpoints(str(tmp_path)) == ["latest"]
        assert resolve_tag(str(tmp_path)) == "latest"  # ...recovery
        restored = restore_checkpoint(str(tmp_path), linear_state())
        assert int(restored.step) == 3
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), np.full((4, 2), 3.0)
        )


# ---------------------------------------------------------------------------
# Trainer-level fallback chain
# ---------------------------------------------------------------------------


def linear_loss_fn(params, batch_stats, batch, rng):
    loss = jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)
    return loss, {"metrics": {"loss": loss}, "batch_stats": batch_stats}


def _linear_trainer(tmp_path, **cfg_kw):
    make_mesh(MeshSpec(dp=8))
    strategy = DataParallel()
    rng = np.random.default_rng(0)
    ds = ArrayDataset(
        x=rng.normal(size=(64, 4)).astype(np.float32),
        y=rng.normal(size=(64, 2)).astype(np.float32),
    )
    cfg_kw.setdefault("epochs", 1)
    cfg_kw.setdefault("log_every", 0)
    return Trainer(
        linear_state(),
        strategy,
        build_train_step(linear_loss_fn),
        DataLoader(ds, 8, seed=0),
        config=TrainerConfig(ckpt_dir=str(tmp_path), **cfg_kw),
    )


class TestRestoreFallbackChain:
    def test_falls_back_past_two_corrupted_to_bit_exact(self, tmp_path):
        for step, fill in ((2, 2.0), (4, 4.0), (6, 6.0)):
            save_checkpoint(
                str(tmp_path), linear_state(step, fill), tag=f"step-{step}"
            )
        # newest: silently truncated shard (torn write after checksum)
        shard = _param_shard(str(tmp_path / "step-6"))
        with open(shard, "r+b") as f:
            f.truncate(os.path.getsize(shard) // 2)
        # second newest: manifest rot
        (tmp_path / "step-4" / "manifest.json").write_text("ceci n'est pas")

        trainer = _linear_trainer(tmp_path)
        assert trainer.restore_checkpoint()
        assert trainer.host_step == 2
        np.testing.assert_array_equal(
            np.asarray(trainer.state.params["w"]), np.full((4, 2), 2.0)
        )

    def test_injected_read_failure_falls_back(self, tmp_path):
        save_checkpoint(str(tmp_path), linear_state(2, 2.0), tag="step-2")
        save_checkpoint(str(tmp_path), linear_state(8, 8.0), tag="step-8")
        trainer = _linear_trainer(tmp_path)
        # every read of step-8's params fails (checksums pass: the rot is
        # in the read path, not the bytes) — the chain must still land
        with faults.injected("ckpt.read_shard:match=step-8"):
            assert trainer.restore_checkpoint()
        assert trainer.host_step == 2

    def test_all_corrupt_raises_not_silent_fresh_start(self, tmp_path):
        save_checkpoint(str(tmp_path), linear_state(2), tag="step-2")
        (tmp_path / "step-2" / "manifest.json").write_text("{")
        trainer = _linear_trainer(tmp_path)
        with pytest.raises(CheckpointCorrupted):
            trainer.restore_checkpoint()

    def test_nothing_on_disk_is_a_fresh_start(self, tmp_path):
        trainer = _linear_trainer(tmp_path)
        assert not trainer.restore_checkpoint()
        # explicitly-requested absent tag: absent, not an error
        assert not trainer.restore_checkpoint(tag="best")

    def test_explicit_tag_with_torn_manifest_raises(self, tmp_path):
        """An explicitly-named tag whose dir exists but whose manifest is
        torn must raise, not silently read as absent and train fresh."""
        save_checkpoint(str(tmp_path), linear_state(5), tag="best")
        (tmp_path / "best" / "manifest.json").write_text("{")
        trainer = _linear_trainer(tmp_path)
        with pytest.raises(CheckpointCorrupted):
            trainer.restore_checkpoint(tag="best")

    def test_resume_after_preemptionless_kill_end_to_end(self, tmp_path):
        """Train, corrupt the newest checkpoint, resume: training
        continues from the newest INTACT one."""
        trainer = _linear_trainer(
            tmp_path, epochs=1, ckpt_every_steps=4, keep_checkpoints=2
        )
        trainer.fit()  # saves step-4, step-8 + latest at epoch end
        assert checkpoint_step(str(tmp_path)) == 8
        shard = _param_shard(str(tmp_path / "latest"))
        with open(shard, "r+b") as f:
            f.seek(os.path.getsize(shard) // 2)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0xFF]))
        resumed = _linear_trainer(
            tmp_path, epochs=2, ckpt_every_steps=4, keep_checkpoints=2
        )
        assert resumed.restore_checkpoint()
        assert resumed.host_step == 8  # step-8, not the rotten latest
        resumed.fit()
        assert resumed.host_step == 16


# ---------------------------------------------------------------------------
# async checkpointer failure surfacing
# ---------------------------------------------------------------------------


class TestAsyncCheckpointerFailures:
    def test_error_surfaces_on_next_save_and_does_not_wedge(self, tmp_path):
        ac = AsyncCheckpointer()
        with faults.injected("ckpt.write_shard:count=1,mode=raise"):
            ac.save(str(tmp_path), linear_state(1))  # fails in background
            if ac._thread is not None:
                ac._thread.join()
            # the failure must raise on the NEXT save, not be dropped
            with pytest.raises(RuntimeError, match="async checkpoint"):
                ac.save(str(tmp_path), linear_state(2))
        # and the checkpointer is not wedged: a later save lands cleanly
        ac.save(str(tmp_path), linear_state(3))
        ac.wait()
        assert checkpoint_step(str(tmp_path)) == 3
        assert verify_checkpoint(str(tmp_path)) == []

    def test_error_surfaces_on_wait(self, tmp_path):
        ac = AsyncCheckpointer()
        with faults.injected("ckpt.write_shard:count=1,mode=raise"):
            ac.save(str(tmp_path), linear_state(1))
            with pytest.raises(RuntimeError, match="async checkpoint"):
                ac.wait()
        ac.wait()  # error consumed exactly once


# ---------------------------------------------------------------------------
# ingest fault tolerance
# ---------------------------------------------------------------------------


def _make_image_folder(root, n_per_class=4, size=20, classes=("cat", "dog")):
    """Tiny deterministic RGB folder tree; returns all file paths."""
    from PIL import Image

    rng = np.random.default_rng(0)
    paths = []
    for c in classes:
        os.makedirs(os.path.join(root, c), exist_ok=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 255, size=(size, size, 3), dtype=np.uint8)
            p = os.path.join(root, c, f"{i:03d}.png")
            Image.fromarray(arr).save(p)
            paths.append(p)
    return paths


def _eval_pipe(**kw):
    kw.setdefault("num_threads", 1)  # deterministic error ordering
    kw.setdefault("retry_backoff_s", 0.0)
    return FolderImagePipeline(16, train=False, resize=18, **kw)


class TestIngestFaultTolerance:
    def test_undecodable_samples_quarantined_with_parity(self, tmp_path):
        clean, dirty = str(tmp_path / "clean"), str(tmp_path / "dirty")
        _make_image_folder(clean)
        shutil.copytree(clean, dirty)
        ds_clean, ds_dirty = ImageFolderDataset(clean), ImageFolderDataset(dirty)
        # rot two files: one junk (undecodable), one truncated PNG
        bad = [ds_dirty.samples[1][0], ds_dirty.samples[5][0]]
        open(bad[0], "wb").write(b"not an image at all")
        blob = open(ds_dirty.samples[5][0], "rb").read()
        open(bad[1], "wb").write(blob[: len(blob) // 2])

        idx = np.arange(len(ds_clean))
        ref = _eval_pipe()(ds_clean, idx)
        pipe = _eval_pipe()
        out = pipe(ds_dirty, idx)

        # the epoch completed at full batch shape, rot quarantined
        assert out["image"].shape == ref["image"].shape
        assert len(pipe.quarantine) == 2
        assert sorted(pipe.quarantine.paths) == sorted(bad)
        # numerics parity on every surviving sample
        for j in range(len(idx)):
            if ds_dirty.samples[j][0] in bad:
                continue
            np.testing.assert_array_equal(
                out["image"][j], ref["image"][j]
            )
            assert out["label"][j] == ref["label"][j]
        # substitution is the next readable sample, not garbage
        for j, path in enumerate(p for p, _ in ds_dirty.samples):
            if path in bad:
                np.testing.assert_array_equal(
                    out["image"][j], ref["image"][j + 1]
                )

    def test_transient_fetch_errors_are_retried(self, tmp_path):
        root = str(tmp_path / "imgs")
        _make_image_folder(root)
        ds = ImageFolderDataset(root)
        pipe = _eval_pipe(io_retries=2)
        with faults.injected("data.fetch:count=2,mode=raise"):
            out = pipe(ds, np.arange(4))
            assert faults.fire_count("data.fetch") == 2
        assert len(pipe.quarantine) == 0  # retries absorbed them
        assert out["image"].shape[0] == 4

    def test_exhausted_transient_errors_substitute_not_quarantine(
        self, tmp_path
    ):
        root = str(tmp_path / "imgs")
        _make_image_folder(root)
        ds = ImageFolderDataset(root)
        first = ds.samples[0][0]
        pipe = _eval_pipe(io_retries=1)
        # this one file fails TRANSIENTLY past its retries: substitute
        # for this batch, but never evict a (probably healthy) sample —
        # a storage blip must not poison the permanent quarantine
        with faults.injected(f"data.fetch:match={os.path.basename(first)}"):
            out = pipe(ds, np.arange(4))
        assert len(pipe.quarantine) == 0
        assert pipe.quarantine.transient_events == 1
        assert out["image"].shape[0] == 4
        # the moment the storage recovers, the sample is back
        out2 = pipe(ds, np.arange(4))
        ref = _eval_pipe()(ds, np.arange(4))
        np.testing.assert_array_equal(out2["image"], ref["image"])

    def test_decode_rot_is_not_retried(self, tmp_path):
        root = str(tmp_path / "imgs")
        _make_image_folder(root)
        ds = ImageFolderDataset(root)
        target = os.path.basename(ds.samples[2][0])
        pipe = _eval_pipe(io_retries=3)
        with faults.injected(f"data.decode:match={target}"):
            pipe(ds, np.arange(4))
            # permanent rot: exactly ONE decode attempt, no retry burn
            assert faults.fire_count("data.decode") == 1
        assert len(pipe.quarantine) == 1

    def test_missing_file_is_permanent_not_transient(self, tmp_path):
        """A file that vanished after indexing (ENOENT) is permanent
        damage: quarantined (budget-counted), never retried/substituted
        forever as if the storage were merely blinking."""
        root = str(tmp_path / "imgs")
        _make_image_folder(root)
        ds = ImageFolderDataset(root)
        gone = ds.samples[0][0]
        os.unlink(gone)
        pipe = _eval_pipe(io_retries=3)
        out = pipe(ds, np.arange(4))
        assert pipe.quarantine.paths == [gone]
        assert pipe.quarantine.transient_events == 0
        assert out["image"].shape[0] == 4

    def test_bad_sample_budget_is_a_hard_stop(self, tmp_path):
        root = str(tmp_path / "imgs")
        _make_image_folder(root)
        ds = ImageFolderDataset(root)
        for path, _ in ds.samples[:3]:
            open(path, "wb").write(b"junk")
        pipe = _eval_pipe(bad_sample_budget=2)
        with pytest.raises(BadSampleBudgetExceeded):
            pipe(ds, np.arange(len(ds)))

    def test_transient_substitutions_have_a_ceiling_too(self, tmp_path):
        """Persistently 'transient' failures (a disk stuck on EIO) must
        eventually be a hard stop — unbounded substitution would quietly
        reshape the training distribution forever."""
        from pytorch_distributed_tpu.data import SampleQuarantine

        q = SampleQuarantine(budget=10, transient_budget=3)
        for i in range(3):
            q.note_transient(f"/s{i}", "EIO")
        with pytest.raises(BadSampleBudgetExceeded, match="persistently"):
            q.note_transient("/s3", "EIO")

    def test_quarantine_shared_across_pipelines(self, tmp_path):
        from pytorch_distributed_tpu.data import SampleQuarantine

        root = str(tmp_path / "imgs")
        _make_image_folder(root)
        ds = ImageFolderDataset(root)
        open(ds.samples[0][0], "wb").write(b"junk")
        q = SampleQuarantine(10)
        a = _eval_pipe(quarantine=q)
        b = _eval_pipe(quarantine=q)
        a(ds, np.arange(2))
        assert len(q) == 1
        b(ds, np.arange(2))  # b skips the known-bad path outright
        assert len(q) == 1


# ---------------------------------------------------------------------------
# watchdog + divergence injection
# ---------------------------------------------------------------------------


class TestWatchdogAttribution:
    def test_stalled_resets_on_tick_and_logs_step(self):
        import logging

        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        # the package logger doesn't propagate to root (rank-0 gated
        # namespace handler), so capture at the module logger directly
        elastic_logger = logging.getLogger(
            "pytorch_distributed_tpu.train.elastic"
        )
        handler = Capture()
        elastic_logger.addHandler(handler)
        try:
            wd = Watchdog(0.15, poll_s=0.03, first_grace_s=0.15)
            with wd:
                wd.tick(41)
                deadline = time.monotonic() + 5
                while not wd.stalled and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert wd.stalled
                assert any("last completed step 41" in m for m in records)
                wd.tick(42)  # satellite: the next successful step re-arms
                assert not wd.stalled
                assert wd.last_step == 42
        finally:
            elastic_logger.removeHandler(handler)


class TestStepNanInjection:
    def test_injected_nan_trips_halt_on_nonfinite(self, tmp_path):
        trainer = _linear_trainer(
            tmp_path, log_every=1, halt_on_nonfinite=2
        )
        with faults.injected("step.nan"):
            with pytest.raises(TrainingDiverged):
                trainer.fit()
        # divergence struck AFTER the first checkpointless steps — the
        # run can restart from scratch; with ckpt_every_steps it would
        # restart from the last finite checkpoint (covered above)
