// hostring: shared-memory multi-process host collectives (Gloo equivalent).
//
// The reference's CPU smoke path runs real multi-process training over the
// gloo process group (BASELINE.json:7); upstream gloo is a C++ rendezvous +
// ring-collectives library. This is the TPU-framework's native equivalent
// for the single-host multi-process case: N OS processes rendezvous over a
// POSIX shared-memory segment and run collectives through per-rank data
// slots guarded by a process-shared sense-reversing barrier.
//
// Algorithm per collective (slot-array exchanges, chunked by slot size):
// copy-shaped collectives (gather/broadcast) are flat —
//   barrier -> each rank writes its contribution to its slot
//   barrier -> each rank reads the slots it needs
//   barrier -> (write-after-read hazard fence before the next collective)
// — while allreduce is a segmented reduce-scatter + allgather (4 barriers
// per chunk; rank r owns segment r, partial publishes — see hr_allreduce).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image). All entry
// points return 0 on success, a negative errno-style code on failure;
// spin-waits carry a deadline so a dead peer fails the job instead of
// hanging it.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x48524E47;  // "HRNG"
constexpr int kErrTimeout = -110;        // -ETIMEDOUT
constexpr int kErrInval = -22;           // -EINVAL
constexpr int kErrSys = -5;              // -EIO

struct Barrier {
  std::atomic<uint32_t> count;
  std::atomic<uint32_t> generation;
};

// Per-(src,dst) SPSC mailbox for true point-to-point transfers. Collectives
// use the barrier-guarded rank slots; P2P must NOT — a barrier needs every
// rank, so barrier-based sendrecv deadlocks any group with bystander ranks.
// Protocol: sender waits seq_send == seq_recv (mailbox free), writes, bumps
// seq_send; receiver waits seq_send > seq_recv, reads, bumps seq_recv.
struct P2PChannel {
  std::atomic<uint64_t> seq_send;
  std::atomic<uint64_t> seq_recv;
};

constexpr size_t kP2PHeaderBytes = 64;  // P2PChannel padded to a cache line
static_assert(sizeof(P2PChannel) <= kP2PHeaderBytes, "p2p header overflow");

// Mailbox payload per ordered pair: 256 KiB capped by a 64 MiB total budget
// so large worlds don't blow up /dev/shm (world^2 channels).
size_t p2p_data_bytes(int world) {
  size_t per = (64ull << 20) / (size_t(world) * size_t(world));
  if (per > (256u << 10)) per = 256u << 10;
  if (per < (4u << 10)) per = 4u << 10;
  return per & ~size_t(63);
}

struct ShmHeader {
  std::atomic<uint32_t> magic;  // kMagic once rank 0 finished initialising
  uint32_t world;
  uint64_t slot_bytes;
  Barrier barrier;
  std::atomic<uint32_t> attached;
  std::atomic<uint32_t> abort_flag;  // a rank died; everyone bails out
};

constexpr size_t kHeaderBytes = 256;  // ShmHeader, padded to cache lines
static_assert(sizeof(ShmHeader) <= kHeaderBytes, "header overflow");

struct Group {
  ShmHeader* hdr;
  uint8_t* slots;  // world * slot_bytes
  uint8_t* p2p;    // world * world * (kP2PHeaderBytes + p2p_bytes)
  size_t map_bytes;
  int rank;
  int world;
  size_t slot_bytes;
  size_t p2p_bytes;  // mailbox payload per channel
  char name[256];
  double timeout_s;
  float* red_scratch = nullptr;  // f32 accumulator for half allreduce
};

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + 1e-9 * double(ts.tv_nsec);
}

// Sense-reversing central barrier. Safe for arbitrary reuse: waiters key on
// the generation counter, the last arrival resets the count and bumps it.
int barrier_wait(Group* g) {
  Barrier* b = &g->hdr->barrier;
  const uint32_t gen = b->generation.load(std::memory_order_acquire);
  if (b->count.fetch_add(1, std::memory_order_acq_rel) + 1 ==
      uint32_t(g->world)) {
    b->count.store(0, std::memory_order_release);
    b->generation.fetch_add(1, std::memory_order_acq_rel);
    return 0;
  }
  const double deadline = now_s() + g->timeout_s;
  while (b->generation.load(std::memory_order_acquire) == gen) {
    if (g->hdr->abort_flag.load(std::memory_order_acquire)) return kErrSys;
    if (now_s() > deadline) {
      g->hdr->abort_flag.store(1, std::memory_order_release);
      return kErrTimeout;
    }
    sched_yield();
  }
  return 0;
}

// U8 is the raw-byte dtype for copy-shaped collectives (gather/broadcast);
// reductions over it are bytewise and only meaningful for MAX/MIN.
// BF16/F16 are the TPU compute dtypes: allreduce ships them at native
// 2-byte bandwidth and accumulates in f32 (NCCL's half-precision design).
enum Dtype : int32_t {
  F32 = 0, F64 = 1, I32 = 2, I64 = 3, U8 = 4, BF16 = 5, F16 = 6
};
// AVG exists so half-precision averaging can divide in f32 BEFORE the
// single rounding (a post-hoc divide of the rounded half sum overflows —
// e.g. f16 world=4 avg of 30000.0). Only hr_allreduce accepts it.
enum Op : int32_t { SUM = 0, PROD = 1, MAX = 2, MIN = 3, AVG = 4 };

size_t dtype_size(int32_t d) {
  switch (d) {
    case F32: case I32: return 4;
    case F64: case I64: return 8;
    case U8: return 1;
    case BF16: case F16: return 2;
    default: return 0;
  }
}

bool is_half(int32_t d) { return d == BF16 || d == F16; }

inline float bf16_to_f32(uint16_t h) {
  uint32_t u = uint32_t(h) << 16;
  float f;
  memcpy(&f, &u, 4);
  return f;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  u += 0x7fffu + ((u >> 16) & 1);  // round to nearest even
  return uint16_t(u >> 16);
}

// Portable software fp16<->f32 (round-to-nearest-even, subnormals, inf/
// nan) — the _Float16 extension needs GCC>=12 on x86-64 and would fail
// the whole library build on older toolchains.
inline float f16_to_f32(uint16_t h) {
  const uint32_t sign = uint32_t(h & 0x8000) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ff;
  uint32_t u;
  if (exp == 0) {
    if (man == 0) {
      u = sign;  // +-0
    } else {  // subnormal: renormalize
      int shift = 0;
      while (!(man & 0x400)) {
        man <<= 1;
        ++shift;
      }
      man &= 0x3ff;
      u = sign | (uint32_t(127 - 15 - shift + 1) << 23) | (man << 13);
    }
  } else if (exp == 31) {
    u = sign | 0x7f800000u | (man << 13);  // inf / nan
  } else {
    u = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float f;
  memcpy(&f, &u, 4);
  return f;
}

inline uint16_t f32_to_f16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  const uint16_t sign = uint16_t((u >> 16) & 0x8000);
  const uint32_t fexp = (u >> 23) & 0xff;
  uint32_t man = u & 0x7fffff;
  if (fexp == 0xff)  // inf / nan (nan keeps a payload bit set)
    return sign | 0x7c00 | (man ? 0x200 | uint16_t(man >> 13) : 0);
  const int32_t exp = int32_t(fexp) - 127 + 15;
  if (exp >= 31) return sign | 0x7c00;  // overflow -> inf
  if (exp <= 0) {                       // subnormal or zero
    if (exp < -10) return sign;         // underflows to zero
    man |= 0x800000;                    // implicit bit
    const uint32_t shift = uint32_t(14 - exp);  // in [14, 24]
    uint32_t half = man >> shift;
    const uint32_t rem = man & ((1u << shift) - 1);
    const uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half & 1))) ++half;
    return sign | uint16_t(half);
  }
  uint32_t half = (uint32_t(exp) << 10) | (man >> 13);
  const uint32_t rem = man & 0x1fff;
  if (rem > 0x1000 || (rem == 0x1000 && (half & 1)))
    ++half;  // RNE; a mantissa carry bumps the exponent (incl. to inf)
  return sign | uint16_t(half);
}

inline float half_to_f32(uint16_t h, int32_t d) {
  return d == BF16 ? bf16_to_f32(h) : f16_to_f32(h);
}

inline uint16_t f32_to_half(float f, int32_t d) {
  return d == BF16 ? f32_to_bf16(f) : f32_to_f16(f);
}

void combine_f32(float* acc, const uint16_t* src, size_t n, int32_t dtype,
                 int32_t op) {
  switch (op) {
    case AVG:  // accumulate like SUM; hr_allreduce divides pre-rounding
    case SUM:
      for (size_t i = 0; i < n; ++i) acc[i] += half_to_f32(src[i], dtype);
      break;
    case PROD:
      for (size_t i = 0; i < n; ++i) acc[i] *= half_to_f32(src[i], dtype);
      break;
    case MAX:
      for (size_t i = 0; i < n; ++i) {
        const float v = half_to_f32(src[i], dtype);
        acc[i] = acc[i] < v ? v : acc[i];
      }
      break;
    case MIN:
      for (size_t i = 0; i < n; ++i) {
        const float v = half_to_f32(src[i], dtype);
        acc[i] = v < acc[i] ? v : acc[i];
      }
      break;
  }
}

template <typename T>
void combine(T* acc, const T* src, size_t n, int32_t op) {
  switch (op) {
    case AVG:  // accumulate like SUM; the caller divides after
    case SUM:  for (size_t i = 0; i < n; ++i) acc[i] += src[i]; break;
    case PROD: for (size_t i = 0; i < n; ++i) acc[i] *= src[i]; break;
    case MAX:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] < src[i] ? src[i] : acc[i];
      break;
    case MIN:
      for (size_t i = 0; i < n; ++i) acc[i] = src[i] < acc[i] ? src[i] : acc[i];
      break;
  }
}

void combine_dispatch(void* acc, const void* src, size_t n, int32_t dtype,
                      int32_t op) {
  switch (dtype) {
    case F32: combine((float*)acc, (const float*)src, n, op); break;
    case F64: combine((double*)acc, (const double*)src, n, op); break;
    case I32: combine((int32_t*)acc, (const int32_t*)src, n, op); break;
    case I64: combine((int64_t*)acc, (const int64_t*)src, n, op); break;
    case U8: combine((uint8_t*)acc, (const uint8_t*)src, n, op); break;
    case BF16: case F16: {
      // pairwise path (rounds per step) — hr_allreduce's segment reduce
      // uses the single-rounding f32-scratch path instead
      uint16_t* a = (uint16_t*)acc;
      const uint16_t* s = (const uint16_t*)src;
      for (size_t i = 0; i < n; ++i) {
        float x = half_to_f32(a[i], dtype);
        combine_f32(&x, &s[i], 1, dtype, op);
        a[i] = f32_to_half(x, dtype);
      }
      break;
    }
  }
}

uint8_t* slot(Group* g, int rank) { return g->slots + size_t(rank) * g->slot_bytes; }

P2PChannel* p2p_channel(Group* g, int src, int dst) {
  return (P2PChannel*)(g->p2p + (size_t(src) * g->world + dst) *
                                    (kP2PHeaderBytes + g->p2p_bytes));
}

uint8_t* p2p_mailbox(Group* g, int src, int dst) {
  return (uint8_t*)p2p_channel(g, src, dst) + kP2PHeaderBytes;
}

}  // namespace

extern "C" {

// Rendezvous: every rank calls hr_init with the same name/world/slot_bytes.
// Rank 0 creates and sizes the segment; the rest open-retry until the magic
// lands. Returns an opaque handle through *out.
int hr_init(const char* name, int rank, int world, uint64_t slot_bytes,
            double timeout_s, void** out) {
  if (!name || !out || world <= 0 || rank < 0 || rank >= world ||
      slot_bytes == 0)
    return kErrInval;
  const size_t p2p_bytes = p2p_data_bytes(world);
  const size_t map_bytes =
      kHeaderBytes + size_t(world) * slot_bytes +
      size_t(world) * size_t(world) * (kP2PHeaderBytes + p2p_bytes);
  int fd = -1;
  const double deadline = now_s() + timeout_s;
  if (rank == 0) {
    shm_unlink(name);  // stale segment from a crashed prior run
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return -errno;
    if (ftruncate(fd, off_t(map_bytes)) != 0) {
      int e = -errno; close(fd); shm_unlink(name); return e;
    }
  } else {
    for (;;) {
      fd = shm_open(name, O_RDWR, 0600);
      if (fd >= 0) {
        struct stat st;
        if (fstat(fd, &st) == 0 && size_t(st.st_size) >= map_bytes) break;
        close(fd);
        fd = -1;
      }
      if (now_s() > deadline) return kErrTimeout;
      sched_yield();
    }
  }
  void* map = mmap(nullptr, map_bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (map == MAP_FAILED) return -errno;

  Group* g = new Group();
  g->hdr = (ShmHeader*)map;
  g->slots = (uint8_t*)map + kHeaderBytes;
  g->p2p = g->slots + size_t(world) * slot_bytes;
  g->map_bytes = map_bytes;
  g->rank = rank;
  g->world = world;
  g->slot_bytes = slot_bytes;
  g->p2p_bytes = p2p_bytes;  // channel seqnos start 0: fresh O_EXCL
                             // segments are ftruncate-zero-filled
  g->timeout_s = timeout_s;
  strncpy(g->name, name, sizeof(g->name) - 1);
  g->name[sizeof(g->name) - 1] = '\0';

  if (rank == 0) {
    g->hdr->world = uint32_t(world);
    g->hdr->slot_bytes = slot_bytes;
    g->hdr->barrier.count.store(0);
    g->hdr->barrier.generation.store(0);
    g->hdr->attached.store(0);
    g->hdr->abort_flag.store(0);
    g->hdr->magic.store(kMagic, std::memory_order_release);
  } else {
    while (g->hdr->magic.load(std::memory_order_acquire) != kMagic) {
      if (now_s() > deadline) {
        munmap(map, map_bytes);
        delete g;
        return kErrTimeout;
      }
      sched_yield();
    }
    if (g->hdr->world != uint32_t(world) || g->hdr->slot_bytes != slot_bytes) {
      munmap(map, map_bytes);
      delete g;
      return kErrInval;
    }
  }
  g->hdr->attached.fetch_add(1);
  int rc = barrier_wait(g);  // everyone attached before first collective
  if (rc != 0) {
    munmap(map, map_bytes);
    delete g;
    return rc;
  }
  *out = g;
  return 0;
}

int hr_barrier(void* h) { return barrier_wait((Group*)h); }

int hr_rank(void* h) { return ((Group*)h)->rank; }
int hr_world(void* h) { return ((Group*)h)->world; }

// In-place allreduce over `count` elements of `data`, chunked by slot size.
//
// Segmented reduce-scatter + allgather over the slot array: rank r reduces
// only segment r of each chunk (its 1/world share) and republishes the
// reduced segment; everyone then gathers the other owners' segments. Total
// combine work across ranks is (world-1)*n adds — the flat all-ranks-
// combine-everything scheme did world*(world-1)*n — and the dead self-copy
// is gone. On a single-core host (all ranks timeshared) this halves wall
// time; on real multi-core it also fixes the O(world) scaling.
int hr_allreduce(void* h, void* data, uint64_t count, int32_t dtype,
                 int32_t op) {
  Group* g = (Group*)h;
  const size_t esize = dtype_size(dtype);
  if (esize == 0) return kErrInval;
  const size_t chunk_elems = g->slot_bytes / esize;
  if (chunk_elems == 0) return kErrInval;
  // AVG divides in the element domain — only meaningful for floats; the
  // ctypes layer floor-divides integers host-side after a SUM instead
  if (op == AVG && !(dtype == F32 || dtype == F64 || is_half(dtype)))
    return kErrInval;
  if (g->world == 1) return 0;  // identity (avg of one value is itself)
  uint8_t* p = (uint8_t*)data;
  for (uint64_t off = 0; off < count; off += chunk_elems) {
    const size_t n = size_t(count - off < chunk_elems ? count - off : chunk_elems);
    uint8_t* base = p + off * esize;
    const size_t seg = n / size_t(g->world);  // elements per owner segment
    const size_t s0 = size_t(g->rank) * seg;
    const size_t sn = (g->rank == g->world - 1) ? n - s0 : seg;
    int rc = barrier_wait(g);
    if (rc != 0) return rc;
    // publish contribution — EXCEPT our own segment, which only this rank
    // would ever read (it reduces straight out of `base` instead)
    if (s0) memcpy(slot(g, g->rank), base, s0 * esize);
    if (s0 + sn < n)
      memcpy(slot(g, g->rank) + (s0 + sn) * esize, base + (s0 + sn) * esize,
             (n - s0 - sn) * esize);
    rc = barrier_wait(g);
    if (rc != 0) return rc;
    if (sn) {
      // reduce own segment across all ranks into the destination buffer
      // (base already holds our own contribution), then republish it in
      // our slot. Writing slot(rank)[seg rank] is race-free: only this
      // rank ever touches segment `rank` after the publish barrier.
      if (is_half(dtype)) {
        // halves accumulate in an f32 scratch — data ships at 2-byte
        // bandwidth but the sum rounds ONCE, like NCCL's half allreduce
        if (!g->red_scratch) g->red_scratch = new float[g->slot_bytes / 2];
        uint16_t* hbase = (uint16_t*)base;
        float* acc = g->red_scratch;
        for (size_t i = 0; i < sn; ++i)
          acc[i] = half_to_f32(hbase[s0 + i], dtype);
        for (int r = 1; r < g->world; ++r) {
          const int src = (g->rank + r) % g->world;
          combine_f32(acc, (const uint16_t*)slot(g, src) + s0, sn, dtype, op);
        }
        if (op == AVG)  // divide BEFORE the single rounding: a rounded
          for (size_t i = 0; i < sn; ++i)  // half sum can overflow to inf
            acc[i] /= float(g->world);
        for (size_t i = 0; i < sn; ++i)
          hbase[s0 + i] = f32_to_half(acc[i], dtype);
      } else {
        for (int r = 1; r < g->world; ++r) {
          const int src = (g->rank + r) % g->world;
          combine_dispatch(base + s0 * esize, slot(g, src) + s0 * esize, sn,
                           dtype, op);
        }
        if (op == AVG) {
          if (dtype == F32) {
            float* fb = (float*)base + s0;
            for (size_t i = 0; i < sn; ++i) fb[i] /= float(g->world);
          } else {  // F64 (gated above)
            double* db = (double*)base + s0;
            for (size_t i = 0; i < sn; ++i) db[i] /= double(g->world);
          }
        }
      }
      memcpy(slot(g, g->rank) + s0 * esize, base + s0 * esize, sn * esize);
    }
    rc = barrier_wait(g);
    if (rc != 0) return rc;
    // allgather the other owners' reduced segments
    for (int r = 1; r < g->world; ++r) {
      const int owner = (g->rank + r) % g->world;
      const size_t o0 = size_t(owner) * seg;
      const size_t on = (owner == g->world - 1) ? n - o0 : seg;
      if (on)
        memcpy(base + o0 * esize, slot(g, owner) + o0 * esize, on * esize);
    }
    rc = barrier_wait(g);
    if (rc != 0) return rc;
  }
  return 0;
}

// Block-quantized f32 allreduce (EQuARX-style): each rank publishes its
// chunk as int8 with one f32 scale per `block` elements (~4x fewer shm
// bytes), segment owners dequantize+accumulate in f32, requantize the
// reduced segment, and EVERY rank — owner included — takes the
// dequantized requantized value, so results are bit-identical across
// ranks (the DDP lockstep invariant). SUM and AVG only.
//
// Slot layout per chunk of n elems: [int8 q[n]][f32 scales[ceil(n/block)]].
namespace {

constexpr size_t kQBlock = 256;  // elements per quantization scale

size_t q_chunk_elems(size_t slot_bytes) {
  // n (padded to 4) + 4*ceil(n/kQBlock) <= slot_bytes, conservatively
  size_t n = slot_bytes * kQBlock / (kQBlock + 4);
  return n > 8 ? n - 8 : n;
}

// f32 scales live right after the int8 payload, 4-byte aligned (the
// payload length is arbitrary on tail chunks)
float* q_scales(uint8_t* slot_base, size_t n) {
  return (float*)(slot_base + ((n + 3) & ~size_t(3)));
}

void quantize_block(const float* x, size_t n, int8_t* q, float* scale) {
  float amax = 0.f;
  bool bad = false;  // NaN/inf: NaN escapes max-comparisons entirely
  for (size_t i = 0; i < n; ++i) {
    const float a = x[i] < 0 ? -x[i] : x[i];
    if (!(a <= 3.4e38f)) bad = true;  // false for NaN and +inf
    amax = a > amax ? a : amax;
  }
  if (bad) {
    // propagate non-finiteness loudly: the whole block dequantizes to
    // NaN instead of casting NaN to int8 (UB) or silently zeroing
    *scale = __builtin_nanf("");
    memset(q, 1, n);
    return;
  }
  const float s = amax / 127.0f;
  *scale = s;
  if (s == 0.f) {
    memset(q, 0, n);
    return;
  }
  const float inv = 1.0f / s;
  for (size_t i = 0; i < n; ++i) {  // branchless: auto-vectorizes
    float v = x[i] * inv;
    v = v < -127.f ? -127.f : (v > 127.f ? 127.f : v);
    q[i] = (int8_t)(v + __builtin_copysignf(0.5f, v));  // round half away
  }
}

void quantize(const float* x, size_t n, int8_t* q, float* scales) {
  for (size_t off = 0; off < n; off += kQBlock) {
    const size_t b = n - off < kQBlock ? n - off : kQBlock;
    quantize_block(x + off, b, q + off, scales + off / kQBlock);
  }
}

// acc[i] += q[i] * scale(block of i)
void dequant_add(float* acc, const int8_t* q, const float* scales, size_t n) {
  for (size_t off = 0; off < n; off += kQBlock) {
    const size_t b = n - off < kQBlock ? n - off : kQBlock;
    const float s = scales[off / kQBlock];
    for (size_t i = 0; i < b; ++i) acc[off + i] += float(q[off + i]) * s;
  }
}

void dequant_copy(float* dst, const int8_t* q, const float* scales,
                  size_t n) {
  for (size_t off = 0; off < n; off += kQBlock) {
    const size_t b = n - off < kQBlock ? n - off : kQBlock;
    const float s = scales[off / kQBlock];
    for (size_t i = 0; i < b; ++i) dst[off + i] = float(q[off + i]) * s;
  }
}

}  // namespace

// Exported for the TCP transport (runtime/transport.py): its q8 owner
// fold must run the EXACT same instruction sequence as the shm ring's
// dequant_add — the compiler contracts acc += q*s to an FMA here, which
// a numpy two-step (multiply, then add) cannot reproduce bit-for-bit.
// Sharing the compiled kernel makes cross-transport q8 bit-identity a
// property of the build, not of rounding luck.
extern "C" void hr_q8_dequant_add(float* acc, const int8_t* q,
                                  const float* scales, uint64_t n) {
  dequant_add(acc, q, scales, (size_t)n);
}

extern "C" int hr_allreduce_q8(void* h, float* data, uint64_t count,
                               int32_t op) {
  Group* g = (Group*)h;
  if (op != SUM && op != AVG) return kErrInval;
  if (g->world == 1) return 0;
  // chunk cap: the q8 layout fits ~slot_bytes elems, but the reduce
  // scratch (shared with the half-dtype path) holds slot_bytes/2 floats —
  // and a segment can span the whole chunk (small tail chunks), so the
  // chunk must fit the scratch
  size_t chunk_elems = q_chunk_elems(g->slot_bytes);
  if (chunk_elems > g->slot_bytes / 2) chunk_elems = g->slot_bytes / 2;
  if (chunk_elems < kQBlock * size_t(g->world)) return kErrInval;
  if (!g->red_scratch) g->red_scratch = new float[g->slot_bytes / 2];
  for (uint64_t off = 0; off < count; off += chunk_elems) {
    const size_t n =
        size_t(count - off < chunk_elems ? count - off : chunk_elems);
    float* base = data + off;
    // BLOCK-ALIGNED segments: scale blocks then never straddle a segment
    // boundary, so the in-phase "peers read my original data while I
    // overwrite my own reduced segment" accesses touch disjoint q/scale
    // regions. The last rank owns the (possibly unaligned) tail.
    const size_t seg = (n / size_t(g->world)) & ~(kQBlock - 1);
    const size_t s0 = size_t(g->rank) * seg;
    const size_t sn = (g->rank == g->world - 1) ? n - s0 : seg;
    int8_t* myq = (int8_t*)slot(g, g->rank);
    float* myscales = q_scales(slot(g, g->rank), n);
    int rc = barrier_wait(g);
    if (rc != 0) return rc;
    // publish — EXCEPT our own segment: no peer ever reads it (peers
    // read only THEIR segments of our slot), and we reduce our own data
    // straight from `base`. Both sub-ranges start block-aligned.
    quantize(base, s0, myq, myscales);
    if (s0 + sn < n)
      quantize(base + s0 + sn, n - s0 - sn, myq + s0 + sn,
               myscales + (s0 + sn) / kQBlock);
    rc = barrier_wait(g);
    if (rc != 0) return rc;
    if (sn) {
      float* acc = g->red_scratch;
      // own contribution from the exact f32 base, peers dequantized
      memcpy(acc, base + s0, sn * sizeof(float));
      for (int r = 1; r < g->world; ++r) {
        const int src = (g->rank + r) % g->world;
        const int8_t* q = (const int8_t*)slot(g, src);
        const float* sc = q_scales(slot(g, src), n);
        dequant_add(acc, q + s0, sc + s0 / kQBlock, sn);
      }
      if (op == AVG)
        for (size_t i = 0; i < sn; ++i) acc[i] /= float(g->world);
      // requantize the reduced segment over our own published segment
      // (disjoint from everything peers still read this phase), and take
      // the dequantized value ourselves — every rank must see the SAME
      // result (DDP lockstep), so the owner cannot keep its exact f32
      quantize(acc, sn, myq + s0, myscales + s0 / kQBlock);
      dequant_copy(base + s0, myq + s0, myscales + s0 / kQBlock, sn);
    }
    rc = barrier_wait(g);
    if (rc != 0) return rc;
    for (int r = 1; r < g->world; ++r) {
      const int owner = (g->rank + r) % g->world;
      const size_t o0 = size_t(owner) * seg;
      const size_t on = (owner == g->world - 1) ? n - o0 : seg;
      if (!on) continue;
      const int8_t* q = (const int8_t*)slot(g, owner);
      const float* sc = q_scales(slot(g, owner), n);
      dequant_copy(base + o0, q + o0, sc + o0 / kQBlock, on);
    }
    rc = barrier_wait(g);
    if (rc != 0) return rc;
  }
  return 0;
}

// Gather each rank's `count` elements into out[world * count].
int hr_allgather(void* h, const void* in, void* out, uint64_t count,
                 int32_t dtype) {
  Group* g = (Group*)h;
  const size_t esize = dtype_size(dtype);
  if (esize == 0) return kErrInval;
  const size_t chunk_elems = g->slot_bytes / esize;
  if (chunk_elems == 0) return kErrInval;
  const uint8_t* src = (const uint8_t*)in;
  uint8_t* dst = (uint8_t*)out;
  for (uint64_t off = 0; off < count; off += chunk_elems) {
    const size_t n = size_t(count - off < chunk_elems ? count - off : chunk_elems);
    int rc = barrier_wait(g);
    if (rc != 0) return rc;
    memcpy(slot(g, g->rank), src + off * esize, n * esize);
    rc = barrier_wait(g);
    if (rc != 0) return rc;
    for (int r = 0; r < g->world; ++r)
      memcpy(dst + (uint64_t(r) * count + off) * esize, slot(g, r), n * esize);
    rc = barrier_wait(g);
    if (rc != 0) return rc;
  }
  return 0;
}

// Reduce in[world * chunk] across ranks; this rank keeps chunk `rank`.
int hr_reduce_scatter(void* h, const void* in, void* out, uint64_t chunk,
                      int32_t dtype, int32_t op) {
  Group* g = (Group*)h;
  if (op == AVG) return kErrInval;  // AVG divides only in hr_allreduce
  const size_t esize = dtype_size(dtype);
  if (esize == 0) return kErrInval;
  const size_t chunk_elems = g->slot_bytes / esize;
  if (chunk_elems == 0) return kErrInval;
  const uint8_t* src = (const uint8_t*)in;
  uint8_t* dst = (uint8_t*)out;
  // Round r: everyone publishes its contribution TO chunk-owner r; owner
  // combines. world rounds of slot traffic, chunked.
  for (uint64_t off = 0; off < chunk; off += chunk_elems) {
    const size_t n = size_t(chunk - off < chunk_elems ? chunk - off : chunk_elems);
    for (int owner = 0; owner < g->world; ++owner) {
      int rc = barrier_wait(g);
      if (rc != 0) return rc;
      memcpy(slot(g, g->rank),
             src + (uint64_t(owner) * chunk + off) * esize, n * esize);
      rc = barrier_wait(g);
      if (rc != 0) return rc;
      if (owner == g->rank) {
        memcpy(dst + off * esize, slot(g, g->rank), n * esize);
        for (int r = 1; r < g->world; ++r) {
          const int from = (g->rank + r) % g->world;
          combine_dispatch(dst + off * esize, slot(g, from), n, dtype, op);
        }
      }
      rc = barrier_wait(g);
      if (rc != 0) return rc;
    }
  }
  return 0;
}

// In-place broadcast of `bytes` from rank `src` to everyone.
int hr_broadcast(void* h, void* data, uint64_t bytes, int32_t src) {
  Group* g = (Group*)h;
  if (src < 0 || src >= g->world) return kErrInval;
  uint8_t* p = (uint8_t*)data;
  for (uint64_t off = 0; off < bytes; off += g->slot_bytes) {
    const size_t n =
        size_t(bytes - off < g->slot_bytes ? bytes - off : g->slot_bytes);
    int rc = barrier_wait(g);
    if (rc != 0) return rc;
    if (g->rank == src) memcpy(slot(g, src), p + off, n);
    rc = barrier_wait(g);
    if (rc != 0) return rc;
    if (g->rank != src) memcpy(p + off, slot(g, src), n);
    rc = barrier_wait(g);
    if (rc != 0) return rc;
  }
  return 0;
}

// True point-to-point: send `bytes` from rank src to rank dst through the
// pair's SPSC mailbox. Only src and dst call this — bystander ranks are
// not involved (and calling from one is an error). Concurrent transfers on
// distinct ordered pairs proceed independently; no group barrier anywhere.
int hr_sendrecv(void* h, void* data, uint64_t bytes, int32_t src, int32_t dst) {
  Group* g = (Group*)h;
  if (src < 0 || src >= g->world || dst < 0 || dst >= g->world || src == dst)
    return kErrInval;
  if (g->rank != src && g->rank != dst) return kErrInval;
  P2PChannel* ch = p2p_channel(g, src, dst);
  uint8_t* mbox = p2p_mailbox(g, src, dst);
  uint8_t* p = (uint8_t*)data;
  const double deadline = now_s() + g->timeout_s;
  for (uint64_t off = 0; off < bytes; off += g->p2p_bytes) {
    const size_t n =
        size_t(bytes - off < g->p2p_bytes ? bytes - off : g->p2p_bytes);
    if (g->rank == src) {
      const uint64_t s = ch->seq_send.load(std::memory_order_acquire);
      while (ch->seq_recv.load(std::memory_order_acquire) != s) {
        if (g->hdr->abort_flag.load(std::memory_order_acquire)) return kErrSys;
        if (now_s() > deadline) {
          g->hdr->abort_flag.store(1, std::memory_order_release);
          return kErrTimeout;
        }
        sched_yield();
      }
      memcpy(mbox, p + off, n);
      ch->seq_send.store(s + 1, std::memory_order_release);
    } else {
      const uint64_t r = ch->seq_recv.load(std::memory_order_acquire);
      while (ch->seq_send.load(std::memory_order_acquire) == r) {
        if (g->hdr->abort_flag.load(std::memory_order_acquire)) return kErrSys;
        if (now_s() > deadline) {
          g->hdr->abort_flag.store(1, std::memory_order_release);
          return kErrTimeout;
        }
        sched_yield();
      }
      memcpy(p + off, mbox, n);
      ch->seq_recv.store(r + 1, std::memory_order_release);
    }
  }
  return 0;
}

int hr_finalize(void* h) {
  Group* g = (Group*)h;
  // Best-effort exit barrier so nobody unlinks a segment in active use; a
  // timed-out peer just falls through to cleanup.
  barrier_wait(g);
  const uint32_t left = g->hdr->attached.fetch_sub(1) - 1;
  if (left == 0 || g->rank == 0) shm_unlink(g->name);
  munmap((void*)g->hdr, g->map_bytes);
  delete[] g->red_scratch;
  delete g;
  return 0;
}

}  // extern "C"
