// Byte-level BPE tokenizer: train + encode, GIL-free.
//
// The reference's LM recipes lean on Hugging Face tokenizers (Rust) for
// corpus preparation; this is the framework's native equivalent for the
// TPU host: byte-level BPE (no pre-tokenization — every byte is a base
// token, merges learned greedily by pair frequency), exposed through a
// minimal C ABI consumed by ctypes (data/tokenizer.py).
//
// Determinism: ties on pair frequency break toward the smaller (left,
// right) pair, so training is reproducible across runs and platforms.
//
// Complexity: training re-counts pairs each merge over the current token
// stream — O(merges * corpus). Fine for the multi-MB corpora recipes
// prepare on-host; encode is the classic lowest-rank-merge loop per
// chunk with a linked-list so each merge is O(chunk).

#include <cstdint>
#include <cstring>
#include <map>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

using Pair = std::pair<int32_t, int32_t>;

struct PairHash {
  size_t operator()(const Pair& p) const {
    return (static_cast<size_t>(static_cast<uint32_t>(p.first)) << 32) ^
           static_cast<uint32_t>(p.second);
  }
};

}  // namespace

extern "C" {

// Train merges on a byte corpus. merges_out receives num_merges (left,
// right) int32 pairs: merge i produces token id 256 + i.
// Returns the number of merges actually learned (< num_merges when the
// corpus runs out of repeating pairs), or -1 on bad args.
int64_t bpe_train(const uint8_t* corpus, int64_t n, int64_t num_merges,
                  int32_t* merges_out) {
  if (!corpus || n < 2 || num_merges < 0 || !merges_out) return -1;
  std::vector<int32_t> toks(corpus, corpus + n);
  int64_t learned = 0;
  std::vector<int32_t> next;
  next.reserve(toks.size());
  for (; learned < num_merges; ++learned) {
    std::unordered_map<Pair, int64_t, PairHash> counts;
    counts.reserve(toks.size() / 2);
    for (size_t i = 0; i + 1 < toks.size(); ++i) {
      ++counts[{toks[i], toks[i + 1]}];
    }
    Pair best{-1, -1};
    int64_t best_count = 1;  // a pair must appear at least twice
    for (const auto& [pair, count] : counts) {
      if (count > best_count ||
          (count == best_count && best.first >= 0 && pair < best)) {
        best = pair;
        best_count = count;
      }
    }
    if (best.first < 0) break;
    const int32_t new_id = static_cast<int32_t>(256 + learned);
    merges_out[2 * learned] = best.first;
    merges_out[2 * learned + 1] = best.second;
    next.clear();
    for (size_t i = 0; i < toks.size();) {
      if (i + 1 < toks.size() && toks[i] == best.first &&
          toks[i + 1] == best.second) {
        next.push_back(new_id);
        i += 2;
      } else {
        next.push_back(toks[i]);
        ++i;
      }
    }
    toks.swap(next);
    if (toks.size() < 2) { ++learned; break; }
  }
  return learned;
}

// Encode bytes with trained merges. ids_out must hold >= n entries
// (output never exceeds input length). Returns the number of ids
// written, or -1 on bad args.
int64_t bpe_encode(const uint8_t* text, int64_t n, const int32_t* merges,
                   int64_t num_merges, int32_t* ids_out) {
  if (!text || n < 0 || (num_merges > 0 && !merges) || !ids_out) return -1;
  if (n == 0) return 0;
  // rank lookup: pair -> merged id (rank == id order: lower id = earlier
  // merge = higher priority)
  std::unordered_map<Pair, int32_t, PairHash> rank;
  rank.reserve(static_cast<size_t>(num_merges) * 2);
  for (int64_t i = 0; i < num_merges; ++i) {
    rank[{merges[2 * i], merges[2 * i + 1]}] =
        static_cast<int32_t>(256 + i);
  }
  // linked list over the token buffer so merges are O(1) splices
  std::vector<int32_t> tok(text, text + n);
  std::vector<int64_t> nxt(n), prv(n);
  for (int64_t i = 0; i < n; ++i) { nxt[i] = i + 1; prv[i] = i - 1; }
  // ordered worklist of candidate merges keyed by (merged id, position):
  // always apply the earliest-learned merge first — BPE's definition
  std::map<std::pair<int32_t, int64_t>, Pair> work;
  auto consider = [&](int64_t i) {
    const int64_t j = nxt[i];
    if (i < 0 || j >= n) return;
    auto it = rank.find({tok[i], tok[j]});
    if (it != rank.end()) work[{it->second, i}] = {tok[i], tok[j]};
  };
  for (int64_t i = 0; i + 1 < n; ++i) consider(i);
  while (!work.empty()) {
    const auto entry = *work.begin();
    work.erase(work.begin());
    const int64_t i = entry.first.second;
    const int64_t j = nxt[i];
    // stale entry? (either side already merged away)
    if (j >= n || tok[i] != entry.second.first ||
        tok[j] != entry.second.second) {
      continue;
    }
    tok[i] = entry.first.first;  // the merged id
    nxt[i] = nxt[j];
    if (nxt[j] < n) prv[nxt[j]] = i;
    tok[j] = -1;
    if (prv[i] >= 0) consider(prv[i]);  // re-examine both new neighbors
    consider(i);
  }
  int64_t m = 0;
  for (int64_t i = 0; i >= 0 && i < n; i = nxt[i]) ids_out[m++] = tok[i];
  return m;
}

// Decode ids back to bytes. out must hold >= max_out bytes; returns
// bytes written or -1 (bad args / id out of range / overflow).
int64_t bpe_decode(const int32_t* ids, int64_t n, const int32_t* merges,
                   int64_t num_merges, uint8_t* out, int64_t max_out) {
  if (!ids || n < 0 || (num_merges > 0 && !merges) || !out) return -1;
  // expand each id depth-first over its merge tree
  int64_t m = 0;
  std::vector<int32_t> stack;
  for (int64_t i = 0; i < n; ++i) {
    stack.push_back(ids[i]);
    while (!stack.empty()) {
      const int32_t t = stack.back();
      stack.pop_back();
      if (t < 0 || t >= 256 + num_merges) return -1;
      if (t < 256) {
        if (m >= max_out) return -1;
        out[m++] = static_cast<uint8_t>(t);
      } else {
        const int64_t k = t - 256;
        stack.push_back(merges[2 * k + 1]);  // right after left (stack)
        stack.push_back(merges[2 * k]);
      }
    }
  }
  return m;
}

}  // extern "C"
