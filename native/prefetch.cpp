// prefetch: native data-loader kernels (threaded gather + image pipeline).
//
// The reference's input pipeline leans on torch DataLoader worker
// *processes* plus torchvision's C++ image ops; the host-side equivalent
// here is a GIL-free, multithreaded batch assembler: gather rows of a
// (possibly memmapped) dataset array and, for images, fuse
// crop -> horizontal flip -> u8->f32 normalize into one pass over the
// pixels. ctypes releases the GIL for the whole call, so worker threads
// scale with host cores — the property that matters for feeding an
// ImageNet-rate TPU from the host (SURVEY.md §7 hard part b).
//
// Augmentation *parameters* (crop offsets, flip flags) are produced by the
// caller: randomness stays in Python where it is seeded/reproducible, the
// pixel work stays here.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kErrInval = -22;

int clamp_threads(int want, int64_t items) {
  unsigned hw = std::thread::hardware_concurrency();
  int t = want > 0 ? want : int(hw ? hw : 1);
  t = std::min<int64_t>(t, items > 0 ? items : 1);
  return std::max(t, 1);
}

// reverse a row's pixels (horizontal flip), keeping channels in order
inline void reverse_pixels(const uint8_t* row, uint8_t* dst, int outW,
                           int C) {
  for (int x = 0; x < outW; ++x)
    memcpy(dst + size_t(x) * C, row + size_t(outW - 1 - x) * C, C);
}

template <typename Fn>
void parallel_for(int64_t n, int num_threads, Fn&& fn) {
  const int t = clamp_threads(num_threads, n);
  if (t == 1) {
    fn(int64_t{0}, n);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(t);
  const int64_t chunk = (n + t - 1) / t;
  for (int i = 0; i < t; ++i) {
    const int64_t lo = i * chunk;
    const int64_t hi = std::min<int64_t>(lo + chunk, n);
    if (lo >= hi) break;
    workers.emplace_back([&fn, lo, hi] { fn(lo, hi); });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

// Gather rows: out[i, :] = src[indices[i], :] for fixed-size rows.
int pf_gather_rows(const void* src, uint64_t row_bytes, int64_t n_src,
                   const int64_t* indices, int64_t n, void* out,
                   int num_threads) {
  if (!src || !indices || !out || row_bytes == 0) return kErrInval;
  for (int64_t i = 0; i < n; ++i)
    if (indices[i] < 0 || indices[i] >= n_src) return kErrInval;
  const uint8_t* s = (const uint8_t*)src;
  uint8_t* d = (uint8_t*)out;
  parallel_for(n, num_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i)
      memcpy(d + uint64_t(i) * row_bytes,
             s + uint64_t(indices[i]) * row_bytes, row_bytes);
  });
  return 0;
}

// Fused image batch assembly:
//   for sample i:  src[indices[i]] (u8, H x W x C, row-major)
//     -> crop outH x outW at (crop_y[i], crop_x[i])
//     -> optional horizontal flip (flip[i])
//     -> f32 normalize: (px/255 - mean[c]) * stdinv[c]
// Caller guarantees 0 <= crop_y <= H-outH and 0 <= crop_x <= W-outW.
int pf_image_batch(const uint8_t* src, int64_t n_src, int H, int W, int C,
                   const int64_t* indices, int64_t n,
                   const int32_t* crop_y, const int32_t* crop_x,
                   const uint8_t* flip, const float* mean,
                   const float* stdinv, float* out, int outH, int outW,
                   int num_threads) {
  if (!src || !indices || !out || !mean || !stdinv) return kErrInval;
  if (outH <= 0 || outW <= 0 || outH > H || outW > W || C <= 0 || C > 16)
    return kErrInval;
  for (int64_t i = 0; i < n; ++i) {
    if (indices[i] < 0 || indices[i] >= n_src) return kErrInval;
    if (crop_y && (crop_y[i] < 0 || crop_y[i] > H - outH)) return kErrInval;
    if (crop_x && (crop_x[i] < 0 || crop_x[i] > W - outW)) return kErrInval;
  }
  const uint64_t src_img = uint64_t(H) * W * C;
  const uint64_t out_img = uint64_t(outH) * outW * C;
  // Row-shaped constant tiles: scale_row[x*C+c] = stdinv[c]/255,
  // bias_row[x*C+c] = -mean[c]*stdinv[c]. The normalize then becomes a
  // pure elementwise u8->FMA pass the compiler vectorizes — a per-pixel
  // 256-entry LUT gather cannot be (measured ~2x slower, and worse on
  // cache-cold sources where the dependent loads stall the prefetcher).
  const int rowN = outW * C;
  std::vector<float> scale_row(rowN), bias_row(rowN);
  for (int x = 0; x < outW; ++x)
    for (int c = 0; c < C; ++c) {
      scale_row[size_t(x) * C + c] = stdinv[c] / 255.0f;
      bias_row[size_t(x) * C + c] = -mean[c] * stdinv[c];
    }

  parallel_for(n, num_threads, [&](int64_t lo, int64_t hi) {
    std::vector<uint8_t> rev(rowN);  // per-thread flip scratch
    const float* sc = scale_row.data();
    const float* bs = bias_row.data();
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* img = src + uint64_t(indices[i]) * src_img;
      float* dst = out + uint64_t(i) * out_img;
      const int cy = crop_y ? crop_y[i] : (H - outH) / 2;
      const int cx = crop_x ? crop_x[i] : (W - outW) / 2;
      const bool fl = flip && flip[i];
      for (int y = 0; y < outH; ++y) {
        const uint8_t* row = img + (uint64_t(cy + y) * W + cx) * C;
        float* drow = dst + uint64_t(y) * rowN;
        const uint8_t* srow = row;
        if (fl) {  // reverse pixels (u8, cheap) then normalize wide
          reverse_pixels(row, rev.data(), outW, C);
          srow = rev.data();
        }
        for (int k = 0; k < rowN; ++k)
          drow[k] = float(srow[k]) * sc[k] + bs[k];
      }
    }
  });
  return 0;
}

// u8-output variant of pf_image_batch: same gather/crop/flip pass but NO
// normalization — the batch ships to the accelerator as uint8 (1/4 the
// host->device bytes of f32) and the (px/255 - mean) * stdinv arithmetic
// runs on-device, where XLA fuses it into the first conv.
int pf_image_batch_u8(const uint8_t* src, int64_t n_src, int H, int W,
                      int C, const int64_t* indices, int64_t n,
                      const int32_t* crop_y, const int32_t* crop_x,
                      const uint8_t* flip, uint8_t* out, int outH, int outW,
                      int num_threads) {
  if (!src || !indices || !out) return kErrInval;
  if (outH <= 0 || outW <= 0 || outH > H || outW > W || C <= 0 || C > 16)
    return kErrInval;
  for (int64_t i = 0; i < n; ++i) {
    if (indices[i] < 0 || indices[i] >= n_src) return kErrInval;
    if (crop_y && (crop_y[i] < 0 || crop_y[i] > H - outH)) return kErrInval;
    if (crop_x && (crop_x[i] < 0 || crop_x[i] > W - outW)) return kErrInval;
  }
  const uint64_t src_img = uint64_t(H) * W * C;
  const uint64_t out_img = uint64_t(outH) * outW * C;
  const uint64_t row_bytes = uint64_t(outW) * C;
  parallel_for(n, num_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* img = src + uint64_t(indices[i]) * src_img;
      uint8_t* dst = out + uint64_t(i) * out_img;
      const int cy = crop_y ? crop_y[i] : (H - outH) / 2;
      const int cx = crop_x ? crop_x[i] : (W - outW) / 2;
      const bool fl = flip && flip[i];
      for (int y = 0; y < outH; ++y) {
        const uint8_t* row = img + (uint64_t(cy + y) * W + cx) * C;
        uint8_t* drow = dst + uint64_t(y) * row_bytes;
        if (!fl) {
          memcpy(drow, row, row_bytes);
        } else {
          reverse_pixels(row, drow, outW, C);
        }
      }
    }
  });
  return 0;
}

}  // extern "C"
