"""serve/ — continuous-batching inference engine (slot-based KV cache).

The online counterpart of ``generation.generate``: requests arrive,
start, and retire independently while ONE compiled decode step serves
every mix of in-flight work (docs/DESIGN.md §11). Quickstart::

    from pytorch_distributed_tpu.serve import (
        EngineConfig, Request, ServeEngine,
    )

    engine = ServeEngine(model, params, EngineConfig(num_slots=4,
                                                     max_len=256))
    h = engine.submit(Request(prompt_ids, max_new_tokens=64,
                              temperature=0.8, top_p=0.95, seed=7))
    engine.run_until_drained()
    print(h.tokens)   # bit-identical to the solo generate() call
"""

from pytorch_distributed_tpu.serve.engine import EngineConfig, ServeEngine
from pytorch_distributed_tpu.serve.loadgen import (
    drive,
    uniform_arrivals,
    warm_up,
)
from pytorch_distributed_tpu.serve.kv_slots import (
    KVSlotPool,
    init_slot_cache,
    put_slot,
    take_slot,
)
from pytorch_distributed_tpu.serve.sampling import (
    filter_logits_rows,
    sample_logits_rows,
)
from pytorch_distributed_tpu.serve.scheduler import (
    PrefillChunk,
    Request,
    RequestHandle,
    RequestStatus,
    Scheduler,
)
from pytorch_distributed_tpu.serve.telemetry import ServeTelemetry

__all__ = [
    "EngineConfig",
    "KVSlotPool",
    "PrefillChunk",
    "Request",
    "RequestHandle",
    "RequestStatus",
    "Scheduler",
    "ServeEngine",
    "ServeTelemetry",
    "drive",
    "filter_logits_rows",
    "init_slot_cache",
    "put_slot",
    "sample_logits_rows",
    "take_slot",
    "uniform_arrivals",
    "warm_up",
]
