"""serve/ — continuous-batching inference engine (paged KV pool).

The online counterpart of ``generation.generate``: requests arrive,
start, and retire independently while ONE compiled decode step serves
every mix of in-flight work (docs/DESIGN.md §11, §16). KV memory is a
page pool: requests hold page tables, identical prompt prefixes share
pages copy-free via refcounts, and ``SpecConfig`` folds draft-verify
speculative decoding into the engine tick. Quickstart::

    from pytorch_distributed_tpu.serve import (
        EngineConfig, Request, ServeEngine, SpecConfig,
    )

    engine = ServeEngine(model, params, EngineConfig(num_slots=4,
                                                     max_len=256))
    h = engine.submit(Request(prompt_ids, max_new_tokens=64,
                              temperature=0.8, top_p=0.95, seed=7))
    engine.run_until_drained()
    print(h.tokens)   # bit-identical to the solo generate() call

    # speculative decoding: 1..k+1 tokens per tick, greedy streams
    # still bit-identical to the target's own generate()
    engine = ServeEngine(model, params, cfg,
                         spec=SpecConfig(draft_model, draft_params,
                                         num_draft_tokens=4))

    # r18 — disaggregated fleet: prefill tier fills pages and ships
    # them (ring KV migration), decode tier owns the tick, a
    # deterministic Router balances on streamed telemetry, and an
    # InProcPrefixStore prefills shared prompts once per FLEET
    from pytorch_distributed_tpu.serve import Router, InProcPrefixStore
    store = InProcPrefixStore()
    router = Router(
        prefill=[ServeEngine(model, params,
                             EngineConfig(role="prefill",
                                          engine_id=f"p{i}"),
                             prefix_store=store) for i in range(2)],
        decode=[ServeEngine(model, params,
                            EngineConfig(role="decode",
                                         engine_id=f"d{i}"))
                for i in range(2)],
        store=store)
    router.warm_up(prompt_ids)
    h = router.submit(Request(prompt_ids, max_new_tokens=64))
    router.run_until_drained()   # same stream a solo engine emits
"""

from pytorch_distributed_tpu.serve.disagg import (
    MigrationError,
    MigrationFrame,
    decode_frame,
    encode_frame,
    recv_frame,
    roundtrip_frame,
    send_frame,
)
from pytorch_distributed_tpu.serve.engine import (
    EngineConfig,
    ServeEngine,
    SpecConfig,
)
from pytorch_distributed_tpu.serve.prefix_store import (
    InProcPrefixStore,
    PrefixStore,
)
from pytorch_distributed_tpu.serve.router import (
    GaugeBoard,
    Router,
    RouterHandle,
)
from pytorch_distributed_tpu.serve.loadgen import (
    drive,
    prefix_shared_requests,
    uniform_arrivals,
    warm_up,
)
from pytorch_distributed_tpu.serve.kv_slots import (
    PagedKVPool,
    SlotLease,
    auto_page_size,
    extract_frames,
    frame_f32_nbytes,
    frame_nbytes,
    frame_signature,
    gather_pages,
    init_page_cache,
    scatter_kv,
    splice_frames,
)
from pytorch_distributed_tpu.serve.sampling import (
    filter_logits_rows,
    sample_logits_rows,
)
from pytorch_distributed_tpu.serve.scheduler import (
    PrefillChunk,
    Request,
    RequestHandle,
    RequestStatus,
    Scheduler,
)
from pytorch_distributed_tpu.serve.telemetry import ServeTelemetry

__all__ = [
    "EngineConfig",
    "GaugeBoard",
    "InProcPrefixStore",
    "MigrationError",
    "MigrationFrame",
    "PagedKVPool",
    "PrefillChunk",
    "PrefixStore",
    "Request",
    "RequestHandle",
    "RequestStatus",
    "Router",
    "RouterHandle",
    "Scheduler",
    "ServeEngine",
    "ServeTelemetry",
    "SlotLease",
    "SpecConfig",
    "auto_page_size",
    "decode_frame",
    "drive",
    "encode_frame",
    "extract_frames",
    "filter_logits_rows",
    "frame_f32_nbytes",
    "frame_nbytes",
    "frame_signature",
    "gather_pages",
    "init_page_cache",
    "prefix_shared_requests",
    "recv_frame",
    "roundtrip_frame",
    "sample_logits_rows",
    "scatter_kv",
    "send_frame",
    "splice_frames",
    "uniform_arrivals",
    "warm_up",
]
