"""Cross-engine prefix registry: prefill a hot prompt once per FLEET.

The r11 pool registry (``kv_slots.PagedKVPool._registry``) is
per-engine: a popular system prompt behind a router is prefilled once
per engine, and dies with the engine. This module externalizes the
registry behind a small store protocol: keys are the pool's own
chain-hash page keys (prefix identity — tokens AND position — not mere
page content, so a hit is bit-exact by the same argument local sharing
is), values are the page's canonical frame bytes in the
``kv_slots.extract_frames`` codec.

Flow (wired in ``serve/engine.py``):

* **publish** — when a prefill finishes, the engine pushes every full
  prompt page the store doesn't already hold (first writer wins; a
  racing duplicate is dropped, mirroring ``register_prefix``). A shared
  prefix is therefore prefilled exactly once per fleet — the bench pins
  ``puts`` as the proof.
* **adopt** — before admitting a queued request, the engine walks its
  chain keys: local registry hit -> nothing to do; store hit -> claim a
  free page (``pool.adopt_page``), splice the store's bytes in, and the
  normal ``allocate`` path shares it copy-free. Adoption stops at the
  first miss (chain contiguity).

Refcounts survive engine churn by design: entries are pinned by HOLDER
(an engine id), and the ROUTER — not the engine — releases a holder's
pins when it retires or loses the engine (``release_holder``). A pinned
entry is never evicted; an unpinned one lives until capacity pressure
reaps it LRU-first. An engine that dies mid-request thus cannot strand
or free fleet state: its pins outlive it exactly until the router
declares it gone.

Honest limits: the reference store is in-process (one router's fleet —
the single-router scope DESIGN.md §23 documents); a networked store
implements the same four methods. Staleness window: an entry evicted
between an engine's lookup and its splice is a missed optimization,
never a correctness hazard — the engine falls back to prefilling the
pages itself.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional, Set

import numpy as np

from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PrefixStore:
    """The store protocol: what engines and the router call.

    Any implementation must keep ``get``/``put`` idempotent and
    first-writer-wins: a key's payload is immutable once stored (chain
    keys commit to tokens and page size, so two honest writers can only
    ever offer identical bytes).
    """

    def get(self, key: bytes, holder: Optional[str] = None):
        raise NotImplementedError

    def put(self, key: bytes, payload, holder: Optional[str] = None):
        raise NotImplementedError

    def release_holder(self, holder: str) -> int:
        raise NotImplementedError

    def stats(self) -> Dict[str, int]:
        raise NotImplementedError


class InProcPrefixStore(PrefixStore):
    """Reference in-process store: LRU + holder pins + counters.

    ``capacity_pages`` bounds resident entries (None = unbounded);
    eviction is LRU over UNPINNED entries only. ``signature``, when
    set, is the fleet's ``kv_slots.frame_signature`` — a put or get
    under a different signature raises, catching a mixed-geometry
    fleet at the store boundary instead of as a corrupt splice.
    """

    def __init__(self, capacity_pages: Optional[int] = None,
                 signature: Optional[str] = None):
        if capacity_pages is not None and capacity_pages < 1:
            raise ValueError(
                f"capacity_pages must be >= 1, got {capacity_pages}"
            )
        self.capacity_pages = capacity_pages
        self.signature = signature
        self._entries: "OrderedDict[bytes, np.ndarray]" = OrderedDict()
        self._holders: Dict[bytes, Set[str]] = {}
        self.puts = 0          # payloads actually stored (dups excluded)
        self.dup_puts = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def _check_signature(self, signature: Optional[str]) -> None:
        if (
            signature is not None and self.signature is not None
            and signature != self.signature
        ):
            raise ValueError(
                "prefix-store geometry mismatch: store holds "
                f"{self.signature!r}, caller offers {signature!r} — "
                "a splice across these would corrupt pages "
                "(set PTD_DISTRIBUTED_DEBUG=DETAIL on the engines for "
                "the full frame layouts)"
            )

    def get(self, key: bytes, holder: Optional[str] = None,
            signature: Optional[str] = None) -> Optional[np.ndarray]:
        """Payload for ``key`` (None on miss). ``holder`` pins the
        entry against eviction until ``release_holder(holder)``."""
        self._check_signature(signature)
        payload = self._entries.get(key)
        if payload is None:
            self.misses += 1
            return None
        self.hits += 1
        self._entries.move_to_end(key)
        if holder is not None:
            self._holders.setdefault(key, set()).add(holder)
        return payload

    def put(self, key: bytes, payload, holder: Optional[str] = None,
            signature: Optional[str] = None) -> bool:
        """Store ``key`` -> frame bytes. Returns True when the payload
        was actually stored (False = already present: first writer
        stays canonical, the duplicate is dropped unread)."""
        self._check_signature(signature)
        if key in self._entries:
            self.dup_puts += 1
            self._entries.move_to_end(key)
            if holder is not None:
                self._holders.setdefault(key, set()).add(holder)
            return False
        while (
            self.capacity_pages is not None
            and len(self._entries) >= self.capacity_pages
        ):
            if not self._evict_one():
                logger.warning(
                    "prefix store full (%d pages) with every entry "
                    "pinned — dropping put instead of evicting live "
                    "state", len(self._entries),
                )
                return False
        arr = np.frombuffer(
            np.ascontiguousarray(payload, np.uint8).tobytes(), np.uint8
        )
        self._entries[key] = arr
        if holder is not None:
            self._holders.setdefault(key, set()).add(holder)
        self.puts += 1
        return True

    def _evict_one(self) -> bool:
        for key in self._entries:
            if not self._holders.get(key):
                del self._entries[key]
                self._holders.pop(key, None)
                self.evictions += 1
                return True
        return False

    def release_holder(self, holder: str) -> int:
        """Drop every pin ``holder`` placed — the router's engine-churn
        hook (retired or lost engines). Entries stay resident (their
        bytes remain canonical for the fleet) until capacity pressure
        evicts them; returns how many pins were released."""
        released = 0
        for key, holders in list(self._holders.items()):
            if holder in holders:
                holders.discard(holder)
                released += 1
            if not holders:
                self._holders.pop(key, None)
        return released

    def pinned(self, key: bytes) -> int:
        """How many holders pin ``key`` (0 = evictable)."""
        return len(self._holders.get(key, ()))

    def resident_bytes(self) -> int:
        return sum(int(v.size) for v in self._entries.values())

    def stats(self) -> Dict[str, int]:
        return {
            "entries": len(self._entries),
            "bytes": self.resident_bytes(),
            "puts": self.puts,
            "dup_puts": self.dup_puts,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "pinned": sum(1 for h in self._holders.values() if h),
        }
