"""Deterministic telemetry-driven admission over a fleet of engines.

The router owns a fleet of ``ServeEngine``s — either N solo engines
(load balancing only) or a disaggregated prefill tier + decode tier
(r18's tentpole: prefill engines fill pages and pack
``MigrationFrame``s; the router ships each frame to a decode engine,
which owns the tick from the first token on). One router, one thread,
one clock: every engine is stepped round-robin in sorted engine-id
order inside ``Router.step()``.

**Admission is deterministic.** The router never inspects engine
internals; it routes on a ``GaugeBoard`` fed exclusively by the
engines' own telemetry streams (the MetricsWriter protocol — a
``_BoardWriter`` tee wraps each engine's writer, so the SAME records
that land in the run's JSONL feed the routing decision). The board
state is a pure function of that record stream, the stream is a pure
function of the (seeded) workload, and the pick is a total order —
``min`` over ``(outstanding, occupancy, TTFT-EWMA, engine_id)`` with
the id as the final tiebreak — so a replayed storm routes identically,
request for request. No wall-clock, no randomness, no dict-order
dependence enters the decision.

**Engine loss is evict-and-replay.** The ``serve.engine_loss`` fault
site is checked once per live engine per step (``path`` = engine id, so
``match=`` picks the victim). A lost engine takes its queue, slots,
outbox, and pages with it; the router re-submits every request it owned
FROM SCRATCH on a surviving peer — same ``Request``, same seed, so the
replayed stream is bit-identical to what the victim would have
produced. The client-visible cost is at-least-once token emission (the
``RouterHandle`` rebinds to the fresh engine handle, dropping the
partial stream) — the documented honest limit; the guarantee is that
the FINAL stream matches the no-fault run exactly. Prefix-store pins
held by the victim are released by the ROUTER (``release_holder``), so
fleet-shared pages never strand.

Honest limits (DESIGN.md §23): single-router scope — the board, the
outbox drain, and the loss sweeps assume one router drives the fleet
from one thread; the occupancy gauge is as stale as the engines'
``telemetry_every`` snapshot cadence (staleness skews balance, never
correctness); replay re-anchors a request's deadline at the re-submit.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from pytorch_distributed_tpu.runtime import faults, flightrec
from pytorch_distributed_tpu.serve.disagg import roundtrip_frame
from pytorch_distributed_tpu.serve.scheduler import (
    Request,
    RequestStatus,
)
from pytorch_distributed_tpu.serve.telemetry import ServeTelemetry
from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: statuses a ROUTER-level request can still make progress from —
#: MIGRATED is terminal for a prefill ENGINE but in-flight for the
#: fleet (its frame is in an outbox or already on a decode peer)
_ROUTER_LIVE = (
    RequestStatus.QUEUED, RequestStatus.PREFILLING,
    RequestStatus.DECODING, RequestStatus.MIGRATED,
)


class GaugeBoard:
    """Latest per-engine routing inputs, folded from telemetry records.

    ``outstanding`` counts requests the router placed on an engine that
    have not yet produced a terminal ``event="request"`` record (the
    router increments at placement; the engine's own stream decrements
    — the board never reaches into engine state). ``ttft_ewma_ms`` and
    ``slot_occupancy`` fold the request/snapshot records as they flow.
    """

    def __init__(self, ema: float = 0.3):
        if not 0.0 < ema <= 1.0:
            raise ValueError(f"ema must be in (0, 1], got {ema}")
        self.ema = ema
        self._state: Dict[str, Dict[str, float]] = {}

    def _ensure(self, engine_id: str) -> Dict[str, float]:
        st = self._state.get(engine_id)
        if st is None:
            st = {
                "outstanding": 0, "ttft_ewma_ms": 0.0,
                "slot_occupancy": 0.0, "done": 0,
            }
            self._state[engine_id] = st
        return st

    def note_routed(self, engine_id: str) -> None:
        self._ensure(engine_id)["outstanding"] += 1

    def drop_engine(self, engine_id: str) -> None:
        self._state.pop(engine_id, None)

    def ingest(self, engine_id: str, metrics: Dict) -> None:
        st = self._ensure(engine_id)
        event = metrics.get("event")
        if event == "request":
            st["outstanding"] = max(0, st["outstanding"] - 1)
            st["done"] += 1
            ttft = metrics.get("ttft_ms")
            if ttft is not None:
                st["ttft_ewma_ms"] = (
                    ttft if st["done"] == 1 else
                    (1 - self.ema) * st["ttft_ewma_ms"]
                    + self.ema * ttft
                )
        elif event == "snapshot":
            occ = metrics.get("slot_occupancy")
            if occ is not None:
                st["slot_occupancy"] = float(occ)

    def rank(self, engine_id: str):
        """Total-order routing key: least-loaded first, engine id as
        the deterministic tiebreak."""
        st = self._ensure(engine_id)
        return (
            st["outstanding"], st["slot_occupancy"],
            st["ttft_ewma_ms"], engine_id,
        )

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        return {eid: dict(st) for eid, st in sorted(self._state.items())}

    def reset(self) -> None:
        self._state.clear()


class _BoardWriter:
    """MetricsWriter tee: every record an engine's telemetry writes is
    folded into the router's board AND forwarded to the engine's
    original writer (when one was wired) — one stream, two readers."""

    def __init__(self, board: GaugeBoard, engine_id: str, inner=None):
        self.board = board
        self.engine_id = engine_id
        self.inner = inner

    def write(self, step, metrics, split="train"):
        if split == "serve":
            self.board.ingest(self.engine_id, metrics)
        if self.inner is not None:
            self.inner.write(step, metrics, split=split)


class RouterHandle:
    """Fleet-level view of one request: delegates to whichever engine
    handle currently drives it (rebound at migration and at replay).
    ``tokens``/``status`` always reflect the CURRENT owner — after a
    replay the partial stream restarts (at-least-once emission), and
    the final stream matches the no-fault run bit for bit."""

    def __init__(self, request: Request, handle, engine_id: str):
        self.request = request
        self.current = handle
        self.engine_id = engine_id
        self.submitted_at = handle.submitted_at
        self.replays = 0

    @property
    def request_id(self) -> str:
        return self.request.request_id

    @property
    def tokens(self) -> List[int]:
        return self.current.tokens

    @property
    def status(self) -> RequestStatus:
        return self.current.status

    @property
    def error(self):
        return self.current.error

    @property
    def first_token_at(self):
        return self.current.first_token_at

    @property
    def done(self) -> bool:
        return self.current.status not in _ROUTER_LIVE

    def __repr__(self):  # pragma: no cover - debugging nicety
        return (
            f"RouterHandle({self.request_id}, {self.status.value}, "
            f"on={self.engine_id}, replays={self.replays})"
        )


class Router:
    """One deterministic admission/migration/loss loop over a fleet.

    Two fleet shapes:

    * ``Router(engines=[...])`` — N solo engines; the router only
      balances admissions.
    * ``Router(prefill=[...], decode=[...])`` — disaggregated tiers;
      the router additionally drains every prefill outbox each step and
      ships each frame (through the FULL wire codec —
      ``roundtrip_frame`` — so in-process fleets pay and account the
      identical framing + fingerprint discipline as cross-process ones)
      to the least-loaded decode engine.

    ``writer`` (MetricsWriter protocol, optional) receives the router's
    own records under ``split="serve"``: ``event="migrate"`` (src, dst,
    nbytes per frame) and ``event="replay"`` (lost engine, dst) — the
    obs_report Fleet section's inputs. Engine telemetry writers are
    wrapped in place at construction; the engines' own records keep
    flowing to whatever the caller wired.
    """

    def __init__(
        self,
        engines: Optional[Sequence] = None,
        *,
        prefill: Optional[Sequence] = None,
        decode: Optional[Sequence] = None,
        writer=None,
        store=None,
        ema: float = 0.3,
    ):
        if engines is not None and (prefill or decode):
            raise ValueError(
                "pass either engines= (solo fleet) or prefill=/decode= "
                "(disaggregated tiers), not both"
            )
        if engines is None and not (prefill and decode):
            raise ValueError(
                "a disaggregated fleet needs BOTH prefill= and decode= "
                "engines (a tier with nobody on the other side can "
                "never finish a request)"
            )
        self.disagg = engines is None
        self.board = GaugeBoard(ema=ema)
        self.writer = writer
        self._engines: Dict[str, object] = {}
        self._prefill_ids: List[str] = []
        self._decode_ids: List[str] = []
        self._solo_ids: List[str] = []
        if self.disagg:
            self._adopt_fleet(prefill, "prefill", "p", self._prefill_ids)
            self._adopt_fleet(decode, "decode", "d", self._decode_ids)
        else:
            self._adopt_fleet(engines, "solo", "e", self._solo_ids)
        sigs = {
            e.migration_signature for e in self._engines.values()
        }
        if len(sigs) > 1:
            raise ValueError(
                "mixed-geometry fleet: engines disagree on the frame "
                f"signature ({sorted(s[:40] for s in sigs)}...) — every "
                "engine behind one router must share model geometry, "
                "page size, and cache dtype"
            )
        self._store = store
        if self._store is None:
            for e in self._engines.values():
                if getattr(e, "_store", None) is not None:
                    self._store = e._store
                    break
        self._live: Dict[str, RouterHandle] = {}
        self._events = 0
        self.migration_frames = 0
        self.migration_bytes = 0          # full wire bytes
        self.migration_payload_bytes = 0  # KV page bytes only
        self.replays = 0
        self.lost_engines: List[str] = []

    def _adopt_fleet(self, fleet, role, prefix_char, ids) -> None:
        if not fleet:
            if role == "solo":
                raise ValueError("engines= must hold at least one engine")
            return
        for i, e in enumerate(fleet):
            if e.role != role:
                raise ValueError(
                    f"fleet slot {role}[{i}] holds a role={e.role!r} "
                    f"engine — construct it with "
                    f"EngineConfig(role={role!r})"
                )
            eid = e.engine_id or f"{prefix_char}{i}"
            if eid in self._engines:
                raise ValueError(f"duplicate engine_id {eid!r}")
            e.engine_id = eid
            e.telemetry.engine_id = eid
            # tee the engine's telemetry into the board — the routing
            # decision reads the same stream the run's JSONL records
            e.telemetry.writer = _BoardWriter(
                self.board, eid, e.telemetry.writer
            )
            self._engines[eid] = e
            ids.append(eid)

    # -- routing -----------------------------------------------------------
    def _pick(self, ids: Sequence[str]) -> str:
        if not ids:
            raise RuntimeError(
                "no surviving engine to route to — the fleet lost its "
                "last member of a required tier"
            )
        return min(ids, key=self.board.rank)

    def _emit_record(self, metrics: Dict) -> None:
        if self.writer is not None:
            self._events += 1
            self.writer.write(self._events, metrics, split="serve")

    def submit(self, request: Request) -> RouterHandle:
        """Route one request to the least-loaded admitting engine."""
        eid = self._pick(
            self._prefill_ids if self.disagg else self._solo_ids
        )
        h = self._engines[eid].submit(request)
        rh = RouterHandle(request, h, eid)
        self._live[request.request_id] = rh
        self.board.note_routed(eid)
        return rh

    # -- the loop ----------------------------------------------------------
    def step(self) -> bool:
        """One fleet iteration: loss sweep -> step every engine (sorted
        id order) -> drain prefill outboxes onto decode engines.
        Returns True when any engine did device work."""
        if faults.active():
            for eid in sorted(self._engines):
                try:
                    faults.check("serve.engine_loss", path=eid)
                except faults.InjectedFault as e:
                    self._lose_engine(eid, e)
        did = False
        for eid in sorted(self._engines):
            e = self._engines[eid]
            if e.has_work():
                did = e.step() or did
        if self.disagg:
            self._drain_outboxes()
        return did

    def _drain_outboxes(self) -> None:
        for eid in list(self._prefill_ids):
            e = self._engines[eid]
            while e.outbox:
                frame = e.outbox.popleft()
                dst = self._pick(self._decode_ids)
                target = self._engines[dst]
                # full wire codec even in-process: identical framing,
                # fingerprint check, and byte accounting as a ring hop
                wire_frame, nbytes = roundtrip_frame(
                    frame, target.migration_signature
                )
                rh = self._live.get(frame.request_id)
                h = target.inject_migration(
                    wire_frame,
                    submitted_at=(
                        rh.submitted_at if rh is not None else None
                    ),
                )
                self.migration_frames += 1
                self.migration_bytes += nbytes
                self.migration_payload_bytes += frame.payload_nbytes
                if rh is not None:
                    rh.current = h
                    rh.engine_id = dst
                self.board.note_routed(dst)
                self._emit_record({
                    "event": "migrate", "engine_id": eid, "dst": dst,
                    "request_id": frame.request_id,
                    "nbytes": int(nbytes),
                    "payload_nbytes": int(frame.payload_nbytes),
                    "n_pages": int(frame.n_pages),
                })

    # -- engine loss -------------------------------------------------------
    def _lose_engine(self, eid: str, cause: BaseException) -> None:
        """Evict a lost engine and replay every request it owned on a
        surviving peer — from scratch, same Request + seed, so the
        replayed final stream is bit-identical to the no-fault run."""
        flightrec.dump(f"serve engine {eid} lost: {cause!r}")
        self._engines.pop(eid)
        for ids in (self._prefill_ids, self._decode_ids, self._solo_ids):
            if eid in ids:
                ids.remove(eid)
        self.board.drop_engine(eid)
        self.lost_engines.append(eid)
        if self._store is not None:
            # the ROUTER releases the victim's prefix-store pins — the
            # engine is gone and can never do it itself; entries stay
            # resident for the fleet until capacity pressure
            self._store.release_holder(eid)
        victims = [
            rh for _, rh in sorted(self._live.items())
            if rh.engine_id == eid and not rh.done
        ]
        logger.warning(
            "serve.router: engine %s lost (%s) — replaying %d "
            "in-flight request(s) on surviving peers",
            eid, cause, len(victims),
        )
        for rh in victims:
            dst = self._pick(
                self._prefill_ids if self.disagg else self._solo_ids
            )
            h = self._engines[dst].submit(rh.request)
            rh.current = h
            rh.engine_id = dst
            rh.submitted_at = h.submitted_at
            rh.replays += 1
            self.replays += 1
            self.board.note_routed(dst)
            self._emit_record({
                "event": "replay", "engine_id": eid, "dst": dst,
                "request_id": rh.request_id,
            })

    # -- drive surface (duck-compatible with ServeEngine) ------------------
    def has_work(self) -> bool:
        return any(
            e.has_work() or (e.role == "prefill" and e.outbox)
            for e in self._engines.values()
        )

    def run_until_drained(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if not self.has_work():
                return
            self.step()
        raise RuntimeError(
            f"fleet did not drain within {max_steps} steps"
        )

    def drain(self, max_steps: int = 1_000_000) -> None:
        self.run_until_drained(max_steps)

    # -- warm-up -----------------------------------------------------------
    def warm_up(self, prompt_ids, *, precompile_buckets: bool = True):
        """Compile every engine's programs outside any measured window.

        Solo engines take the standard 2-token warm request; prefill
        engines run one warm prefill to a packed frame, and that SAME
        frame (round-tripped through the codec) warms every decode
        engine's splice + inject + decode programs. Afterwards each
        engine's telemetry is replaced fresh (board included), so
        warm-up TTFTs never reach a reported percentile.
        """
        warm = Request(prompt_ids, max_new_tokens=2, request_id="warmup")
        if self.disagg:
            frame = None
            for eid in self._prefill_ids:
                e = self._engines[eid]
                h = e.submit(Request(
                    prompt_ids, max_new_tokens=2,
                    request_id=f"warmup-{eid}",
                ))
                while e.has_work():
                    e.step()
                if h.status is not RequestStatus.MIGRATED or not e.outbox:
                    raise RuntimeError(
                        f"warm-up prefill on {eid} did not migrate: "
                        f"{h.status.value}"
                    )
                frame = e.outbox.popleft()
            for eid in self._decode_ids:
                e = self._engines[eid]
                wire_frame, _ = roundtrip_frame(
                    frame, e.migration_signature
                )
                h = e.inject_migration(wire_frame)
                while e.has_work():
                    e.step()
                if h.status is not RequestStatus.COMPLETED:
                    raise RuntimeError(
                        f"warm-up decode on {eid} failed: "
                        f"{h.status.value}"
                    )
                if e.decode_compiles < 1:
                    raise RuntimeError(
                        f"warm-up on {eid} drained without a decode "
                        "tick — the compile would land mid-measurement"
                    )
                if precompile_buckets:
                    e.precompile_decode_buckets()
        else:
            for eid in self._solo_ids:
                e = self._engines[eid]
                h = e.submit(Request(
                    prompt_ids, max_new_tokens=2,
                    request_id=f"warmup-{eid}",
                ))
                e.run_until_drained()
                if h.status is not RequestStatus.COMPLETED:
                    raise RuntimeError(
                        f"warm-up on {eid} failed: {h.status.value}"
                    )
                if e.decode_compiles < 1:
                    raise RuntimeError(
                        f"warm-up on {eid} drained without a decode tick"
                    )
                if precompile_buckets:
                    e.precompile_decode_buckets()
        del warm
        # reset measurement state: warm-up records must not bias the
        # board's EWMAs or any reported percentile
        for eid, e in self._engines.items():
            tee = e.telemetry.writer
            e.telemetry = ServeTelemetry(
                writer=tee, clock=e.telemetry.clock, engine_id=eid,
            )
        self.board.reset()
        self.migration_frames = 0
        self.migration_bytes = 0
        self.migration_payload_bytes = 0
        self._live.clear()

    # -- aggregates --------------------------------------------------------
    def summary(self) -> Dict[str, object]:
        per_engine = {
            eid: self._engines[eid].telemetry.summary()
            for eid in sorted(self._engines)
        }
        ttfts = []
        for e in self._engines.values():
            ttfts.extend(e.telemetry.ttfts_s)
        out = {
            "engines": per_engine,
            "migration_frames": self.migration_frames,
            "migration_bytes": self.migration_bytes,
            "migration_payload_bytes": self.migration_payload_bytes,
            "replays": self.replays,
            "lost_engines": list(self.lost_engines),
            "board": self.board.snapshot(),
        }
        if ttfts:
            from pytorch_distributed_tpu.utils.timing import percentile
            for q in (50, 95, 99):
                out[f"ttft_ms_p{q}"] = percentile(ttfts, q) * 1e3
        return out
