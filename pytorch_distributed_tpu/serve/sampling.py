"""Per-row sampling for heterogeneous slot batches.

``generation.sample_logits`` takes ONE static (temperature, top_k,
top_p) per call — correct for offline batches where every row shares
the sampling config, impossible for a slot batch where every row is a
different request. This module is the row-vectorized form: parameters
arrive as ``[B]`` arrays and every row follows exactly the math of
``generation.filter_logits``/``sample_logits`` with that row's values,
so a request's token stream is BIT-IDENTICAL to a solo ``generate``
call with the same seed and params (pinned by tests/test_serve.py).

Exactness notes (why the always-on filter path is a no-op for "off"
rows, bit for bit):

* ``top_k`` off is encoded as ``k = V``: the k-th sorted logit is the
  row minimum, and ``logits < min`` masks nothing.
* ``top_p`` off is encoded as ``inf``: every sorted entry survives
  ``cum_before < inf``, the surviving minimum is the global minimum,
  and ``logits < min`` again masks nothing. (Encoding "off" as 1.0
  would be *almost* right — but an f32 cumsum can overshoot 1.0 and
  drop a tail token a None-filtered ``generate`` would keep.)
* Filters only MASK (set ``-inf``); kept logits are never rewritten,
  so a no-op mask leaves the row bitwise equal to the unfiltered path.
* Greedy rows (``temperature == 0``) take ``argmax`` of the RAW logits
  exactly like ``sample_logits``'s early return; their lane through
  the sampling path divides by a substituted 1.0 (never 0) and the
  result is discarded by the final select.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: per-row encodings of "filter off" — see module docstring
TOP_K_OFF = 0
TOP_P_OFF = jnp.inf


def filter_logits_rows(
    logits: jnp.ndarray,   # [B, V]
    temps: jnp.ndarray,    # [B] f32; rows with 0 are greedy (caller selects)
    top_ks: jnp.ndarray,   # [B] int32; TOP_K_OFF (0) = no k filter
    top_ps: jnp.ndarray,   # [B] f32; TOP_P_OFF (inf) = no p filter
) -> jnp.ndarray:
    """Row-wise ``generation.filter_logits``: scale, k-filter, p-filter."""
    V = logits.shape[-1]
    neg_inf = jnp.finfo(jnp.float32).min
    safe_t = jnp.where(temps > 0, temps, 1.0).astype(jnp.float32)
    l32 = logits.astype(jnp.float32) / safe_t[:, None]
    # one descending sort serves both filters (generation.filter_logits)
    sorted_desc = jnp.sort(l32, axis=-1)[..., ::-1]
    k = jnp.where(top_ks > 0, jnp.minimum(top_ks, V), V).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    l32 = jnp.where(l32 < kth, neg_inf, l32)
    sorted_desc = jnp.where(
        jnp.arange(V)[None, :] < k[:, None], sorted_desc, neg_inf
    )
    # a token survives if the cumulative probability BEFORE it is still
    # < top_p (the top token always survives)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    keep = cum_before < top_ps[:, None]
    thresh = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(l32 < thresh, neg_inf, l32)


def sample_logits_rows(
    logits: jnp.ndarray,    # [B, V]
    subkeys,                # [B] typed rng keys (one consumed per row)
    temps: jnp.ndarray,
    top_ks: jnp.ndarray,
    top_ps: jnp.ndarray,
) -> jnp.ndarray:
    """[B, V] logits -> [B] token ids, each row by its own params/key.

    Greedy rows (``temps == 0``) are ``argmax`` of the raw logits;
    sampling rows draw ``categorical`` from their filtered/scaled
    distribution with their own key — the exact per-row transcript of
    ``generation.sample_logits``.
    """
    filtered = filter_logits_rows(logits, temps, top_ks, top_ps)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row, axis=-1)
    )(subkeys, filtered).astype(jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0, greedy, sampled)
