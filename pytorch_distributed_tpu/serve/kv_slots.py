"""Paged, prefix-shared KV-cache pool — the serving engine's memory system.

The original pool gave every request a monolithic ``[max_len]`` slot:
simple, but each slot pinned ``max_len - actual_len`` dead positions of
HBM forever — fatal at a realistic length mix, where the p50 request is
a fraction of the p99 the pool must be sized for. This rewrite makes the
PAGE the allocation unit:

* **Device storage is one page pool per layer**: each KV leaf is
  ``[..., num_pages + 1, page_size, H, D]`` (page 0 is a reserved null
  page — never allocated, padding for unused page-table entries). Pages
  are position-agnostic frames; which request owns which page, at which
  sequence offset, is host bookkeeping.
* **Requests hold a page table** (``[max_pages]`` int32 per slot) instead
  of a buffer row. The jitted programs gather a request's pages into a
  dense ``[max_len]`` view, run the unchanged model decode contract
  (``write_pos`` per-row writes, per-row causal masks), and scatter ONLY
  the deliberately-written positions back. The persistent pool is
  written by nothing else — free slots and mid-prefill rows no longer
  even write garbage (their scatter indices are dropped), which is a
  strictly stronger invariant than the old "garbage lands where masks
  hide it".
* **Freed pages return to one shared free list** (a min-heap: lowest
  page first, so seeded workloads replay exactly; push/pop is O(log n)
  with tiny constants — measured flat from 64 to 2048 slots in the
  serving bench's admit micro-pin, vs the old allocate's per-call sort).
* **Identical prefixes share pages copy-free via refcounts.** Full
  prompt pages are content-addressed by a chain hash of the token
  prefix; admission walks the registry and maps matching leading pages
  into the new request's table (refcount++, zero bytes copied, zero
  prefill compute), resuming prefill at the first unshared page.
  Copy-on-write discipline is enforced eagerly at admission: a shared
  page is READ-ONLY — the page containing the first divergent (or
  to-be-written) token is always private, so no jitted program can ever
  write a refcount>1 page. The partial boundary page is recomputed by
  the request's own prefill rather than copied (identical bytes either
  way — KV at position p depends only on tokens [0, p]).

Bit-parity story (why sharing cannot change tokens): a shared page holds
exactly the KV this request's own prefill would have produced — same
tokens, same absolute positions, same deterministic program — so the
gathered dense view is bitwise what the unshared engine computed, and
the solo-``generate`` parity suite holds with sharing on.

The static-shape tax, and its round-12 removal: through round 11 every
decode tick gathered the live slots' pages into a transient dense
``[S, max_len]`` view — per-tick read traffic roughly doubled (gather +
attention read) and the transient peak carried a full dense copy. The
default engine (``decode_mode="paged"``) now attends IN PLACE over the
pool (``ops/paged_attention``): new-token K/V lands via per-page
scatters and attention streams the pages, so the remaining dense spans
(chunked prefill's one row, the speculative draft's short context) are
bucket-sliced to the live maximum's power-of-two page width, never
``max_len``. The gather helpers below stay the ``decode_mode="dense"``
baseline path — bench.py's ``serving_paged_attn`` phase measures the
paged tick against it (tokens/s and analytic HBM bytes/token, parity
enforced in-phase). Resident KV is ``pages_in_use × page_size`` either
way (``serving_kv_bytes_ratio`` >= 2x pinned by test_bench_contract).
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import math
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.generation import cache_batch_axis
from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# warn-once dedup for degenerate auto page sizes (the rule-engine's
# replicate-with-warning precedent, autoplan/rules.py)
_warned_page_sizes: set = set()


def reset_page_size_warnings() -> None:
    """Clear the warn-once dedup (tests asserting the warning fires)."""
    _warned_page_sizes.clear()


def auto_page_size(max_len: int, cap: int = 32) -> int:
    """Largest power-of-two divisor of ``max_len``, capped at ``cap``.

    A page must divide ``max_len`` exactly (the dense view is
    ``max_pages * page_size`` wide and the engine equates it with
    ``max_len``); powers of two keep the div/mod in the scatter index
    arithmetic cheap. ``max_len`` odd degenerates to 1-token pages —
    still VALID, but every token becomes its own page: the page table
    is ``max_len`` entries per slot, every allocation/refcount walk is
    per-token, the paged-attention stream pays one page step per
    token, and prefix sharing hashes per token. That cost used to be
    silent; now it warns once per ``max_len`` (the rule engine's
    replicate-with-warning precedent) — pass an even/power-of-two
    ``max_len`` or an explicit ``page_size`` to opt out knowingly.
    """
    ps = math.gcd(max_len, 1 << 30)  # largest power-of-2 divisor
    while ps > cap:
        ps //= 2
    if ps == 1 and max_len > 1 and max_len not in _warned_page_sizes:
        _warned_page_sizes.add(max_len)
        logger.warning(
            "auto_page_size(max_len=%d): odd max_len degenerates to "
            "1-token pages — %d page-table entries per slot, per-token "
            "bookkeeping and page streaming, per-token prefix hashing. "
            "Use an even (ideally power-of-two-divisible) max_len or "
            "pass page_size explicitly.",
            max_len, max_len,
        )
    return ps


def init_page_cache(model, params, num_pages: int, page_size: int):
    """Zeroed page-pool pytree: ``num_pages + 1`` frames of ``page_size``.

    Shapes come from ``jax.eval_shape`` over the model's own decode
    apply (batch = page frames, length = page size), so the pool is
    EXACTLY the leaf set the model mutates — scan layouts, int8 KV
    scale buffers and all — reinterpreted as position-agnostic frames.
    Frame 0 is the reserved null page backing unused page-table entries.
    """

    def shape_fn(p):
        _, state = model.apply(
            {"params": p},
            jnp.zeros((num_pages + 1, 1), jnp.int32),
            decode=True,
            cache_len=page_size,
            mutable=["cache"],
        )
        return state["cache"]

    shapes = jax.eval_shape(shape_fn, params)
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )


def gather_pages(cache, page_tables: jnp.ndarray):
    """Pool pytree + ``[B, max_pages]`` tables -> dense ``[B, T]`` view.

    ``T = max_pages * page_size``. Only KV-payload leaves (those with a
    batch axis per ``generation.cache_batch_axis`` — int8 scale buffers
    included) are gathered; shared counters pass through untouched, as
    in the old per-slot slicing. The result is a valid decode cache for
    ``model.apply`` with per-row ``write_pos``/``positions``.
    """
    B, mp = page_tables.shape
    flat = page_tables.reshape(-1)

    def f(path, x):
        ax = cache_batch_axis(path, x)
        if ax is None:
            return x
        ps = x.shape[ax + 1]
        g = jnp.take(x, flat, axis=ax)
        return g.reshape(x.shape[:ax] + (B, mp * ps) + x.shape[ax + 2:])

    return jax.tree_util.tree_map_with_path(f, cache)


def scatter_kv(cache, dense, page_tables, positions, keep):
    """Write ``positions`` of the dense view back into the page pool.

    ``positions``/``keep`` are ``[B, W]``: for each dense row, the W
    buffer positions whose KV should persist, and a bool gate per
    position (False -> the write is DROPPED, not redirected — the one
    mechanism that keeps free/mid-prefill rows from ever touching the
    pool). Every kept position must land in a page the row privately
    owns — the pool's copy-on-write discipline guarantees it at
    admission, and ``PagedKVPool.check_consistency`` + the shared-page
    checksum test pin it.

    Callers are the engine's jitted programs only (prefill chunk, decode
    tick, speculative verify); the scatter itself is a fused
    ``dynamic_update``-class op inside those compiles.
    """
    B, W = positions.shape

    def f(path, x, d):
        ax = cache_batch_axis(path, x)
        if ax is None:
            return x
        npp, ps = x.shape[ax], x.shape[ax + 1]
        # page-table rows are per dense row; positions beyond the table
        # clamp (jnp.take_along_axis default) — such rows are always
        # keep=False so the clamped garbage index is dropped anyway
        page = jnp.take_along_axis(page_tables, positions // ps, axis=1)
        dst = page * ps + positions % ps                    # [B, W]
        dst = jnp.where(keep, dst, npp * ps)                # OOB -> drop
        idx = positions.reshape((1,) * ax + (B, W, 1, 1))
        upd = jnp.take_along_axis(d, idx, axis=ax + 1)      # [.., B, W, H, D]
        flat = x.reshape(x.shape[:ax] + (npp * ps,) + x.shape[ax + 2:])
        flat = jnp.moveaxis(flat, ax, 0)
        upd = upd.reshape(upd.shape[:ax] + (B * W,) + upd.shape[ax + 2:])
        upd = jnp.moveaxis(upd, ax, 0)
        flat = flat.at[dst.reshape(-1)].set(  # ptdlint: disable=PTD004
            upd.astype(flat.dtype), mode="drop",
        )  # fused scatter: only ever traced inside the engine's jitted
        # programs (cross-module, so the per-module lint closure cannot
        # see the jit wrapping it)
        return jnp.moveaxis(flat, 0, ax).reshape(x.shape)

    return jax.tree_util.tree_map_with_path(f, cache, dense)


def _frame_leaves(cache):
    """(name, batch_axis, leaf) for every KV-payload leaf, in canonical
    tree-flatten order — the ONE iteration order the frame codec (and
    therefore the migration wire format and the prefix store's page
    payloads) is defined over."""
    out = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        ax = cache_batch_axis(path, leaf)
        if ax is not None:
            name = getattr(path[-1], "key", None) or str(path[-1])
            out.append((name, ax, leaf))
    return out


def frame_signature(cache, page_size: int) -> str:
    """Geometry commitment for one page frame: leaf names, per-frame
    shapes and dtypes (in codec order) plus the page size. Two pools
    agree on this string iff ``extract_frames`` bytes from one splice
    losslessly into the other — the DETAIL string the migration
    fingerprint handshake commits to (the ``_verify_p2p`` idiom)."""
    parts = [f"ps={page_size}"]
    for name, ax, leaf in _frame_leaves(cache):
        frame = leaf.shape[:ax] + leaf.shape[ax + 1:]
        parts.append(f"{name}:{frame}:{leaf.dtype}")
    return "|".join(parts)


def frame_nbytes(cache) -> int:
    """Native bytes of ONE page frame across the KV-payload leaves —
    the exact per-page payload size ``extract_frames`` produces (int8
    caches: int8 K/V plus their f32 per-token scale sidecars)."""
    total = 0
    for _, ax, leaf in _frame_leaves(cache):
        elems = leaf.size // leaf.shape[ax]
        total += int(elems) * leaf.dtype.itemsize
    return total


def frame_f32_nbytes(cache) -> int:
    """Bytes ONE page frame would cost with an f32 KV cache: payload
    elements at 4 bytes, no scale sidecars (an f32 cache has none).
    The denominator of the bench's migration-bytes ratio — an int8
    pool's native frames cost ``(1 + 4/D) / 4`` of this."""
    total = 0
    for name, ax, leaf in _frame_leaves(cache):
        if name.endswith("_scale"):
            continue
        total += int(leaf.size // leaf.shape[ax]) * 4
    return total


def extract_frames(cache, pages) -> np.ndarray:
    """Gather whole page frames into one flat ``uint8`` payload.

    Layout is leaf-major in ``_frame_leaves`` order: for each KV-payload
    leaf, the ``len(pages)`` frames' native bytes (C order, native
    dtype — int8 payloads ship as int8, their scale sidecars as f32).
    Verbatim bytes, so a splice on a geometry-identical pool is
    lossless for ANY cache dtype: migration can never change tokens.
    """
    idx = jnp.asarray(np.asarray(pages, np.int32).reshape(-1))
    chunks = []
    for _, ax, leaf in _frame_leaves(cache):
        g = np.asarray(jnp.take(leaf, idx, axis=ax))
        chunks.append(np.ascontiguousarray(g).tobytes())
    return np.frombuffer(b"".join(chunks), np.uint8)


def splice_frames(cache, pages, payload):
    """Inverse of :func:`extract_frames`: write frame bytes into the
    pool at ``pages``. Host-side, once per migrated request (NOT per
    tick — the per-request cost the eager-scatter rule polices is paid
    exactly once per hand-off, priced in the bench's migration
    accounting). Raises when the payload size disagrees with the pool's
    frame geometry — the byte-level half of the fingerprint handshake.
    """
    idx = jnp.asarray(np.asarray(pages, np.int32).reshape(-1))
    n = int(idx.size)
    buf = np.asarray(payload, np.uint8).reshape(-1)
    off = 0

    def f(path, leaf):
        nonlocal off
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            return leaf
        shape = leaf.shape[:ax] + (n,) + leaf.shape[ax + 1:]
        count = int(np.prod(shape, dtype=np.int64)) * leaf.dtype.itemsize
        if off + count > buf.size:
            raise ValueError(
                f"migration payload too short: leaf at {path} needs "
                f"bytes [{off}, {off + count}) of {buf.size}"
            )
        frames = np.ascontiguousarray(buf[off:off + count]).view(
            leaf.dtype
        ).reshape(shape)
        off += count
        m = jnp.moveaxis(leaf, ax, 0)
        m = m.at[idx].set(  # ptdlint: disable=PTD004
            jnp.moveaxis(jnp.asarray(frames), ax, 0)
        )  # once per migrated request (bounded, priced), never per tick
        return jnp.moveaxis(m, 0, ax)

    out = jax.tree_util.tree_map_with_path(f, cache)
    if off != buf.size:
        raise ValueError(
            f"migration payload size mismatch: spliced {off} bytes, "
            f"payload holds {buf.size} — pool geometries disagree"
        )
    return out


@dataclasses.dataclass(frozen=True)
class SlotLease:
    """One admission's allocation: which slot, which pages, where
    prefill resumes. ``page_row`` is the device-ready ``[max_pages]``
    table row (unused entries = null page 0); ``page_keys`` are the
    chain-hash keys of the prompt's full pages, kept so the pool can
    register them for future sharing once prefill has written them."""

    slot: int
    skip: int                 # prefill resumes here (page-aligned, < P)
    page_row: np.ndarray      # [max_pages] int32
    n_pages: int              # pages charged to this slot
    shared_pages: int         # leading pages mapped from the registry
    page_keys: Tuple[bytes, ...]


class PagedKVPool:
    """Page-pool device tree + host page tables / refcounts / registry.

    ``lengths[i]`` keeps its old meaning — slot ``i``'s filled dense
    prefix, the single source of truth the engine turns into positions,
    write cursors and the implicit per-row causal mask. What changed is
    what backs a slot: a page table instead of a buffer row.
    """

    def __init__(
        self,
        model,
        params,
        num_slots: int,
        max_len: int,
        *,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        prefix_cache: bool = True,
    ):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        ps = page_size or auto_page_size(max_len)
        if ps < 1 or max_len % ps:
            raise ValueError(
                f"page_size {ps} must be >= 1 and divide max_len "
                f"{max_len} (the dense view is max_pages * page_size "
                f"wide and must equal max_len exactly)"
            )
        self.num_slots = num_slots
        self.max_len = max_len
        self.page_size = ps
        self.max_pages = max_len // ps
        # default sizes the pool at memory parity with the old fixed
        # [S, max_len] design — callers size it DOWN to the realistic
        # length mix for the memory win (bench.py's serving_paged phase)
        self.num_pages = (
            num_pages if num_pages is not None
            else num_slots * self.max_pages
        )
        if self.num_pages < self.max_pages:
            raise ValueError(
                f"num_pages {self.num_pages} cannot hold even one "
                f"max-length request ({self.max_pages} pages)"
            )
        self.prefix_cache = prefix_cache
        self.cache = init_page_cache(model, params, self.num_pages, ps)
        self.lengths = np.zeros(num_slots, np.int32)
        self.page_tables = np.zeros(
            (num_slots, self.max_pages), np.int32
        )
        self._free_slots: List[int] = list(range(num_slots))
        heapq.heapify(self._free_slots)
        self._occupied = np.zeros(num_slots, bool)
        self._free_pages: List[int] = list(range(1, self.num_pages + 1))
        heapq.heapify(self._free_pages)
        self._ref = np.zeros(self.num_pages + 1, np.int32)
        self._slot_pages: List[Tuple[int, ...]] = [
            () for _ in range(num_slots)
        ]
        # prefix registry: chain-hash key -> page id, LRU-ordered; an
        # entry holds one refcount, so a registered page survives its
        # writer's retirement and stays shareable until evicted
        self._registry: "OrderedDict[bytes, int]" = OrderedDict()
        self._page_key: Dict[int, bytes] = {}
        # observability counters (engine telemetry + loadgen summary)
        self.prefix_lookups = 0
        self.prefix_hits = 0          # admissions that shared >= 1 page
        self.shared_tokens = 0        # prompt tokens served from shares
        self.prompt_tokens = 0
        self.peak_pages = 0

    # -- prefix hashing ----------------------------------------------------
    def chain_keys(self, prompt_ids) -> List[bytes]:
        """Chain hash per FULL prompt page: key_i commits to tokens
        [0, (i+1)*page_size) — prefix identity, not mere page content.

        Exposed so a caller retrying a page-blocked admission every
        engine step can hash the (immutable) prompt ONCE and pass the
        result back via ``keys=`` — the keys depend only on the tokens
        and the page size, so they are shared between the target and
        draft pools (same geometry by construction). Returns [] with
        the prefix cache off."""
        if not self.prefix_cache:
            return []
        ids = np.ascontiguousarray(prompt_ids, dtype=np.int32)
        ps = self.page_size
        keys, key = [], b""
        for i in range(len(ids) // ps):
            h = hashlib.blake2b(key, digest_size=16)
            h.update(ids[i * ps:(i + 1) * ps].tobytes())
            key = h.digest()
            keys.append(key)
        return keys

    # -- allocation --------------------------------------------------------
    def shareable_skip(
        self,
        prompt_ids,
        *,
        max_new: int = 0,
        chunk: Optional[int] = None,
        tail: int = 0,
        max_skip: Optional[int] = None,
        keys: Optional[List[bytes]] = None,
    ) -> int:
        """How many prompt tokens an allocate() now would serve from the
        registry (page-aligned). Read-only — lets a caller coordinating
        two pools (the speculative engine's target + draft) compute the
        joint skip before committing either allocation. ``keys`` must
        be this prompt's ``chain_keys`` when precomputed."""
        plan = self._plan(
            np.asarray(prompt_ids, np.int32).reshape(-1),
            max_new=max_new, chunk=chunk, tail=tail, max_skip=max_skip,
            keys=keys,
        )
        return plan[1] * self.page_size

    def _plan(self, ids, *, max_new, chunk, tail, max_skip, keys=None):
        """(keys, shared_pages, span) for a prospective admission."""
        P = int(ids.size)
        ps = self.page_size
        if keys is None:
            keys = self.chain_keys(ids)
        # at least one real prompt token must prefill (the final chunk
        # samples the first token from the last prompt column)
        cap = (P - 1) // ps
        if max_skip is not None:
            cap = min(cap, max_skip // ps)
        shared = 0
        for i in range(min(len(keys), cap)):
            if keys[i] not in self._registry:
                break
            shared += 1

        def span_for(shared_pages: int) -> int:
            skip = shared_pages * ps
            pre_end = skip + (
                -(-(P - skip) // chunk) * chunk if chunk else P - skip
            )
            return max(P + max_new + tail, pre_end)

        # chunked prefill writes full chunk widths from `skip`; if the
        # (page-aligned, not chunk-aligned) skip pushes the padded final
        # chunk past the dense width, drop shares until it fits
        while shared and span_for(shared) > self.max_len:
            shared -= 1
        span = span_for(shared)
        if span > self.max_len:
            raise ValueError(
                f"request needs {span} buffer positions (prompt {P} "
                f"rounded to chunks of {chunk} + {max_new} new "
                f"+ {tail} speculative) but max_len is {self.max_len}"
            )
        return keys, shared, span

    def allocate(
        self,
        prompt_ids=None,
        *,
        max_new: int = 0,
        chunk: Optional[int] = None,
        tail: int = 0,
        max_skip: Optional[int] = None,
        keys: Optional[List[bytes]] = None,
    ) -> Optional[SlotLease]:
        """Admit one request: lowest free slot + pages for its worst-case
        span, sharing registered prefix pages where the registry allows.
        Returns None when slots or pages are exhausted (the caller keeps
        the request queued — strict FIFO, no admission reordering).

        ``tail`` reserves extra positions past ``prompt + max_new`` (the
        speculative verify writes up to k rejected-draft entries beyond
        the emitted horizon). ``max_skip`` caps prefix sharing (used to
        align the target and draft pools on one joint skip); ``keys``
        passes precomputed ``chain_keys`` so a head-of-line request
        retried every engine step hashes its prompt once, not per
        attempt.
        """
        if not self._free_slots:
            return None
        ps = self.page_size
        ids = (
            np.asarray(prompt_ids, np.int32).reshape(-1)
            if prompt_ids is not None else np.zeros(0, np.int32)
        )
        P = int(ids.size)
        if P:
            keys, shared_n, span = self._plan(
                ids, max_new=max_new, chunk=chunk, tail=tail,
                max_skip=max_skip, keys=keys,
            )
        else:
            keys, shared_n = [], 0
            span = max(max_new + tail, 1)
        n_span = -(-span // ps)
        needed = n_span - shared_n
        # feasibility BEFORE mutation: free pages plus registry entries
        # nothing references (evictable) must cover the private need
        shared_pages = [self._registry[k] for k in keys[:shared_n]]
        evictable = sum(
            1 for pg in self._registry.values()
            if self._ref[pg] == 1 and pg not in shared_pages
        )
        if needed > len(self._free_pages) + evictable:
            return None
        # commit: pin shares first so eviction can never reap them
        for pg in shared_pages:
            self._ref[pg] += 1
            self._registry.move_to_end(self._page_key[pg])
        fresh = []
        for _ in range(needed):
            if not self._free_pages:
                self._evict_lru()
            fresh.append(heapq.heappop(self._free_pages))
        for pg in fresh:
            self._ref[pg] = 1
        slot = heapq.heappop(self._free_slots)
        self._occupied[slot] = True
        row = np.zeros(self.max_pages, np.int32)
        row[:shared_n] = shared_pages
        row[shared_n:n_span] = fresh
        self.page_tables[slot] = row
        self._slot_pages[slot] = tuple(shared_pages) + tuple(fresh)
        skip = shared_n * ps
        self.lengths[slot] = skip
        if P:
            self.prefix_lookups += 1
            self.prompt_tokens += P
            if shared_n:
                self.prefix_hits += 1
                self.shared_tokens += skip
        self.peak_pages = max(self.peak_pages, self.pages_in_use)
        return SlotLease(
            slot=slot, skip=skip, page_row=row, n_pages=n_span,
            shared_pages=shared_n, page_keys=tuple(keys),
        )

    def _evict_lru(self) -> None:
        """Reap the least-recently-shared registry page nobody holds."""
        for key, pg in self._registry.items():
            if self._ref[pg] == 1:
                del self._registry[key]
                del self._page_key[pg]
                self._ref[pg] = 0
                heapq.heappush(self._free_pages, pg)
                return
        raise RuntimeError(
            "page eviction requested with no evictable registry entry "
            "(allocate() counted wrong — a refcount invariant broke)"
        )

    def register_prefix(self, lease: SlotLease, prompt_ids) -> None:
        """Publish a finished prefill's full prompt pages for sharing.

        Called once the slot's prefill completed (every full page now
        holds canonical prompt KV; the padded final-chunk garbage and
        all decode writes land strictly beyond the last full page, so a
        registered page is immutable for the rest of its life). Already-
        registered keys just refresh their LRU position; a racing
        duplicate keeps the first registration canonical.
        """
        if not self.prefix_cache:
            return
        row = self.page_tables[lease.slot]
        for i, key in enumerate(lease.page_keys):
            page = int(row[i])
            cur = self._registry.get(key)
            if cur is not None:
                self._registry.move_to_end(key)
                continue
            if page in self._page_key:  # already canonical for another key
                continue
            self._registry[key] = page
            self._page_key[page] = key
            self._ref[page] += 1

    def adopt_page(self, key: bytes) -> Optional[int]:
        """Claim one free page and register it under ``key`` — the
        bookkeeping half of pulling a prefix page from a cross-engine
        store (``serve/prefix_store.py``): the caller splices the
        store's canonical frame bytes into the returned page, after
        which the page is indistinguishable from one this pool's own
        prefill produced and every sharing invariant applies unchanged.
        The registry holds the page's one reference (it survives any
        requester's retirement, exactly like a locally-registered
        prefix). Returns the already-registered page when ``key`` is
        known, and None when the prefix cache is off or no page can be
        freed — adoption is an optimization, never a requirement."""
        if not self.prefix_cache:
            return None
        cur = self._registry.get(key)
        if cur is not None:
            self._registry.move_to_end(key)
            return cur
        if not self._free_pages:
            if not any(
                self._ref[pg] == 1 for pg in self._registry.values()
            ):
                return None
            self._evict_lru()
        pg = heapq.heappop(self._free_pages)
        self._ref[pg] = 1
        self._registry[key] = pg
        self._page_key[pg] = key
        return pg

    def free(self, slot: int) -> None:
        """Retire a slot: drop its page references; pages nobody else
        holds (no other slot, no registry entry) return to the free
        list. O(pages held); no device writes — unreferenced page bytes
        are dead until reallocation overwrites them."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        if not self._occupied[slot]:
            raise ValueError(f"slot {slot} is already free")
        self._occupied[slot] = False
        for pg in self._slot_pages[slot]:
            self._ref[pg] -= 1
            if self._ref[pg] == 0:
                heapq.heappush(self._free_pages, pg)
        self._slot_pages[slot] = ()
        self.page_tables[slot] = 0
        self.lengths[slot] = 0
        heapq.heappush(self._free_slots, slot)

    # -- introspection -----------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free_slots)

    @property
    def num_occupied(self) -> int:
        return self.num_slots - len(self._free_slots)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free_pages)

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of prompt tokens served from shared pages."""
        return (
            self.shared_tokens / self.prompt_tokens
            if self.prompt_tokens else 0.0
        )

    def occupied_slots(self) -> List[int]:
        return [i for i in range(self.num_slots) if self._occupied[i]]

    def kv_bytes(self) -> int:
        """Resident bytes of the page pool's KV-payload leaves (null
        page included — it is real allocated memory)."""
        total = 0
        for path, leaf in jax.tree_util.tree_leaves_with_path(self.cache):
            if cache_batch_axis(path, leaf) is not None:
                total += int(leaf.size) * leaf.dtype.itemsize
        return total

    def device_page_table(self, slot: int) -> np.ndarray:
        return self.page_tables[slot].copy()

    def valid_mask(self) -> np.ndarray:
        """[S, max_len] bool over the DENSE view: True where a buffer
        position of an occupied slot holds a live token — the host
        statement of what each row's causal mask lets attention read."""
        mask = (
            np.arange(self.max_len)[None, :] < self.lengths[:, None]
        )
        mask[~self._occupied] = False
        return mask

    def check_consistency(self) -> None:
        """Audit the refcount/free-list/registry invariants; raises on
        the first violation. Tests call it after every lifecycle storm
        (mid-speculation eviction included)."""
        if sorted(self._free_slots) != [
            s for s in range(self.num_slots) if not self._occupied[s]
        ]:
            raise AssertionError("slot free list / occupancy flags drift")
        expect = np.zeros(self.num_pages + 1, np.int64)
        for slot, pages in enumerate(self._slot_pages):
            if pages and not self._occupied[slot]:
                raise AssertionError(f"free slot {slot} still holds pages")
            for pg in pages:
                if not 1 <= pg <= self.num_pages:
                    raise AssertionError(
                        f"slot {slot} references invalid page {pg}"
                    )
                expect[pg] += 1
        for key, pg in self._registry.items():
            if self._page_key.get(pg) != key:
                raise AssertionError(f"registry/page_key disagree on {pg}")
            expect[pg] += 1
        if len(self._page_key) != len(self._registry):
            raise AssertionError("page_key index out of sync with registry")
        if not np.array_equal(expect, self._ref.astype(np.int64)):
            bad = np.nonzero(expect != self._ref)[0]
            raise AssertionError(
                f"refcount drift on pages {bad.tolist()}: "
                f"expected {expect[bad].tolist()}, "
                f"recorded {self._ref[bad].tolist()}"
            )
        free = sorted(self._free_pages)
        if len(set(free)) != len(free):
            raise AssertionError("duplicate entries in the page free list")
        unref = sorted(
            pg for pg in range(1, self.num_pages + 1)
            if expect[pg] == 0
        )
        if free != unref:
            raise AssertionError(
                f"free list {free} != unreferenced pages {unref}"
            )
        if expect[0] != 0:
            raise AssertionError("null page 0 acquired a reference")
