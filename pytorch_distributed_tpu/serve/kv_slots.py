"""Slot-based KV-cache pool — the static-shape heart of the serve engine.

One fixed ``[S, max_len, H, D]`` buffer set per layer (the model's own
flax ``cache`` collection, materialized once via ``jax.eval_shape`` —
no throwaway compile) plus host-side slot bookkeeping. All request
dynamism — admissions, retirements, ragged lengths — is expressed as
which slot a request owns and how many buffer positions it has filled;
the jitted prefill/decode programs see ONE static shape forever.

Key invariants:

* **Free is O(1) and write-free.** Retiring a request only returns its
  slot index to the free list; the stale KV bytes stay in HBM. They are
  harmless because every read is masked by the row's length (attention's
  per-row ``q_offset`` causal mask ends at ``lengths[slot]``) and every
  reuse overwrites from position 0 before anything reads.
* **Per-slot sequences are LEFT-ALIGNED**: a slot's tokens occupy buffer
  positions ``[0, lengths[slot])`` and buffer position == sequence
  position — so ``lengths`` doubles as the rope/wpe position vector AND
  the per-row KV write cursor (``write_pos``), with no translation
  table between the two.
* **Allocation is deterministic** (lowest free index first) so seeded
  workloads replay exactly.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.generation import cache_batch_axis


def init_slot_cache(model, params, num_slots: int, max_len: int):
    """Zeroed decode-cache pytree for ``num_slots`` slots of ``max_len``.

    Shapes come from ``jax.eval_shape`` over the model's own decode
    apply, so the pool is EXACTLY the tree the model mutates — scan
    layouts, int8 KV scale buffers, position counters and all — without
    tracing a compile or touching device memory until the zeros
    materialize.
    """

    def shape_fn(p):
        _, state = model.apply(
            {"params": p},
            jnp.zeros((num_slots, 1), jnp.int32),
            decode=True,
            cache_len=max_len,
            mutable=["cache"],
        )
        return state["cache"]

    shapes = jax.eval_shape(shape_fn, params)
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )


def take_slot(cache, slot):
    """Extract slot ``slot`` as a batch-1 cache (traced ``slot`` ok).

    Only leaves with a batch axis (``generation.cache_batch_axis``) are
    sliced; shared counters pass through — the result is a valid cache
    for a batch-1 ``model.apply`` whose per-row ``write_pos`` ignores
    those counters anyway.
    """

    def f(path, x):
        ax = cache_batch_axis(path, x)
        if ax is None:
            return x
        return jax.lax.dynamic_slice_in_dim(x, slot, 1, axis=ax)

    return jax.tree_util.tree_map_with_path(f, cache)


def put_slot(cache, row_cache, slot):
    """Write a batch-1 cache back into slot ``slot`` of the pool.

    The pool keeps its own shared counters (they are meaningless under
    per-row ``write_pos`` but must stay structurally consistent); only
    batch-carrying leaves are updated.
    """

    def f(path, x, r):
        ax = cache_batch_axis(path, x)
        if ax is None:
            return x
        return jax.lax.dynamic_update_slice_in_dim(
            x, r.astype(x.dtype), slot, axis=ax
        )

    return jax.tree_util.tree_map_with_path(f, cache, row_cache)


class KVSlotPool:
    """The pool: device cache pytree + host slot/length bookkeeping.

    ``lengths[i]`` is slot ``i``'s filled prefix — the number of buffer
    positions holding real (written, valid) KV entries. It is the single
    source of truth the engine turns into ``positions`` (rope/wpe),
    ``write_pos`` (KV write cursor) and the implicit attention mask
    (per-row causal ``q_offset``) each tick.
    """

    def __init__(self, model, params, num_slots: int, max_len: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.num_slots = num_slots
        self.max_len = max_len
        self.cache = init_slot_cache(model, params, num_slots, max_len)
        self.lengths = np.zeros(num_slots, np.int32)
        self._free: List[int] = list(range(num_slots))

    # -- slot lifecycle ----------------------------------------------------
    def allocate(self) -> Optional[int]:
        """Claim the lowest free slot (deterministic), or None when full.
        The slot starts at length 0; its stale bytes are dead until the
        first prefill chunk overwrites them."""
        if not self._free:
            return None
        self._free.sort()
        slot = self._free.pop(0)
        self.lengths[slot] = 0
        return slot

    def free(self, slot: int) -> None:
        """Return ``slot`` to the pool. O(1): no device writes — masks
        make the stale KV unreachable and reuse overwrites it."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        self.lengths[slot] = 0
        self._free.append(slot)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_occupied(self) -> int:
        return self.num_slots - len(self._free)

    def occupied_slots(self) -> List[int]:
        free = set(self._free)
        return [i for i in range(self.num_slots) if i not in free]

    # -- masks (introspection / tests; the jitted step derives its own) ----
    def valid_mask(self) -> np.ndarray:
        """[S, max_len] bool: True where a buffer position holds a live
        token of an occupied slot — the host-visible statement of what
        the per-row causal mask lets attention read."""
        mask = (
            np.arange(self.max_len)[None, :] < self.lengths[:, None]
        )
        mask[list(self._free)] = False
        return mask
