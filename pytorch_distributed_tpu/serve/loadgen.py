"""Offered-load driving shared by bench.py's ``serving`` phase and
``scripts/serve_loadgen.py`` — ONE warm-up and pacing discipline, so the
bench phase and its CLI twin can never silently measure different
things (they already diverged once: a 1-token warm-up retires at
prefill and leaves the decode compile inside the measured window).
"""

from __future__ import annotations

import time
from typing import List, Sequence

import numpy as np

from pytorch_distributed_tpu.serve.scheduler import Request, RequestStatus
from pytorch_distributed_tpu.serve.telemetry import ServeTelemetry


def warm_up(
    engine, prompt_ids, telemetry: ServeTelemetry = None, *,
    precompile_buckets: bool = True,
) -> None:
    """Compile the jitted programs outside any measured window.

    A 2-token request is the minimum that reaches a decode tick — a
    1-token request emits its only token from the prefill program and
    retires without ever compiling decode, so the first measured tick
    would pay the full jit compile (checked here, loudly). With length
    buckets the decode tick is one program PER OCCUPIED BUCKET;
    ``precompile_buckets`` (default on) compiles every bucket via the
    engine's no-op dispatch so a live request crossing a page-bucket
    boundary mid-measurement never pays a compile either. Afterwards
    the engine's telemetry is replaced (``telemetry`` or a fresh one)
    so the warm-up's compile-sized TTFT stays out of every reported
    stream and percentile. The engine's ``max_len`` must fit
    ``roundup(len(prompt_ids), prefill_chunk) + 2``.
    """
    h = engine.submit(Request(prompt_ids, max_new_tokens=2))
    engine.run_until_drained()
    if h.status is not RequestStatus.COMPLETED:
        raise RuntimeError(f"warm-up request failed: {h.status.value}")
    if engine.decode_compiles < 1:
        raise RuntimeError(
            "warm-up drained without a decode tick — the decode compile "
            "would land inside the measured window"
        )
    if precompile_buckets:
        engine.precompile_decode_buckets()
    engine.telemetry = telemetry or ServeTelemetry(
        # keep the engine's writer/clock/engine_id: replacing a
        # writer-backed telemetry with a writer-less one would silently
        # drop the JSONL stream the caller wired up, and dropping the
        # fleet label would anonymize a fleet member's records
        writer=engine.telemetry.writer,
        clock=engine.telemetry.clock,
        engine_id=engine.telemetry.engine_id,
    )
    # a caller-built telemetry was stamped BEFORE this warm-up ran —
    # restart its wall clock or summary() throughput eats the compile
    engine.telemetry.started_at = engine.telemetry.clock()


def drive(
    engine,
    requests: Sequence[Request],
    arrivals: Sequence[float],
    *,
    clock=time.perf_counter,
) -> float:
    """Submit ``requests[i]`` at ``arrivals[i]`` seconds from start and
    step the engine until everything drains; returns the wall seconds.

    Between steps with no work and a pending arrival, sleeps at most
    2 ms so pacing stays accurate without busy-burning the host core.
    """
    if len(requests) != len(arrivals):
        raise ValueError("requests and arrivals must pair up")
    t0 = clock()
    i, n = 0, len(requests)
    while i < n or engine.has_work():
        now = clock() - t0
        while i < n and now >= arrivals[i]:
            engine.submit(requests[i])
            i += 1
        if not engine.step() and i < n:
            time.sleep(min(max(arrivals[i] - now, 0.0), 0.002))
    return clock() - t0


def uniform_arrivals(n: int, rate: float) -> List[float]:
    """Fixed-rate arrival offsets: request i at ``i / rate`` (all at 0
    when ``rate`` is 0 — closed-loop saturation)."""
    if rate <= 0:
        return [0.0] * n
    return [i / rate for i in range(n)]


def prefix_shared_requests(
    rng,
    n: int,
    vocab: int,
    *,
    prompt_len=(4, 16),
    new_tokens=(8, 32),
    prefix_share: float = 0.0,
    shared_prefix_len: int = 0,
    temperature: float = 0.0,
    top_k=None,
    top_p=None,
    deadline_s=None,
) -> List[Request]:
    """Seeded mixed-length workload with a common-system-prompt knob.

    ``prefix_share`` of the requests open with ONE shared
    ``shared_prefix_len``-token system prompt (drawn once from ``rng``)
    followed by their own tail; the rest are fully independent. This is
    the workload shape the paged pool's prefix registry exists for —
    bench.py's ``serving_paged`` phase and ``scripts/serve_loadgen.py
    --prefix-share`` both build their request streams here so the two
    can never exercise different sharing paths. Lengths are inclusive
    ``(lo, hi)`` ranges; per-request seeds come from ``rng`` so sampled
    runs replay exactly.
    """
    if not 0.0 <= prefix_share <= 1.0:
        raise ValueError(
            f"prefix_share must be in [0, 1], got {prefix_share}"
        )
    if prefix_share > 0.0 and shared_prefix_len < 1:
        raise ValueError(
            "prefix_share > 0 needs shared_prefix_len >= 1 "
            "(the common system prompt must exist to be shared)"
        )
    system = rng.integers(
        1, vocab, size=shared_prefix_len
    ).astype(np.int32) if shared_prefix_len else None
    p_lo, p_hi = prompt_len
    n_lo, n_hi = new_tokens
    reqs = []
    for i in range(n):
        tail = rng.integers(
            1, vocab, size=int(rng.integers(p_lo, p_hi + 1))
        ).astype(np.int32)
        shared = system is not None and rng.random() < prefix_share
        ids = np.concatenate([system, tail]) if shared else tail
        reqs.append(Request(
            prompt_ids=ids,
            max_new_tokens=int(rng.integers(n_lo, n_hi + 1)),
            temperature=temperature, top_k=top_k, top_p=top_p,
            deadline_s=deadline_s,
            seed=int(rng.integers(0, 2**31)),
        ))
    return reqs
