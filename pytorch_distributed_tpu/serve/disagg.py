"""Migration wire format: prefill-tier page frames -> decode-tier splice.

The paged pool makes a request's KV a page list, so tier hand-off is
three arrays over the existing ``hostring.send/recv`` path (or a
zero-copy loopback inside one router process — SAME codec, so the byte
accounting and the fingerprint discipline are identical either way):

* **preamble** ``int64[2]`` — meta and payload sizes, so the receiver
  can shape its ``recv`` buffers (the P2P mailbox needs shapes known
  up front);
* **header** ``uint8[96]`` — ``blake2b-256(signature | meta | payload)``
  in bytes [0, 32) plus the leading bytes of the sender's frame
  signature as a human-readable hint — the ``_verify_p2p`` DETAIL
  idiom, applied per migration packet. The digest is recomputed on the
  receiver with ITS OWN pool signature: a geometry mismatch (different
  model, page size, dtype, scan layout) or a corrupted payload both
  land in the same loud :class:`MigrationError` refusal, before a
  single byte touches the pool;
* **meta** — JSON: the request's constructor fields (the decode side
  rebuilds the ``Request`` and re-derives the sampling row — key =
  ``split(PRNGKey(seed))[0]``, toks = the shipped first token, length
  = prompt_len — rather than shipping device state);
* **payload** — ``kv_slots.extract_frames`` bytes for the
  ``ceil(P / page_size)`` pages that hold written prompt KV, verbatim
  in the pool's native dtype. int8 pools therefore ship int8 K/V plus
  f32 per-token scales — ``(1 + 4/D)/4`` of the f32 bytes — while
  staying exactly lossless: the bit-parity gate and the byte pin hold
  on the SAME run.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Sequence

import numpy as np

HEADER_BYTES = 96
_DIGEST_BYTES = 32

#: Request constructor fields a frame carries (prompt_ids handled
#: separately — it is an array)
_REQ_FIELDS = (
    "max_new_tokens", "temperature", "top_k", "top_p", "eos_id",
    "seed", "deadline_s", "request_id",
)


class MigrationError(RuntimeError):
    """A migration packet was refused before touching the pool."""


@dataclasses.dataclass
class MigrationFrame:
    """One migrated request: everything the decode tier needs."""

    request: Dict[str, object]   # Request ctor kwargs, prompt_ids as list
    first_token: int             # sampled by the prefill tier's final chunk
    prompt_len: int
    n_pages: int                 # frames in ``payload``
    signature: str               # sender's kv_slots.frame_signature
    payload: np.ndarray          # uint8, extract_frames codec
    src_engine: str = ""

    @property
    def request_id(self) -> str:
        return str(self.request.get("request_id", ""))

    @property
    def payload_nbytes(self) -> int:
        return int(self.payload.size)


def request_to_wire(req) -> Dict[str, object]:
    """JSON-safe ``Request`` constructor kwargs."""
    d = {k: getattr(req, k) for k in _REQ_FIELDS}
    d["prompt_ids"] = np.asarray(req.prompt_ids, np.int32).tolist()
    return d


def request_from_wire(d: Dict[str, object]):
    from pytorch_distributed_tpu.serve.scheduler import Request

    kw = dict(d)
    kw["prompt_ids"] = np.asarray(kw["prompt_ids"], np.int32)
    return Request(**kw)


def _digest(signature: str, meta: bytes, payload: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=_DIGEST_BYTES)
    h.update(signature.encode())
    h.update(meta)
    h.update(np.ascontiguousarray(payload, np.uint8).tobytes())
    return h.digest()


def encode_frame(frame: MigrationFrame) -> List[np.ndarray]:
    """Frame -> ``[preamble, header, meta, payload]`` wire arrays."""
    meta = json.dumps({
        "request": frame.request,
        "first_token": int(frame.first_token),
        "prompt_len": int(frame.prompt_len),
        "n_pages": int(frame.n_pages),
        "signature": frame.signature,
        "src_engine": frame.src_engine,
    }, sort_keys=True).encode()
    payload = np.ascontiguousarray(frame.payload, np.uint8).reshape(-1)
    header = np.zeros(HEADER_BYTES, np.uint8)
    header[:_DIGEST_BYTES] = np.frombuffer(
        _digest(frame.signature, meta, payload), np.uint8
    )
    hint = frame.signature.encode()[:HEADER_BYTES - _DIGEST_BYTES]
    header[_DIGEST_BYTES:_DIGEST_BYTES + len(hint)] = np.frombuffer(
        hint, np.uint8
    )
    preamble = np.array([len(meta), payload.size], np.int64)
    return [preamble, header, np.frombuffer(meta, np.uint8), payload]


def decode_frame(
    header: np.ndarray,
    meta: np.ndarray,
    payload: np.ndarray,
    expect_signature: Optional[str] = None,
) -> MigrationFrame:
    """Wire arrays -> frame, refusing on any fingerprint mismatch.

    ``expect_signature`` is the RECEIVING pool's frame signature; the
    digest is recomputed over (that signature, meta, payload), so a
    sender with different pool geometry — or bytes damaged in flight —
    is refused identically, naming both layouts.
    """
    meta_b = np.ascontiguousarray(meta, np.uint8).tobytes()
    try:
        obj = json.loads(meta_b.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise MigrationError(
            f"migration meta is not valid JSON ({e}) — framing drift "
            "between sender and receiver"
        ) from e
    theirs = str(obj.get("signature", ""))
    check_sig = expect_signature if expect_signature is not None else theirs
    want = np.frombuffer(
        _digest(check_sig, meta_b, payload), np.uint8
    )
    got = np.ascontiguousarray(header, np.uint8).reshape(-1)
    if got.size != HEADER_BYTES or not np.array_equal(
        got[:_DIGEST_BYTES], want
    ):
        raise MigrationError(
            "migration fingerprint mismatch: receiver pool is "
            f"{check_sig!r}, sender declared {theirs!r} — refusing the "
            "splice (geometry drift or bytes corrupted in flight; set "
            "PTD_DISTRIBUTED_DEBUG=DETAIL on both tiers for full frame "
            "layouts)"
        )
    payload = np.ascontiguousarray(payload, np.uint8).reshape(-1)
    return MigrationFrame(
        request=obj["request"],
        first_token=int(obj["first_token"]),
        prompt_len=int(obj["prompt_len"]),
        n_pages=int(obj["n_pages"]),
        signature=theirs,
        payload=payload,
        src_engine=str(obj.get("src_engine", "")),
    )


def wire_nbytes(arrays: Sequence[np.ndarray]) -> int:
    """Total bytes a frame occupies on the wire (preamble + header +
    meta + payload) — what the router's migration accounting records."""
    return int(sum(int(a.nbytes) for a in arrays))


def send_frame(ring, frame: MigrationFrame, dst: int) -> int:
    """Ship one frame to ``dst`` over the ring's P2P mailboxes; returns
    wire bytes. Pure sends — bystander ranks are uninvolved."""
    arrays = encode_frame(frame)
    for a in arrays:
        ring.send(a, dst)
    return wire_nbytes(arrays)


def recv_frame(
    ring, src: int, expect_signature: Optional[str] = None
) -> MigrationFrame:
    """Receive one frame from ``src``, fingerprint-checked against the
    receiver's own pool ``expect_signature`` before anything is used."""
    pre = ring.recv(np.zeros(2, np.int64), src)
    meta_len, payload_len = int(pre[0]), int(pre[1])
    if not (0 <= meta_len <= 1 << 30 and 0 <= payload_len <= 1 << 34):
        raise MigrationError(
            f"migration preamble implausible: meta={meta_len} "
            f"payload={payload_len} bytes — stream out of sync"
        )
    header = ring.recv(np.zeros(HEADER_BYTES, np.uint8), src)
    meta = ring.recv(np.zeros(meta_len, np.uint8), src)
    payload = ring.recv(np.zeros(payload_len, np.uint8), src)
    return decode_frame(header, meta, payload, expect_signature)


def roundtrip_frame(
    frame: MigrationFrame, expect_signature: Optional[str] = None
):
    """In-process loopback through the FULL wire codec: encode, then
    decode under the receiver's signature. Returns ``(frame, wire
    bytes)``. The router uses this instead of a bare object hand-off so
    in-process fleets pay (and account) the identical framing +
    fingerprint discipline as cross-process ones."""
    arrays = encode_frame(frame)
    out = decode_frame(arrays[1], arrays[2], arrays[3], expect_signature)
    return out, wire_nbytes(arrays)
