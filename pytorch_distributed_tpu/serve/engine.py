"""Continuous-batching serve loop: admit/retire mid-flight, compile once.

The engine composes the pieces: a ``PagedKVPool`` (page-granular device
state + host page tables), a ``Scheduler`` (host dynamism), per-row
sampling, and a FIXED set of jitted programs, each compiled exactly once
for the engine's lifetime — the bounded-compile-count invariant, pinned
by tests:

* ``prefill``: one ``[1, prefill_chunk]`` model pass writing a chunk of
  one request's prompt into its pages (gather pages -> dense row ->
  ``write_pos`` chunk write -> scatter the chunk back), sampling the
  first token on the final chunk. With speculation enabled the SAME
  program also prefills the draft model's pages — still one program.
* ``decode``: one ``[S, 1]`` tick over ALL slots through the same
  ``generation.decode_step_body`` the offline ``generate`` scan uses —
  attending IN PLACE over the page pool (``ops/paged_attention``: the
  engine installs a ``PagedView`` around the traced model apply, new
  K/V lands via per-page scatters of only the deliberately-written
  positions, and attention streams the pages — no transient
  ``[S, max_len]`` dense view, the round-11 gather tax this round
  removed). ``decode_mode="dense"`` keeps the round-11 dense-gather
  program as the A/B baseline the bench's ``serving_paged_attn`` phase
  measures against. Free / mid-prefill rows still never write — the
  per-page write drops their rows exactly as the dense scatter did.
* **length buckets** bound what the remaining dense spans (chunked
  prefill's per-slot row, the speculative draft's short context) and
  the paged streams actually touch: widths round up to the live
  maximum's power-of-two page bucket instead of always ``max_len``,
  with the bucket width a STATIC jit argument — at most one program
  per occupied bucket (<= log2(max_pages) + 1 decode programs, each
  compiled exactly once, tracked per bucket in
  ``decode_buckets``/``prefill_buckets``).
* with ``SpecConfig``: the decode tick is replaced by ONE fused
  speculative program — k sequential draft proposals (a ``lax.scan`` of
  single-token draft steps) + one ``[S, k+1]`` target verify pass +
  per-row acceptance — emitting 1..k+1 tokens per request per tick for
  one host dispatch. Draft and verify could be two programs; fusing
  them halves dispatches and keeps the count at one, still counted via
  ``decode_compiles``.

Cache-rewind for rejected drafts is FREE here, unlike the offline
``speculative.generate_speculative`` (whose append-only cache pays
permanent slot bubbles): the pool's left-aligned position==buffer-slot
layout means a rejected draft's KV sits at positions >= the row's
accepted length — exactly where the next tick's chunk writes land
before anything attends them. No kv_mask, no compaction, no bubbles.

Static-shape invariant: no program's input shapes depend on which
requests are in flight. Parity invariant: every COMPLETED greedy or
sampled (non-speculative) request's token stream is bit-identical to a
solo ``generate(prompt, ..., rng=jax.random.PRNGKey(seed))``; under
speculation, greedy streams stay bit-identical (the verify accepts
exactly the target's own argmax prefix + correction) while sampled rows
follow Leviathan rejection sampling (distribution-exact, not
token-comparable — same contract as ``generate_speculative``).

Failure model (degrade, don't crash): ``serve.prefill``/``serve.decode``
fault sites fire per-request — a poisoned request is evicted as FAILED
mid-speculation or not, its slot and page references released (shared
pages survive for their other holders), and the engine keeps serving.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.generation import (
    cache_batch_axis,
    decode_step_body,
    model_max_len,
)
from pytorch_distributed_tpu.serve.disagg import (
    MigrationError,
    MigrationFrame,
    request_from_wire,
    request_to_wire,
)
from pytorch_distributed_tpu.ops.paged_attention import (
    PagedView,
    paged_view,
    resolve_paged_attention_impl,
)
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.runtime import tracing
from pytorch_distributed_tpu.serve.kv_slots import (
    PagedKVPool,
    extract_frames,
    frame_signature,
    gather_pages,
    scatter_kv,
    splice_frames,
)
from pytorch_distributed_tpu.serve.sampling import (
    TOP_K_OFF,
    TOP_P_OFF,
    filter_logits_rows,
    sample_logits_rows,
)
from pytorch_distributed_tpu.serve.scheduler import (
    Request,
    RequestHandle,
    RequestStatus,
    Scheduler,
)
from pytorch_distributed_tpu.serve.telemetry import ServeTelemetry
from pytorch_distributed_tpu.speculative import speculative_accept
from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Opt-in speculative decoding for the engine tick.

    ``draft_model``/``draft_params`` must share the target's vocabulary
    and the ``generate`` decode contract; ``num_draft_tokens`` (k) is
    the static proposal width — every tick drafts k tokens and verifies
    them in one ``[S, k+1]`` target pass, emitting 1..k+1 tokens per
    decoding request.
    """

    draft_model: Any
    draft_params: Any
    num_draft_tokens: int = 4

    def __post_init__(self):
        if self.num_draft_tokens < 1:
            raise ValueError(
                f"num_draft_tokens must be >= 1, "
                f"got {self.num_draft_tokens}"
            )


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    num_slots: int = 4          # S: max concurrent in-flight requests
    max_len: int = 256          # per-request dense KV capacity
    prefill_chunk: int = 32     # static prompt-chunk width
    prefill_chunks_per_step: int = 1  # prefill/decode interleave ratio
    telemetry_every: int = 32   # engine steps between occupancy snapshots
    # paged pool knobs: page_size None -> largest power-of-2 divisor of
    # max_len (<= 32); num_pages None -> memory parity with the old
    # fixed [S, max_len] pool (size it DOWN to the realistic length mix
    # for the memory win); prefix_cache shares identical page-aligned
    # prompt prefixes copy-free via refcounts
    page_size: Optional[int] = None
    num_pages: Optional[int] = None
    prefix_cache: bool = True
    # "paged" (default): the decode tick attends in place over the page
    # pool (ops/paged_attention) with length-bucketed widths; "dense"
    # keeps the round-11 full-width gather programs — the A/B baseline
    # bench.py's serving_paged_attn phase measures the paged path against
    decode_mode: str = "paged"
    # r18 tiers: "solo" (the default — the bit-identical A/B baseline,
    # every pre-r18 code path byte-for-byte unchanged) serves requests
    # end to end; "prefill" fills pages and ships MigrationFrames via
    # ``outbox`` instead of decoding; "decode" owns the tick and takes
    # work via ``inject_migration`` only. All three roles drive the SAME
    # jitted programs — a role only changes which ones a request reaches.
    role: str = "solo"
    # fleet label: stamps telemetry records (engine_id gauge label) and
    # migration frames; None keeps the single-engine-implicit schema
    engine_id: Optional[str] = None
    # synthetic per-token compute (the r15 ``shard_delay_s`` idiom for
    # serving): what a disaggregated tier can actually overlap. A
    # prefill chunk sleeps prefill_delay_s * chunk_len; a decode tick
    # sleeps decode_delay_s * active_slots. Bench/chaos only — sleeps
    # never touch the math, so CRCs are invariant to either knob, and a
    # 1-core host running N sleeping processes behaves like an N-way
    # fleet (compute overlaps; the python between sleeps serializes).
    prefill_delay_s: float = 0.0
    decode_delay_s: float = 0.0

    def __post_init__(self):
        if self.decode_mode not in ("paged", "dense"):
            raise ValueError(
                f"decode_mode must be 'paged' or 'dense', got "
                f"{self.decode_mode!r}"
            )
        if self.role not in ("solo", "prefill", "decode"):
            raise ValueError(
                f"role must be 'solo', 'prefill' or 'decode', got "
                f"{self.role!r}"
            )
        if self.num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self.prefill_chunks_per_step < 1:
            raise ValueError("prefill_chunks_per_step must be >= 1")
        if self.max_len < 2:
            raise ValueError("max_len must be >= 2 (1 prompt + 1 new)")
        if self.prefill_chunk > self.max_len:
            # every prompt rounds up to at least one chunk of KV slots,
            # so this config could never admit ANY request — fail at
            # construction naming the real culprit, not per-submit
            # blaming the prompt
            raise ValueError(
                f"prefill_chunk {self.prefill_chunk} > max_len "
                f"{self.max_len}: no request could ever be admitted"
            )
        if self.prefill_delay_s < 0 or self.decode_delay_s < 0:
            raise ValueError(
                "prefill_delay_s / decode_delay_s must be >= 0, got "
                f"{self.prefill_delay_s} / {self.decode_delay_s}"
            )
        if self.page_size is not None and (
            self.page_size < 1 or self.max_len % self.page_size
        ):
            raise ValueError(
                f"page_size {self.page_size} must divide max_len "
                f"{self.max_len} (the paged dense view is "
                f"max_pages * page_size wide)"
            )


class ServeEngine:
    """Single-threaded, deterministic serve loop.

    Drive it with ``submit()`` + ``step()`` (one scheduler iteration:
    deadline sweep -> cancellations -> admission -> prefill chunks ->
    decode tick), or ``run_until_drained()``. Tokens stream into each
    ``RequestHandle.tokens`` as they are emitted (or via
    ``handle.on_token``).

    ``params`` may be placed by any ``parallel/strategies.py`` strategy
    — the jitted programs follow the committed shardings (TP rules
    shard the per-slot compute exactly as they shard ``generate``).
    """

    def __init__(
        self,
        model,
        params,
        config: EngineConfig = EngineConfig(),
        *,
        spec: Optional[SpecConfig] = None,
        telemetry: Optional[ServeTelemetry] = None,
        prefix_store=None,
        clock=time.monotonic,
    ):
        self.model = model
        self.params = params
        self.config = config
        self.spec = spec
        self.role = config.role
        self.engine_id = config.engine_id
        if config.role != "solo" and spec is not None:
            # tiered speculation would also have to migrate the DRAFT
            # pool's pages and re-derive its rng chain — future work;
            # refuse loudly rather than ship a frame the decode tier
            # cannot faithfully adopt
            raise ValueError(
                f"role={config.role!r} requires spec=None: speculative "
                "decoding is solo-engine only (the draft cache does not "
                "ride the migration frame)"
            )
        if prefix_store is not None and spec is not None:
            raise ValueError(
                "prefix_store requires spec=None: store adoption splices "
                "target pages only, and a draft pool sharing the slot "
                "would miss the prefix"
            )
        self.telemetry = telemetry or ServeTelemetry(
            clock=clock, engine_id=config.engine_id
        )
        if self.telemetry.engine_id is None and config.engine_id:
            # caller-supplied telemetry inherits the fleet label so
            # merged multi-engine streams stay disambiguable
            self.telemetry.engine_id = config.engine_id
        self._clock = clock
        limit = model_max_len(model)
        if limit is not None and config.max_len > limit:
            raise ValueError(
                f"max_len {config.max_len} exceeds the model's maximum "
                f"sequence length {limit}"
            )
        self.pool = PagedKVPool(
            model, params, config.num_slots, config.max_len,
            page_size=config.page_size, num_pages=config.num_pages,
            prefix_cache=config.prefix_cache,
        )
        self.draft_pool = None
        self._spec_tail = 0
        if spec is not None:
            dlimit = model_max_len(spec.draft_model)
            if dlimit is not None and config.max_len > dlimit:
                raise ValueError(
                    f"max_len {config.max_len} exceeds the DRAFT "
                    f"model's maximum sequence length {dlimit}"
                )
            # the draft shares the target's page geometry so one chunk
            # stream and one joint prefix skip drive both caches
            self.draft_pool = PagedKVPool(
                spec.draft_model, spec.draft_params,
                config.num_slots, config.max_len,
                page_size=self.pool.page_size,
                num_pages=config.num_pages,
                prefix_cache=config.prefix_cache,
            )
            # verify writes up to k rejected-draft entries past the
            # emitted horizon — reserved at admission, checked at submit
            self._spec_tail = spec.num_draft_tokens
        self.scheduler = Scheduler(config.num_slots, config.prefill_chunk)
        # -- r18 fleet state ------------------------------------------------
        # one geometry string commits the pool's frame layout; every
        # migration packet and store access is fingerprint-checked
        # against it (the _verify_p2p DETAIL idiom, per hand-off)
        self.migration_signature = frame_signature(
            self.pool.cache, self.pool.page_size
        )
        #: prefill role: packed frames awaiting the router's pick-up
        self.outbox: Deque[MigrationFrame] = deque()
        #: decode/solo role: injected handles awaiting slot capacity
        self._inject_backlog: Deque[RequestHandle] = deque()
        self._store = prefix_store
        if prefix_store is not None:
            sig = getattr(prefix_store, "signature", None)
            if sig is None:
                # first engine to attach commits the fleet geometry
                prefix_store.signature = self.migration_signature
            elif sig != self.migration_signature:
                raise ValueError(
                    "prefix-store geometry mismatch at attach: store "
                    f"holds {sig!r}, this engine's pool is "
                    f"{self.migration_signature!r}"
                )
        self._holder = config.engine_id or f"engine-{id(self):x}"
        self.migrated_out = 0          # frames shipped (prefill role)
        self.migrated_in = 0           # frames spliced (decode/solo)
        self.store_published_pages = 0
        self.store_adopted_pages = 0
        S = config.num_slots
        mp = self.pool.max_pages
        # per-slot sampling/decode state lives ON DEVICE and is updated
        # in place: rows change only at request transitions (admission,
        # prefill-final, eviction), and the decode tick advances the
        # continuing rows inside the jitted program — so a steady-state
        # tick is ONE jit call plus one token fetch, no per-tick
        # host->device re-uploads (measured 2ms/tick of pure host
        # overhead before this). Stale rows of freed/mid-prefill slots
        # are harmless: their sampled tokens are discarded and their
        # pool writes are DROPPED (scatter keep-mask), so stale state
        # never reaches the persistent pages.
        self._toks = jnp.zeros(S, jnp.int32)
        self._lengths = jnp.zeros(S, jnp.int32)
        self._temps = jnp.zeros(S, jnp.float32)
        self._top_ks = jnp.full(S, TOP_K_OFF, jnp.int32)
        self._top_ps = jnp.full(S, TOP_P_OFF, jnp.float32)
        # old-style uint32 [2] keys: stackable/vmappable plain arrays
        # with the same threefry streams as jax.random.key
        self._keys = jnp.tile(jax.random.PRNGKey(0)[None, :], (S, 1))
        # device page tables (target + draft), updated only at admission
        self._pt = jnp.zeros((S, mp), jnp.int32)
        self._dpt = (
            jnp.zeros((S, mp), jnp.int32) if spec is not None else None
        )
        self._n_deadlines = 0  # live requests carrying a deadline
        self._any_cancel = False
        # the decoding set only changes at request transitions — cache
        # the (slot, handle) list and the device-side active mask so a
        # steady-state tick rebuilds neither
        self._decoding_dirty = True
        self._decoding_cached = []
        self._active_cached = None
        self._steps = 0
        self._decode_ticks = 0
        self.prefill_compiles = 0
        self.decode_compiles = 0
        # length buckets: the static widths the prefill/decode programs
        # compile at — powers of two in pages, capped at max_pages
        # (dense mode has exactly one width, the full table)
        if config.decode_mode == "paged":
            self._buckets = self._bucket_list(mp)
        else:
            self._buckets = [mp]
        # per-bucket compile counts (the traced program bodies bump
        # them): the bounded-compile invariant is now "each occupied
        # bucket compiled EXACTLY once" — decode_compiles stays the
        # cumulative total across buckets
        self._decode_bucket_compiles: dict = {}
        self._prefill_bucket_compiles: dict = {}
        # analytic HBM accounting for the decode hot path: bytes one
        # tick moves under this mode/impl's traffic model (DESIGN.md
        # §17), accumulated host-side as plain ints so the disarmed
        # tracing cost stays one is-None test. _attn_impl resolves
        # ONCE — the accounting follows the backend the programs trace
        self._resolved_impl = (
            resolve_paged_attention_impl()
            if config.decode_mode == "paged" else "dense"
        )
        self._attn_impl = self._resolved_impl
        if self._attn_impl == "kernel" and getattr(
            getattr(model, "config", None), "kv_cache_quantize", None
        ) is not None:
            # the kernel takes fp pools only — paged_attention falls
            # back to the gather impl for quantized caches, and the
            # byte accounting must price what actually runs
            self._attn_impl = "gather"
        self._frame_bytes_target = self._frame_bytes(self.pool.cache)
        self._frame_bytes_draft = (
            self._frame_bytes(self.draft_pool.cache)
            if self.draft_pool is not None else 0
        )
        self._tick_cost_cache: dict = {}
        self.decode_gather_bytes = 0   # dense-intermediate traffic only
        self.decode_hbm_bytes = 0      # gather + attention-stream reads
        self._decode_tokens = 0        # tokens emitted by decode ticks
        # speculative bookkeeping (raw per-verify acceptance; host ints)
        self.spec_verifies = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        # donation lets XLA update the page pools in place; XLA:CPU
        # cannot alias and would warn every call, so gate on backend
        donate = jax.default_backend() != "cpu"
        # distinct attributes per program (never rebound to a different
        # signature) so donation bookkeeping is auditable per call site
        self._prefill = self._decode = None
        self._prefill_spec = self._spec_tick = None
        # the bucket width rides as a STATIC argument: one compiled
        # program per occupied width, each counted by the traced body
        if spec is None:
            self._prefill = jax.jit(
                self._prefill_fn, donate_argnums=(1,) if donate else (),
                static_argnums=(14,),
            )
            # pool + the in-program-advanced rows (toks/lengths/keys)
            # are donated: each is replaced by its returned successor
            self._decode = jax.jit(
                self._decode_fn,
                donate_argnums=(1, 3, 4, 5) if donate else (),
                static_argnums=(10,),
            )
        else:
            self._prefill_spec = jax.jit(
                self._prefill_spec_fn,
                donate_argnums=(2, 3) if donate else (),
                static_argnums=(17,),
            )
            self._spec_tick = jax.jit(
                self._spec_fn,
                donate_argnums=(2, 3, 6, 7, 8) if donate else (),
                static_argnums=(13,),
            )
        # admission-time row setup as ONE jitted program: eager
        # .at[].set dispatches cost ~2.4ms EACH on this backend
        # (measured under cProfile — per-request transitions were half
        # the serving wall-clock), a fused compiled update is ~0.1ms
        self._admit_rows = jax.jit(self._admit_rows_fn)
        # migration admission writes a DECODING row directly (no
        # prefill pass): same fused-update rationale as _admit_rows
        self._inject_rows = jax.jit(self._inject_rows_fn)

    # -- jitted programs ---------------------------------------------------
    @staticmethod
    def _bucket_list(max_pages: int):
        """Power-of-two page widths up to (and always including) the
        full table — the static shapes the bucketed programs compile
        at. <= log2(max_pages) + 1 entries."""
        out, b = [], 1
        while b < max_pages:
            out.append(b)
            b *= 2
        out.append(max_pages)
        return out

    def _bucket_for(self, pages: int) -> int:
        for b in self._buckets:
            if b >= pages:
                return b
        return self._buckets[-1]

    @staticmethod
    def _frame_bytes(cache) -> int:
        """Bytes of ONE page frame across every KV-payload leaf (layer
        stacking included) — the unit of the analytic HBM accounting."""
        total = 0
        for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
            ax = cache_batch_axis(path, leaf)
            if ax is not None:
                total += (
                    int(leaf.size) // int(leaf.shape[ax])
                    * leaf.dtype.itemsize
                )
        return total

    def _prefill_chunk_body(self, model, params, cache, pt, ids, slot,
                            start, n_pages):
        """One model's chunk prefill over its page pool: gather the
        slot's pages — only the leading ``n_pages`` bucket the chunk
        can reach, not the full ``max_len`` span — to a dense row, run
        the ``[1, C]`` chunk write, and scatter exactly the chunk's
        positions back (padded final-chunk positions included — they
        stay inside the slot's reserved private span and are
        overwritten or masked, as before). Returns
        (chunk logits, updated pool)."""
        C = self.config.prefill_chunk
        row_pt = jax.lax.dynamic_slice_in_dim(pt, slot, 1, axis=0)
        row_pt = jax.lax.slice_in_dim(row_pt, 0, n_pages, axis=1)
        row = gather_pages(cache, row_pt)
        positions = (start + jnp.arange(C))[None, :]
        logits, state = model.apply(
            {"params": params, "cache": row},
            ids,
            decode=True,
            cache_len=n_pages * self.pool.page_size,
            mutable=["cache"],
            positions=positions,
            write_pos=jnp.asarray(start, jnp.int32)[None],
        )
        cache = scatter_kv(
            cache, state["cache"], row_pt, positions,
            jnp.ones((1, C), bool),
        )
        return logits, cache

    def _prefill_tail(self, logits, slot, start, last_idx, final, toks,
                      lengths, keys, temps, top_ks, top_ps):
        """Shared epilogue: advance the device length cursor and, on the
        final chunk, sample/persist the first token + rng split."""
        # the device length cursor advances with EVERY chunk, not just
        # the final one — a decode tick between chunks must see the
        # cursor at the NEXT chunk's start (its write is dropped, but
        # its positions/mask derive from the cursor)
        lengths = lengths.at[slot].set(start + last_idx + 1)
        # rng discipline mirrors generate(): ONE split before the first
        # token, persisted (with the token) only on the final chunk
        pair = jax.random.split(keys[slot])
        last = jax.lax.dynamic_index_in_dim(
            logits, last_idx, axis=1, keepdims=False
        )  # [1, V] — the chunk's last REAL prompt column
        tok = sample_logits_rows(
            last, pair[1][None], temps[slot][None],
            top_ks[slot][None], top_ps[slot][None],
        )[0]
        keys = jnp.where(final, keys.at[slot].set(pair[0]), keys)
        toks = jnp.where(final, toks.at[slot].set(tok), toks)
        return tok, toks, lengths, keys

    def _prefill_fn(self, params, cache, pt, ids, slot, start, last_idx,
                    final, toks, lengths, keys, temps, top_ks, top_ps,
                    n_pages):
        # traced once per (engine lifetime, bucket width) — python side
        # effects count compiles, cumulatively and per bucket (the
        # bounded-compile invariant, pinned by tests)
        self.prefill_compiles += 1
        self._prefill_bucket_compiles[n_pages] = (
            self._prefill_bucket_compiles.get(n_pages, 0) + 1
        )
        logits, cache = self._prefill_chunk_body(
            self.model, params, cache, pt, ids, slot, start, n_pages
        )
        tok, toks, lengths, keys = self._prefill_tail(
            logits, slot, start, last_idx, final, toks, lengths, keys,
            temps, top_ks, top_ps,
        )
        return cache, tok, toks, lengths, keys

    def _prefill_spec_fn(self, params, dparams, cache, dcache, pt, dpt,
                         ids, slot, start, last_idx, final, toks,
                         lengths, keys, temps, top_ks, top_ps, n_pages):
        """Speculative prefill: the SAME chunk through target AND draft
        (the draft needs the prompt's KV before it can propose) — one
        program per bucket, one dispatch per chunk."""
        self.prefill_compiles += 1
        self._prefill_bucket_compiles[n_pages] = (
            self._prefill_bucket_compiles.get(n_pages, 0) + 1
        )
        logits, cache = self._prefill_chunk_body(
            self.model, params, cache, pt, ids, slot, start, n_pages
        )
        _, dcache = self._prefill_chunk_body(
            self.spec.draft_model, dparams, dcache, dpt, ids, slot,
            start, n_pages,
        )
        tok, toks, lengths, keys = self._prefill_tail(
            logits, slot, start, last_idx, final, toks, lengths, keys,
            temps, top_ks, top_ps,
        )
        return cache, dcache, tok, toks, lengths, keys

    def _admit_rows_fn(self, temps, top_ks, top_ps, keys, lengths, pt,
                       dpt, slot, temp, top_k, top_p, seed, skip,
                       pt_row, dpt_row):
        # the write cursor parks at `skip` — the first position the
        # request's own prefill will write. Everything before it is
        # shared-prefix pages (read-only by the CoW discipline); the
        # decode tick's write for this inactive row is dropped anyway,
        # but positions/masks derive from the cursor and must never
        # point inside a shared page.
        out = (
            temps.at[slot].set(temp),
            top_ks.at[slot].set(top_k),
            top_ps.at[slot].set(top_p),
            keys.at[slot].set(jax.random.PRNGKey(seed)),
            lengths.at[slot].set(skip),
            pt.at[slot].set(pt_row),
        )
        if dpt is not None:
            out = out + (dpt.at[slot].set(dpt_row),)
        return out

    def _inject_rows_fn(self, temps, top_ks, top_ps, keys, lengths, toks,
                        pt, slot, temp, top_k, top_p, seed, length, tok,
                        pt_row):
        # re-derive the row state the prefill tier's final chunk left
        # behind instead of shipping it: generate()'s discipline is ONE
        # split of PRNGKey(seed) before the first token, so the decode
        # key is split(...)[0], the pending token is the shipped first
        # token, and the cursor sits at prompt_len — bit-identical to
        # the solo engine's post-prefill row by construction
        key0 = jax.random.split(jax.random.PRNGKey(seed))[0]
        return (
            temps.at[slot].set(temp),
            top_ks.at[slot].set(top_k),
            top_ps.at[slot].set(top_p),
            keys.at[slot].set(key0),
            lengths.at[slot].set(length),
            toks.at[slot].set(tok),
            pt.at[slot].set(pt_row),
        )

    def _decode_fn(self, params, cache, pt, toks, lengths, keys, temps,
                   top_ks, top_ps, active, n_pages):
        self.decode_compiles += 1
        self._decode_bucket_compiles[n_pages] = (
            self._decode_bucket_compiles.get(n_pages, 0) + 1
        )
        if self.config.decode_mode == "paged":
            # attend in place over the pool: decode_cache writes the
            # new token through per-page scatters (inactive rows drop
            # theirs) and attention streams the bucket-sliced tables —
            # no dense intermediate, no scatter-back; the model's
            # returned cache IS the updated pool
            ptb = jax.lax.slice_in_dim(pt, 0, n_pages, axis=1)
            with paged_view(PagedView(
                page_tables=ptb, keep=active,
                page_size=self.pool.page_size,
            )):
                last, cache = decode_step_body(
                    self.model, params, cache, toks,
                    cache_len=self.config.max_len,
                    positions=lengths[:, None],
                    write_pos=lengths,
                )
        else:
            dense = gather_pages(cache, pt)
            last, dense = decode_step_body(
                self.model, params, dense, toks,
                cache_len=self.config.max_len,
                positions=lengths[:, None],
                write_pos=lengths,
            )
            # persist ONLY the decoding rows' written token; free and
            # mid-prefill rows drop their write on the floor
            cache = scatter_kv(
                cache, dense, pt, lengths[:, None], active[:, None]
            )
        pair = jax.vmap(jax.random.split)(keys)  # [S, 2, 2]
        nxt = sample_logits_rows(last, pair[:, 1], temps, top_ks, top_ps)
        # advance ONLY the decoding rows in place: the continuing token
        # becomes next tick's input, the rng chain splits once, the
        # length grows one — inactive rows (free / mid-prefill) keep
        # their state so their request transitions stay host-authored
        toks_out = jnp.where(active, nxt, toks)
        lengths_out = lengths + active.astype(jnp.int32)
        keys_out = jnp.where(active[:, None], pair[:, 0], keys)
        return cache, nxt, toks_out, lengths_out, keys_out

    def _spec_fn(self, params, dparams, cache, dcache, pt, dpt, toks,
                 lengths, keys, temps, top_ks, top_ps, active, n_pages):
        """The fused speculative tick: k draft proposals -> one [S, k+1]
        target verify -> per-row acceptance -> page scatters.

        Greedy rows accept the longest prefix where the target's own
        argmax agrees (output EXACTLY the target's greedy stream);
        sampled rows run Leviathan rejection sampling per row with that
        row's filtered distributions. Emits ``a+1`` tokens per active
        row; the host truncates at eos / max_new (any truncation
        retires the request, so device/host state never diverges for a
        row that keeps decoding).

        In paged mode the DRAFT keeps a dense view — its k sequential
        single-token steps re-read the whole live context every step,
        the one shape a dense span still wins — but bucket-sliced to
        ``n_pages`` instead of ``max_len``-wide; the target verify
        attends in place over the pool like the plain tick, with the
        ``[S, k+1]`` query block riding the same paged primitive.
        """
        self.decode_compiles += 1
        self._decode_bucket_compiles[n_pages] = (
            self._decode_bucket_compiles.get(n_pages, 0) + 1
        )
        k = self.spec.num_draft_tokens
        S = self.config.num_slots
        max_len = self.config.max_len
        paged = self.config.decode_mode == "paged"
        width = n_pages * self.pool.page_size
        dpt = jax.lax.slice_in_dim(dpt, 0, n_pages, axis=1)
        idx = jnp.arange(k + 1)[None, :]
        pair = jax.vmap(jax.random.split)(keys)   # [S, 2, 2]
        ticket = pair[:, 1]  # per-row key budget for this tick's draws
        greedy_row = temps <= 0
        # the sampled machinery (per-row filtered distributions — a
        # vocab sort per position — plus rejection sampling) is real
        # compute the all-greedy steady state shouldn't pay: one
        # runtime branch skips it when no live row samples
        any_sampled = jnp.any(~greedy_row)

        dense_d = gather_pages(dcache, dpt)

        def dstep(carry, j):
            dense_d, tok = carry
            logits, dense_d = decode_step_body(
                self.spec.draft_model, dparams, dense_d, tok,
                cache_len=width,
                positions=(lengths + j)[:, None],
                write_pos=lengths + j,
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def propose_sampled(lg):
                filt = filter_logits_rows(lg, temps, top_ks, top_ps)
                sub = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                    ticket, 1 + j
                )
                sampled = jax.vmap(
                    lambda kk, row: jax.random.categorical(
                        kk, row, axis=-1
                    )
                )(sub, filt).astype(jnp.int32)
                return (
                    jnp.where(greedy_row, greedy, sampled),
                    jax.nn.softmax(filt, axis=-1),
                )

            nxt, q = jax.lax.cond(
                any_sampled, propose_sampled,
                lambda lg: (greedy, jnp.zeros(lg.shape, jnp.float32)),
                logits,
            )
            return (dense_d, nxt), (nxt, q)

        (dense_d, last_prop), (drafts, qs) = jax.lax.scan(
            dstep, (dense_d, toks), jnp.arange(k), length=k
        )
        # one sampling-free feed caches the FINAL proposal's K/V
        # (speculative.py's dfill, carried over): a fully accepted
        # round advances past position lengths+k, and without this
        # write that position would hold a permanent hole the draft
        # attends forever after — acceptance quietly degrades while
        # emitted tokens stay correct. For partial acceptance the
        # entry is rejected-tail garbage the next round overwrites
        # before any query reaches it, like every other rejected slot.
        _, dense_d = decode_step_body(
            self.spec.draft_model, dparams, dense_d, last_prop,
            cache_len=width,
            positions=(lengths + k)[:, None],
            write_pos=lengths + k,
        )
        drafts = drafts.T                      # [S, k]
        q_probs = jnp.moveaxis(qs, 0, 1)       # [S, k, V]
        dpos = lengths[:, None] + jnp.arange(k + 1)[None, :]
        dcache = scatter_kv(
            dcache, dense_d, dpt, dpos,
            active[:, None] & jnp.ones((1, k + 1), bool),
        )

        # ---- verify: one chunked target pass scores the proposal ----
        chunk = jnp.concatenate([toks[:, None], drafts], axis=1)
        if paged:
            # the [S, k+1] verify attends in place over the pool: the
            # k+1 K/V entries land via per-page scatters (inactive rows
            # dropped) and the paged primitive streams the bucket
            ptb = jax.lax.slice_in_dim(pt, 0, n_pages, axis=1)
            with paged_view(PagedView(
                page_tables=ptb, keep=active,
                page_size=self.pool.page_size,
            )):
                logits, st = self.model.apply(
                    {"params": params, "cache": cache},
                    chunk, decode=True, cache_len=max_len,
                    mutable=["cache"],
                    positions=lengths[:, None] + idx,
                    write_pos=lengths,
                )
            cache = st["cache"]
        else:
            dense_t = gather_pages(cache, pt)
            logits, st = self.model.apply(
                {"params": params, "cache": dense_t},
                chunk, decode=True, cache_len=max_len,
                mutable=["cache"],
                positions=lengths[:, None] + idx,
                write_pos=lengths,
            )
            vpos = lengths[:, None] + idx
            cache = scatter_kv(
                cache, st["cache"], pt, vpos,
                active[:, None] & jnp.ones((1, k + 1), bool),
            )

        # ---- acceptance ----
        # greedy: the longest draft prefix matching the target's own
        # argmax chain, correction = the target's next choice — the
        # emitted stream IS target-greedy, token for token
        preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, k+1]
        match = drafts == preds[:, :k]
        a_g = jnp.sum(
            jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
        )
        corr_g = jnp.take_along_axis(preds, a_g[:, None], axis=1)[:, 0]

        def accept_sampled(lg):
            # Leviathan rejection sampling per row with the row's own
            # filtered target/draft distributions and its own key chain
            p_filt = jax.vmap(
                lambda col: filter_logits_rows(
                    col, temps, top_ks, top_ps
                ),
                in_axes=1, out_axes=1,
            )(lg)
            p_probs = jax.nn.softmax(p_filt, axis=-1)  # [S, k+1, V]
            acc_keys = jax.vmap(
                jax.random.fold_in, in_axes=(0, None)
            )(ticket, 0)
            a_s, corr_s = jax.vmap(
                lambda p, q, d, kk: speculative_accept(
                    p[None], q[None], d[None], kk
                )
            )(p_probs, q_probs, drafts, acc_keys)
            return (
                jnp.where(greedy_row, a_g, a_s[:, 0]),
                jnp.where(greedy_row, corr_g, corr_s[:, 0]),
            )

        a, corr = jax.lax.cond(
            any_sampled, accept_sampled, lambda lg: (a_g, corr_g),
            logits,
        )

        drafts_ext = jnp.concatenate(
            [drafts, jnp.zeros((S, 1), jnp.int32)], axis=1
        )
        emit = jnp.where(idx < a[:, None], drafts_ext, corr[:, None])
        # the correction is the round's last emitted token — next
        # tick's input, its KV not yet written (it was an OUTPUT), so
        # next tick's chunk write at the new length caches it and
        # overwrites the first rejected entry in the same stroke
        toks_out = jnp.where(active, corr, toks)
        lengths_out = lengths + jnp.where(
            active, a + 1, jnp.zeros_like(a)
        )
        keys_out = jnp.where(active[:, None], pair[:, 0], keys)
        # accepted count rides as one extra column so the host pays a
        # SINGLE device fetch per tick (two syncs measurably hurt the
        # dispatch-bound regime speculation targets)
        emit_acc = jnp.concatenate([emit, a[:, None]], axis=1)
        return (
            cache, dcache, emit_acc, toks_out, lengths_out, keys_out,
        )

    # -- intake ------------------------------------------------------------
    def _validate_request(self, request: Request) -> None:
        cfg = self.config
        P = request.prompt_len
        chunks = -(-P // cfg.prefill_chunk)  # ceil
        if chunks * cfg.prefill_chunk > cfg.max_len:
            # the final chunk's [C]-wide write would clamp at the buffer
            # edge and corrupt earlier positions — refuse up front
            raise ValueError(
                f"prompt ({P} tokens) rounds up to "
                f"{chunks * cfg.prefill_chunk} chunked-prefill slots, "
                f"exceeding max_len {cfg.max_len}"
            )
        if P + request.max_new_tokens + self._spec_tail > cfg.max_len:
            tail_note = (
                f" + {self._spec_tail} speculative-verify slots"
                if self._spec_tail else ""
            )
            raise ValueError(
                f"prompt ({P}) + max_new_tokens "
                f"({request.max_new_tokens}){tail_note} exceeds the "
                f"engine's max_len {cfg.max_len}"
            )

    def submit(self, request: Request) -> RequestHandle:
        """Validate + enqueue; returns the streaming handle."""
        if self.role == "decode":
            raise RuntimeError(
                "decode-tier engines take work via inject_migration() "
                "only — route submissions to a prefill or solo engine"
            )
        self._validate_request(request)
        handle = RequestHandle(request, submitted_at=self._clock())
        if request.deadline_s is not None:
            self._n_deadlines += 1
        self.scheduler.enqueue(handle)
        self.telemetry.record_submit(handle)
        return handle

    def cancel(self, request_id: str) -> bool:
        """Flag a live request for eviction at the next step."""
        h = self.scheduler.find(request_id)
        if h is None:
            return False
        h._cancel = True
        self._any_cancel = True
        return True

    # -- migration intake (decode/solo roles) ------------------------------
    def inject_migration(
        self, frame: MigrationFrame, submitted_at: Optional[float] = None,
    ) -> RequestHandle:
        """Adopt a prefill-tier frame: fingerprint-check it, rebuild the
        ``Request``, and queue it for direct-to-DECODING admission at
        the next ``step()``. ``submitted_at`` (the router's original
        submit time) keeps TTFT honest across the tier hand-off."""
        if self.role == "prefill":
            raise RuntimeError(
                "prefill-tier engines ship frames via outbox; they do "
                "not accept them"
            )
        if frame.signature != self.migration_signature:
            raise MigrationError(
                "migration frame geometry mismatch: this pool is "
                f"{self.migration_signature!r}, frame declares "
                f"{frame.signature!r} — refusing the splice"
            )
        req = request_from_wire(frame.request)
        self._validate_request(req)
        if frame.prompt_len != req.prompt_len:
            raise MigrationError(
                f"frame prompt_len {frame.prompt_len} disagrees with "
                f"its own request ({req.prompt_len} tokens)"
            )
        want_pages = -(-frame.prompt_len // self.pool.page_size)
        if frame.n_pages != want_pages:
            raise MigrationError(
                f"frame ships {frame.n_pages} pages but a "
                f"{frame.prompt_len}-token prompt spans {want_pages} "
                f"at page_size {self.pool.page_size}"
            )
        h = RequestHandle(
            req,
            submitted_at=(
                self._clock() if submitted_at is None else submitted_at
            ),
        )
        if req.deadline_s is not None:
            self._n_deadlines += 1
        h._mig_frame = frame
        self._inject_backlog.append(h)
        self.telemetry.record_submit(h)
        return h

    def _admit_injected(self, h: RequestHandle) -> bool:
        """Bind an injected handle to a slot: allocate the same span the
        solo path would (chunk-rounded prompt + max_new + tail), splice
        the frame's page bytes in, and write the decode row the prefill
        tier's final chunk would have left — the handle enters DECODING
        with no prefill pass. Returns False when no slot/pages fit yet
        (strict FIFO over the backlog, like the queue)."""
        frame: MigrationFrame = h._mig_frame
        req = h.request
        # keys=[] disables BOTH the shared-prefix walk and registration:
        # the arriving pages are private splices, and registering them
        # would advertise pages this engine never hashed. Delta
        # migration (shipping only the pages the decode side lacks) is
        # the documented future step.
        lease = self.pool.allocate(
            req.prompt_ids, max_new=req.max_new_tokens,
            chunk=self.config.prefill_chunk, tail=self._spec_tail,
            keys=[],
        )
        if lease is None:
            return False
        self.scheduler.adopt(h, lease)
        pages = np.asarray(lease.page_row[:frame.n_pages], np.int32)
        span = (
            tracing._NULL_SPAN if tracing._tracer is None
            else tracing.span(
                "serve.migrate_in", request=req.request_id,
                pages=int(frame.n_pages), nbytes=frame.payload_nbytes,
            )
        )
        with span:
            self.pool.cache = splice_frames(
                self.pool.cache, pages, frame.payload
            )
        self.pool.lengths[lease.slot] = frame.prompt_len
        (
            self._temps, self._top_ks, self._top_ps, self._keys,
            self._lengths, self._toks, self._pt,
        ) = self._inject_rows(
            self._temps, self._top_ks, self._top_ps, self._keys,
            self._lengths, self._toks, self._pt, lease.slot,
            req.temperature,
            TOP_K_OFF if req.top_k is None else req.top_k,
            TOP_P_OFF if req.top_p is None else req.top_p,
            req.seed, frame.prompt_len, frame.first_token,
            lease.page_row,
        )
        self._decoding_dirty = True
        self.migrated_in += 1
        h._mig_frame = None
        self._emit(h, int(frame.first_token))
        return True

    def _drain_inject_backlog(self) -> None:
        now = self._clock()
        while self._inject_backlog:
            h = self._inject_backlog[0]
            if h.done:  # cancelled/expired while waiting
                self._inject_backlog.popleft()
                continue
            if h.deadline_at is not None and now >= h.deadline_at:
                self._inject_backlog.popleft()
                self._finish(h, RequestStatus.EXPIRED)
                continue
            if not self._admit_injected(h):
                break
            self._inject_backlog.popleft()

    # -- the loop ----------------------------------------------------------
    def has_work(self) -> bool:
        # O(1): the drive loop asks once per step — no live-handle list
        return bool(
            self.scheduler.queue or self.scheduler.by_slot
            or self._inject_backlog
        )

    def step(self) -> bool:
        """One scheduler iteration; returns True when any device work
        ran (a prefill chunk or a decode tick)."""
        self._steps += 1
        # the sweeps scan every live handle — skip them entirely on the
        # (typical) ticks where no deadline or cancellation exists
        if self._n_deadlines:
            now = self._clock()
            for h in self.scheduler.sweep_expired(now):
                self._finish(h, RequestStatus.EXPIRED)
        if self._any_cancel:
            self._any_cancel = False
            for h in self.scheduler.sweep_cancelled():
                self._finish(h, RequestStatus.CANCELLED)
        if self._inject_backlog:
            self._drain_inject_backlog()
        if self._store is not None and self.scheduler.queue:
            self._adopt_from_store()
        for h in self.scheduler.admit(
            self.pool, self.draft_pool, tail=self._spec_tail
        ):
            with tracing.span(
                "serve.admit", request=h.request.request_id
            ):
                self._configure_slot(h)
        did = self._run_prefill()
        did = self._run_decode() or did
        if self.config.telemetry_every and (
            self._steps % self.config.telemetry_every == 0
        ):
            self._snapshot()
        return did

    # -- length buckets + analytic HBM accounting --------------------------
    def _compile_note(self, kind: str, n_pages: int) -> str:
        """Recompile-sentinel key: per bucket when buckets exist (each
        bucket is its own program with its own once-contract); the
        round-11 plain name when exactly one width exists."""
        if len(self._buckets) == 1:
            return f"serve.{kind}"
        return f"serve.{kind}[b{n_pages}]"

    def _tick_bucket(self, decoding) -> int:
        """The static page width this tick's programs run at: the
        smallest bucket covering every ACTIVE row's reads and writes
        (max live length + the tick's write span). Inactive rows may
        point beyond it — their reads are discarded and their writes
        dropped, so the clamp is harmless by construction."""
        if self.config.decode_mode == "dense":
            return self.pool.max_pages
        if resolve_paged_attention_impl() != self._resolved_impl:
            # set_paged_attention_impl() cleared the jit caches: the
            # next dispatch would retrace (breaking the compiled-once-
            # per-bucket contract) while the analytic byte model kept
            # pricing the OLD backend — refuse loudly instead of
            # silently desynchronizing both
            raise RuntimeError(
                f"paged-attention impl changed under a live engine "
                f"(engine resolved {self._resolved_impl!r}, flag now "
                f"resolves {resolve_paged_attention_impl()!r}) — "
                f"construct a new ServeEngine after "
                f"set_paged_attention_impl()"
            )
        W = 1 if self.spec is None else self.spec.num_draft_tokens + 1
        need = max(
            int(self.pool.lengths[slot]) for slot, _ in decoding
        ) + W
        return self._bucket_for(-(-need // self.pool.page_size))

    def _tick_cost(self, n_pages: int):
        """(gather_bytes, total_hbm_bytes) one decode tick moves under
        the active mode/impl's analytic traffic model (DESIGN.md §17) —
        cached per bucket so the per-tick cost is two integer adds."""
        cost = self._tick_cost_cache.get(n_pages)
        if cost is None:
            S = self.config.num_slots
            fb = self._frame_bytes_target
            # gather traffic = the dense intermediate (pool read +
            # dense write); the attention stream reads each page once
            gather = attn = 0
            if self._attn_impl in ("dense", "gather"):
                gather += 2 * S * n_pages * fb
            attn += S * n_pages * fb
            if self.spec is not None:
                # the draft keeps a (bucketed) dense view: one gather,
                # k+1 proposal steps + the fill feed each re-read it
                k = self.spec.num_draft_tokens
                dfb = self._frame_bytes_draft
                gather += 2 * S * n_pages * dfb
                attn += (k + 2) * S * n_pages * dfb
            cost = (gather, gather + attn)
            self._tick_cost_cache[n_pages] = cost
        return cost

    @property
    def decode_buckets(self):
        """Bucket widths (pages) the decode tick has compiled at."""
        return set(self._decode_bucket_compiles)

    @property
    def prefill_buckets(self):
        return set(self._prefill_bucket_compiles)

    @property
    def decode_hbm_bytes_per_token(self) -> float:
        """Analytic decode-path HBM bytes per emitted token — the
        number the dense-gather path roughly doubled and this round's
        paged attention removes (serve.decode_hbm_bytes_per_token
        tracing counter / bench serving_paged_attn phase)."""
        return self.decode_hbm_bytes / max(self._decode_tokens, 1)

    def precompile_decode_buckets(self) -> None:
        """Compile every decode-tick bucket with a no-op dispatch so
        serving never pays a compile mid-measurement.

        All rows ride as INACTIVE: pool writes are dropped by the keep
        gate, and toks/lengths/keys pass through their ``where(active,
        ...)`` untouched — device state is semantically unchanged. The
        analytic byte counters are left alone (nothing was served).
        ``serve.loadgen.warm_up`` calls this after its warm request; a
        test driving the engine directly still sees one compile per
        OCCUPIED bucket.
        """
        idle = jnp.zeros(self.config.num_slots, bool)
        for n in self._buckets:
            if self.spec is None:
                (
                    self.pool.cache, _, self._toks, self._lengths,
                    self._keys,
                ) = self._decode(
                    self.params, self.pool.cache, self._pt, self._toks,
                    self._lengths, self._keys, self._temps,
                    self._top_ks, self._top_ps, idle, n,
                )
            else:
                (
                    self.pool.cache, self.draft_pool.cache, _,
                    self._toks, self._lengths, self._keys,
                ) = self._spec_tick(
                    self.params, self.spec.draft_params,
                    self.pool.cache, self.draft_pool.cache,
                    self._pt, self._dpt, self._toks, self._lengths,
                    self._keys, self._temps, self._top_ks,
                    self._top_ps, idle, n,
                )

    def _snapshot(self) -> None:
        pool = self.pool
        gauges = dict(
            pages_in_use=pool.pages_in_use,
            pages_total=pool.num_pages,
            page_occupancy=(
                pool.pages_in_use / pool.num_pages if pool.num_pages
                else 0.0
            ),
            prefix_hit_rate=pool.prefix_hit_rate,
            decode_gather_bytes=self.decode_gather_bytes,
            decode_hbm_bytes_per_token=round(
                self.decode_hbm_bytes_per_token, 1
            ),
        )
        if self.spec is not None:
            gauges.update(
                spec_verifies=self.spec_verifies,
                spec_drafted=self.spec_drafted,
                spec_accepted=self.spec_accepted,
            )
        self.telemetry.record_snapshot(
            queue_depth=self.scheduler.queue_depth(),
            slots_occupied=pool.num_occupied,
            slots_total=pool.num_slots,
            decode_ticks=self._decode_ticks,
            **gauges,
        )
        if tracing._tracer is not None:
            tracing.counter("serve.kv_pages_in_use", pool.pages_in_use)
            tracing.counter(
                "serve.kv_page_occupancy", gauges["page_occupancy"]
            )
            tracing.counter(
                "serve.prefix_hit_rate", pool.prefix_hit_rate
            )
            # the decode-path gather tax (and its removal) as recorded
            # facts — plain precomputed ints, armed-only emission
            tracing.counter(
                "serve.decode_gather_bytes", self.decode_gather_bytes
            )
            tracing.counter(
                "serve.decode_hbm_bytes_per_token",
                gauges["decode_hbm_bytes_per_token"],
            )
            if self.spec is not None and self.spec_verifies:
                tracing.counter(
                    "serve.spec_accepted_per_verify",
                    self.spec_accepted / self.spec_verifies,
                )

    def run_until_drained(self, max_steps: int = 1_000_000) -> None:
        """Step until every submitted request reaches a terminal state."""
        for _ in range(max_steps):
            if not self.has_work():
                return
            self.step()
        raise RuntimeError(
            f"engine did not drain within {max_steps} steps "
            f"({len(self.scheduler.live_handles())} requests live)"
        )

    # -- cross-engine prefix store (r18) -----------------------------------
    def _adopt_from_store(self) -> None:
        """Walk each queued request's chain keys once: pages the FLEET
        already prefilled (store hit) but this pool doesn't hold are
        claimed (``adopt_page``) and spliced in, so the normal
        ``allocate`` path then shares them copy-free — the hot system
        prompt is prefilled once per fleet, not once per engine. Stops
        at the first miss (chain contiguity); any failure to claim a
        page is a skipped optimization, never an error."""
        pool = self.pool
        if not pool.prefix_cache:
            return
        # a handle is re-walked only when the store has grown since its
        # last walk (``puts`` moved): a queued request that missed
        # yesterday adopts the page a PEER published today, and the
        # steady state pays zero store traffic per step
        version = getattr(self._store, "puts", None)
        for h in self.scheduler.queue:
            if getattr(h, "_store_walked", None) == version:
                continue
            h._store_walked = version
            req = h.request
            if h._chain_keys is None:
                h._chain_keys = pool.chain_keys(req.prompt_ids)
            cap = (req.prompt_len - 1) // pool.page_size
            for key in h._chain_keys[:cap]:
                if key in pool._registry:
                    continue  # already local (own prefill or adoption)
                payload = self._store.get(
                    key, holder=self._holder,
                    signature=self.migration_signature,
                )
                if payload is None:
                    break
                pg = pool.adopt_page(key)
                if pg is None:
                    break
                pool.cache = splice_frames(
                    pool.cache, np.asarray([pg], np.int32), payload
                )
                self.store_adopted_pages += 1

    def _publish_prefixes(self, h: RequestHandle) -> None:
        """Push the finished prompt's full pages the store lacks (first
        writer wins — a racing peer's duplicate is dropped unread)."""
        lease = h._lease
        row = self.pool.page_tables[lease.slot]
        for i, key in enumerate(lease.page_keys):
            if key in self._store:
                continue
            payload = extract_frames(
                self.pool.cache, np.asarray([row[i]], np.int32)
            )
            if self._store.put(
                key, payload, holder=self._holder,
                signature=self.migration_signature,
            ):
                self.store_published_pages += 1

    # -- migration packing (prefill role) ----------------------------------
    def _pack_migration(self, h: RequestHandle, first_token: int):
        """Freeze a finished prefill into a MigrationFrame — called
        strictly BEFORE ``_finish`` releases the slot (packing reads
        the live pages). Ships ``ceil(P / page_size)`` pages: every
        position < P lives there; bytes beyond P in the last page are
        garbage on BOTH tiers and never attended before overwrite."""
        req = h.request
        lease = h._lease
        n = -(-req.prompt_len // self.pool.page_size)
        pages = np.asarray(
            self.pool.page_tables[lease.slot][:n], np.int32
        )
        payload = extract_frames(self.pool.cache, pages)
        return MigrationFrame(
            request=request_to_wire(req),
            first_token=int(first_token),
            prompt_len=req.prompt_len,
            n_pages=n,
            signature=self.migration_signature,
            payload=payload,
            src_engine=self.engine_id or "",
        )

    # -- phase bodies ------------------------------------------------------
    def _run_prefill(self) -> bool:
        cfg = self.config
        plans = self.scheduler.plan_prefill(cfg.prefill_chunks_per_step)
        did = False
        for plan in plans:
            h = plan.handle
            if h.done:  # evicted earlier in this very step's plan list
                continue
            if faults.active():
                try:
                    faults.check("serve.prefill", path=h.request.request_id)
                except faults.InjectedFault as e:
                    self._finish(h, RequestStatus.FAILED, error=e)
                    continue
            ids = np.zeros((1, cfg.prefill_chunk), np.int32)
            ids[0, :plan.chunk_len] = plan.ids
            slot = h.slot
            # the chunk can reach positions [0, start + C): gather the
            # smallest bucket covering them, not the max_len-wide row
            n_pages = self._bucket_for(
                -(-(plan.start + cfg.prefill_chunk)
                  // self.pool.page_size)
            )
            # scalars pass as plain python values (weak-typed, no
            # retrace); ALL slot-row updates — per-chunk length cursor,
            # final-chunk key/token persist — happen inside the one
            # compiled program (eager .at[].set is ms-scale here)
            with tracing.span(
                "serve.prefill_chunk", request=h.request.request_id
            ):
                if self.spec is None:
                    tok = self._dispatch_prefill(ids, slot, plan, n_pages)
                else:
                    tok = self._dispatch_prefill_spec(
                        ids, slot, plan, n_pages
                    )
            # the recompile sentinel's once-contract is per PROGRAM —
            # with buckets, a bucket IS a program, so single-bucket
            # engines keep the plain name and multi-bucket engines get
            # one sentinel key per bucket (one shared key would let a
            # recompile of bucket A mask a later recompile of bucket B)
            # (armed-only: the lookups are not disarmed-trivial args)
            if tracing._tracer is not None:
                tracing.note_compiles(
                    self._compile_note("prefill", n_pages),
                    self._prefill_bucket_compiles.get(n_pages),
                )
            self.pool.lengths[slot] = plan.start + plan.chunk_len
            if cfg.prefill_delay_s:
                time.sleep(cfg.prefill_delay_s * plan.chunk_len)
            did = True
            if plan.final:
                # the slot's full prompt pages now hold canonical KV —
                # publish them for copy-free sharing by later admissions
                self.pool.register_prefix(h._lease, h.request.prompt_ids)
                if self.draft_pool is not None:
                    self.draft_pool.register_prefix(
                        h._dlease, h.request.prompt_ids
                    )
                if self._store is not None:
                    self._publish_prefixes(h)
                if self.role == "prefill":
                    # tier hand-off: pack the prompt's pages + the first
                    # token into a frame, park it in the outbox for the
                    # router, and retire the request here as MIGRATED —
                    # it continues on a decode-tier peer
                    try:
                        if faults.active():
                            faults.check(
                                "serve.kv_migrate",
                                path=h.request.request_id,
                            )
                        frame = self._pack_migration(h, int(tok))
                    except faults.InjectedFault as e:
                        self._finish(h, RequestStatus.FAILED, error=e)
                        continue
                    self.outbox.append(frame)
                    self.migrated_out += 1
                    self._finish(h, RequestStatus.MIGRATED)
                    continue
                self.scheduler.prefill_finished(h)
                self._decoding_dirty = True
                self._emit(h, int(tok))
        return did

    def _run_decode(self) -> bool:
        if self._decoding_dirty:
            self._decoding_cached = self.scheduler.decoding()
            active = np.zeros(self.config.num_slots, bool)
            for slot, _ in self._decoding_cached:
                active[slot] = True
            self._active_cached = jnp.asarray(active)
            self._decoding_dirty = False
        decoding = self._decoding_cached
        if not decoding:
            return False
        self._decode_ticks += 1
        n_pages = self._tick_bucket(decoding)
        if self.spec is not None:
            return self._run_spec_tick(decoding, n_pages)
        # one jit call; toks/lengths/keys advance in-program for the
        # active rows, so the only per-tick host traffic is the sampled
        # tokens coming down
        # armed-only arg evaluation (PTD002): the steady-state tick is
        # the serving hot path — disarmed cost stays one is-None test
        span = (
            tracing._NULL_SPAN if tracing._tracer is None
            else tracing.span("serve.decode_tick", active=len(decoding))
        )
        with span:
            (
                self.pool.cache, nxt, self._toks, self._lengths,
                self._keys,
            ) = self._decode(
                self.params, self.pool.cache, self._pt, self._toks,
                self._lengths, self._keys, self._temps, self._top_ks,
                self._top_ps, self._active_cached, n_pages,
            )
        if tracing._tracer is not None:  # armed-only arg evaluation
            tracing.note_compiles(
                self._compile_note("decode", n_pages),
                self._decode_bucket_compiles.get(n_pages),
            )
        gb, hb = self._tick_cost(n_pages)
        self.decode_gather_bytes += gb
        self.decode_hbm_bytes += hb
        self._decode_tokens += len(decoding)
        if self.config.decode_delay_s:
            time.sleep(self.config.decode_delay_s * len(decoding))
        with tracing.span("serve.token_fetch"):
            # the one per-tick device sync: every sampled token comes down
            nxt = np.asarray(nxt)
        fault_armed = faults.active()
        for slot, h in decoding:
            # the tick wrote this slot's token at lengths[slot]; mirror
            # the in-program length advance, then judge the token
            self.pool.lengths[slot] += 1
            if fault_armed:
                try:
                    faults.check("serve.decode", path=h.request.request_id)
                except faults.InjectedFault as e:
                    self._finish(h, RequestStatus.FAILED, error=e)
                    continue
            self._emit(h, int(nxt[slot]))
        return True

    def _dispatch_prefill(self, ids, slot, plan, n_pages):
        """One plain prefill-chunk dispatch; the donated pool buffer is
        rebound to its returned successor before anything reads it."""
        (
            cache, tok, self._toks, self._lengths, self._keys,
        ) = self._prefill(
            self.params, self.pool.cache, self._pt, ids,
            slot, plan.start, plan.chunk_len - 1, plan.final,
            self._toks, self._lengths, self._keys,
            self._temps, self._top_ks, self._top_ps, n_pages,
        )
        self.pool.cache = cache
        return tok

    def _dispatch_prefill_spec(self, ids, slot, plan, n_pages):
        """One fused target+draft prefill-chunk dispatch; both donated
        pool buffers rebind to their returned successors."""
        (
            cache, dcache, tok, self._toks, self._lengths, self._keys,
        ) = self._prefill_spec(
            self.params, self.spec.draft_params,
            self.pool.cache, self.draft_pool.cache,
            self._pt, self._dpt, ids,
            slot, plan.start, plan.chunk_len - 1, plan.final,
            self._toks, self._lengths, self._keys,
            self._temps, self._top_ks, self._top_ps, n_pages,
        )
        self.pool.cache = cache
        self.draft_pool.cache = dcache
        self.draft_pool.lengths[slot] = plan.start + plan.chunk_len
        return tok

    def _run_spec_tick(self, decoding, n_pages) -> bool:
        """One fused draft+verify tick; emits 1..k+1 tokens/request."""
        span = (
            tracing._NULL_SPAN if tracing._tracer is None
            else tracing.span(
                "serve.spec_tick", active=len(decoding),
                k=self.spec.num_draft_tokens,
            )
        )
        with span:
            (
                self.pool.cache, self.draft_pool.cache, emit_acc,
                self._toks, self._lengths, self._keys,
            ) = self._spec_tick(
                self.params, self.spec.draft_params,
                self.pool.cache, self.draft_pool.cache,
                self._pt, self._dpt, self._toks, self._lengths,
                self._keys, self._temps, self._top_ks, self._top_ps,
                self._active_cached, n_pages,
            )
        if tracing._tracer is not None:  # armed-only arg evaluation
            tracing.note_compiles(
                self._compile_note("decode", n_pages),
                self._decode_bucket_compiles.get(n_pages),
            )
        gb, hb = self._tick_cost(n_pages)
        self.decode_gather_bytes += gb
        self.decode_hbm_bytes += hb
        with tracing.span("serve.token_fetch"):
            # ONE per-tick device sync: k+1 emit columns + the
            # accepted count packed into a single [S, k+2] fetch
            emit_acc = np.asarray(emit_acc)
        emit, acc = emit_acc[:, :-1], emit_acc[:, -1]
        k = self.spec.num_draft_tokens
        self.spec_verifies += 1
        fault_armed = faults.active()
        for slot, h in decoding:
            n = int(acc[slot]) + 1
            self._decode_tokens += n
            # mirror the in-program advances: the verify wrote k+1
            # entries but only a+1 became sequence; the rejected tail
            # sits beyond the accepted length where the next tick's
            # chunk write lands before anything attends it
            self.pool.lengths[slot] += n
            self.draft_pool.lengths[slot] += n
            self.spec_drafted += k
            self.spec_accepted += n - 1
            if fault_armed:
                try:
                    faults.check("serve.decode", path=h.request.request_id)
                except faults.InjectedFault as e:
                    self._finish(h, RequestStatus.FAILED, error=e)
                    continue
            for j in range(n):
                self._emit(h, int(emit[slot, j]))
                if h.done:  # eos / max_new truncation retires the row
                    break
        return True

    # -- emission / retirement ---------------------------------------------
    def _emit(self, h: RequestHandle, token: int) -> None:
        now = self._clock()
        h.emit(token, now)
        req = h.request
        # continuing requests need no device write here: the decode tick
        # already advanced the slot's token/length/key rows in-program
        if req.eos_id is not None and token == req.eos_id:
            self._finish(h, RequestStatus.COMPLETED)
        elif len(h.tokens) >= req.max_new_tokens:
            self._finish(h, RequestStatus.COMPLETED)

    def _finish(
        self,
        h: RequestHandle,
        status: RequestStatus,
        error: Optional[BaseException] = None,
    ) -> None:
        h.status = status
        h.error = error
        h.finished_at = self._clock()
        if h.request.deadline_s is not None:
            self._n_deadlines -= 1
        self._decoding_dirty = True
        with tracing.span(
            "serve.evict",
            request=h.request.request_id, status=status.value,
        ):
            self.scheduler.release(h, self.pool, self.draft_pool)
        self.telemetry.record_done(h)
        if status is RequestStatus.FAILED:
            logger.warning(
                "serve: evicted request %s after fault: %s",
                h.request.request_id, error,
            )

    # -- admission-time slot setup ----------------------------------------
    def _configure_slot(self, h: RequestHandle) -> None:
        req = h.request
        lease = h._lease
        dpt_row = (
            h._dlease.page_row if h._dlease is not None
            else np.zeros(0, np.int32)
        )
        out = self._admit_rows(
            self._temps, self._top_ks, self._top_ps, self._keys,
            self._lengths, self._pt,
            self._dpt, h.slot,
            req.temperature,
            TOP_K_OFF if req.top_k is None else req.top_k,
            TOP_P_OFF if req.top_p is None else req.top_p,
            req.seed, lease.skip, lease.page_row, dpt_row,
        )
        (
            self._temps, self._top_ks, self._top_ps, self._keys,
            self._lengths, self._pt,
        ) = out[:6]
        if self._dpt is not None:
            self._dpt = out[6]
