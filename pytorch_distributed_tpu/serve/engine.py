"""Continuous-batching serve loop: admit/retire mid-flight, compile once.

The engine composes the pieces: a ``KVSlotPool`` (static device state),
a ``Scheduler`` (host dynamism), per-row sampling, and TWO jitted
programs that are each compiled exactly once for the engine's lifetime:

* ``prefill``: one ``[1, prefill_chunk]`` model pass writing a chunk of
  one request's prompt into its slot (``write_pos`` per-row KV writes),
  sampling the first token on the final chunk;
* ``decode``: one ``[S, 1]`` tick over ALL slots — occupied, mid-
  prefill, or free — through the SAME ``generation.decode_step_body``
  the offline ``generate`` scan uses, then per-row sampling with each
  slot's own (temperature, top_k, top_p, rng).

Static-shape invariant: neither program's input shapes depend on which
requests are in flight. Rows without a decoding request still compute —
their sampled tokens are discarded on the host and their KV write lands
at the row's current length, a position that is either masked (free
slots, garbage until reuse overwrites from 0) or overwritten by the
next prefill chunk (mid-prefill slots). Compile counts are exposed
(``prefill_compiles``/``decode_compiles``) so tests can PIN "one
compile per program for a whole mixed workload".

Parity invariant: every request's emitted token stream is bit-identical
to a solo ``generate(prompt, ..., rng=jax.random.PRNGKey(seed))`` —
regardless of batch composition, slot reuse, chunked prefill splits, or
neighboring evictions. The load-bearing facts: batch rows are
independent under XLA, masked cache tails contribute exact zeros, the
per-row sampler is a bitwise transcript of ``generation.sample_logits``
(serve/sampling.py), and each request's rng chain splits exactly when
``generate``'s would (once at prefill, once per decode tick).

Failure model (degrade, don't crash): ``serve.prefill``/``serve.decode``
fault sites (runtime/faults.py) fire per-request — a poisoned request
is evicted as FAILED with the exception on its handle, its slot frees,
and the engine keeps serving everyone else.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.generation import (
    decode_step_body,
    model_max_len,
)
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.runtime import tracing
from pytorch_distributed_tpu.serve.kv_slots import (
    KVSlotPool,
    put_slot,
    take_slot,
)
from pytorch_distributed_tpu.serve.sampling import (
    TOP_K_OFF,
    TOP_P_OFF,
    sample_logits_rows,
)
from pytorch_distributed_tpu.serve.scheduler import (
    Request,
    RequestHandle,
    RequestStatus,
    Scheduler,
)
from pytorch_distributed_tpu.serve.telemetry import ServeTelemetry
from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    num_slots: int = 4          # S: max concurrent in-flight requests
    max_len: int = 256          # per-slot KV capacity (prompt + new)
    prefill_chunk: int = 32     # static prompt-chunk width
    prefill_chunks_per_step: int = 1  # prefill/decode interleave ratio
    telemetry_every: int = 32   # engine steps between occupancy snapshots

    def __post_init__(self):
        if self.num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self.prefill_chunks_per_step < 1:
            raise ValueError("prefill_chunks_per_step must be >= 1")
        if self.max_len < 2:
            raise ValueError("max_len must be >= 2 (1 prompt + 1 new)")
        if self.prefill_chunk > self.max_len:
            # every prompt rounds up to at least one chunk of KV slots,
            # so this config could never admit ANY request — fail at
            # construction naming the real culprit, not per-submit
            # blaming the prompt
            raise ValueError(
                f"prefill_chunk {self.prefill_chunk} > max_len "
                f"{self.max_len}: no request could ever be admitted"
            )


class ServeEngine:
    """Single-threaded, deterministic serve loop.

    Drive it with ``submit()`` + ``step()`` (one scheduler iteration:
    deadline sweep -> cancellations -> admission -> prefill chunks ->
    decode tick), or ``run_until_drained()``. Tokens stream into each
    ``RequestHandle.tokens`` as they are emitted (or via
    ``handle.on_token``).

    ``params`` may be placed by any ``parallel/strategies.py`` strategy
    — the jitted programs follow the committed shardings (TP rules
    shard the per-slot compute exactly as they shard ``generate``).
    """

    def __init__(
        self,
        model,
        params,
        config: EngineConfig = EngineConfig(),
        *,
        telemetry: Optional[ServeTelemetry] = None,
        clock=time.monotonic,
    ):
        self.model = model
        self.params = params
        self.config = config
        self.telemetry = telemetry or ServeTelemetry(clock=clock)
        self._clock = clock
        limit = model_max_len(model)
        if limit is not None and config.max_len > limit:
            raise ValueError(
                f"max_len {config.max_len} exceeds the model's maximum "
                f"sequence length {limit}"
            )
        self.pool = KVSlotPool(
            model, params, config.num_slots, config.max_len
        )
        self.scheduler = Scheduler(config.num_slots, config.prefill_chunk)
        S = config.num_slots
        # per-slot sampling/decode state lives ON DEVICE and is updated
        # in place: rows change only at request transitions (admission,
        # prefill-final, eviction), and the decode tick advances the
        # continuing rows inside the jitted program — so a steady-state
        # tick is ONE jit call plus one token fetch, no per-tick
        # host->device re-uploads (measured 2ms/tick of pure host
        # overhead before this). Stale rows of freed/mid-prefill slots
        # are harmless: their sampled tokens are discarded and their KV
        # writes land at positions that are overwritten before any mask
        # lets attention read them.
        self._toks = jnp.zeros(S, jnp.int32)
        self._lengths = jnp.zeros(S, jnp.int32)
        self._temps = jnp.zeros(S, jnp.float32)
        self._top_ks = jnp.full(S, TOP_K_OFF, jnp.int32)
        self._top_ps = jnp.full(S, TOP_P_OFF, jnp.float32)
        # old-style uint32 [2] keys: stackable/vmappable plain arrays
        # with the same threefry streams as jax.random.key
        self._keys = jnp.tile(jax.random.PRNGKey(0)[None, :], (S, 1))
        self._n_deadlines = 0  # live requests carrying a deadline
        self._any_cancel = False
        # the decoding set only changes at request transitions — cache
        # the (slot, handle) list and the device-side active mask so a
        # steady-state tick rebuilds neither
        self._decoding_dirty = True
        self._decoding_cached = []
        self._active_cached = None
        self._steps = 0
        self._decode_ticks = 0
        self.prefill_compiles = 0
        self.decode_compiles = 0
        # donation lets XLA update the pool cache in place; XLA:CPU
        # cannot alias and would warn every call, so gate on backend
        donate = jax.default_backend() != "cpu"
        self._prefill = jax.jit(
            self._prefill_fn, donate_argnums=(1,) if donate else ()
        )
        # cache + the in-program-advanced rows (toks/lengths/keys) are
        # donated: each is replaced by its returned successor every tick
        self._decode = jax.jit(
            self._decode_fn, donate_argnums=(1, 2, 3, 4) if donate else ()
        )
        # admission-time row setup as ONE jitted program: eager
        # .at[].set dispatches cost ~2.4ms EACH on this backend
        # (measured under cProfile — per-request transitions were half
        # the serving wall-clock), a fused compiled update is ~0.1ms
        self._admit_rows = jax.jit(self._admit_rows_fn)

    # -- jitted programs ---------------------------------------------------
    def _prefill_fn(self, params, cache, ids, slot, start, last_idx,
                    final, toks, lengths, keys, temps, top_ks, top_ps):
        # traced once per engine lifetime — python side effect counts
        # compiles (the static-shape invariant, pinned by tests)
        self.prefill_compiles += 1
        C = self.config.prefill_chunk
        row = take_slot(cache, slot)
        positions = (start + jnp.arange(C))[None, :]
        logits, state = self.model.apply(
            {"params": params, "cache": row},
            ids,
            decode=True,
            cache_len=self.config.max_len,
            mutable=["cache"],
            positions=positions,
            write_pos=jnp.asarray(start, jnp.int32)[None],
        )
        cache = put_slot(cache, state["cache"], slot)
        # the device length cursor advances with EVERY chunk, not just
        # the final one: a decode tick between chunks writes this
        # inactive row's K/V at its cursor, and only a cursor at the
        # NEXT chunk's start keeps that garbage in a range the next
        # chunk overwrites — a stale cursor lands it on already-
        # prefilled positions (a measured corruption, caught by the
        # mixed-workload parity test)
        lengths = lengths.at[slot].set(start + last_idx + 1)
        # rng discipline mirrors generate(): ONE split before the first
        # token, persisted (with the token) only on the final chunk
        pair = jax.random.split(keys[slot])
        last = jax.lax.dynamic_index_in_dim(
            logits, last_idx, axis=1, keepdims=False
        )  # [1, V] — the chunk's last REAL prompt column
        tok = sample_logits_rows(
            last, pair[1][None], temps[slot][None],
            top_ks[slot][None], top_ps[slot][None],
        )[0]
        keys = jnp.where(final, keys.at[slot].set(pair[0]), keys)
        toks = jnp.where(final, toks.at[slot].set(tok), toks)
        return cache, tok, toks, lengths, keys

    def _admit_rows_fn(self, temps, top_ks, top_ps, keys, lengths, slot,
                       temp, top_k, top_p, seed):
        # the write cursor parks at 0 so any tick before the first
        # chunk drops its garbage where that chunk will overwrite it
        return (
            temps.at[slot].set(temp),
            top_ks.at[slot].set(top_k),
            top_ps.at[slot].set(top_p),
            keys.at[slot].set(jax.random.PRNGKey(seed)),
            lengths.at[slot].set(0),
        )

    def _decode_fn(self, params, cache, toks, lengths, keys, temps,
                   top_ks, top_ps, active):
        self.decode_compiles += 1
        last, cache = decode_step_body(
            self.model, params, cache, toks,
            cache_len=self.config.max_len,
            positions=lengths[:, None],
            write_pos=lengths,
        )
        pair = jax.vmap(jax.random.split)(keys)  # [S, 2, 2]
        nxt = sample_logits_rows(last, pair[:, 1], temps, top_ks, top_ps)
        # advance ONLY the decoding rows in place: the continuing token
        # becomes next tick's input, the rng chain splits once, the
        # length grows one — inactive rows (free / mid-prefill) keep
        # their state so their request transitions stay host-authored
        toks_out = jnp.where(active, nxt, toks)
        lengths_out = lengths + active.astype(jnp.int32)
        keys_out = jnp.where(active[:, None], pair[:, 0], keys)
        return cache, nxt, toks_out, lengths_out, keys_out

    # -- intake ------------------------------------------------------------
    def submit(self, request: Request) -> RequestHandle:
        """Validate + enqueue; returns the streaming handle."""
        cfg = self.config
        P = request.prompt_len
        chunks = -(-P // cfg.prefill_chunk)  # ceil
        if chunks * cfg.prefill_chunk > cfg.max_len:
            # the final chunk's [C]-wide write would clamp at the buffer
            # edge and corrupt earlier positions — refuse up front
            raise ValueError(
                f"prompt ({P} tokens) rounds up to "
                f"{chunks * cfg.prefill_chunk} chunked-prefill slots, "
                f"exceeding max_len {cfg.max_len}"
            )
        if P + request.max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt ({P}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the engine's "
                f"max_len {cfg.max_len}"
            )
        handle = RequestHandle(request, submitted_at=self._clock())
        if request.deadline_s is not None:
            self._n_deadlines += 1
        self.scheduler.enqueue(handle)
        self.telemetry.record_submit(handle)
        return handle

    def cancel(self, request_id: str) -> bool:
        """Flag a live request for eviction at the next step."""
        h = self.scheduler.find(request_id)
        if h is None:
            return False
        h._cancel = True
        self._any_cancel = True
        return True

    # -- the loop ----------------------------------------------------------
    def has_work(self) -> bool:
        # O(1): the drive loop asks once per step — no live-handle list
        return bool(self.scheduler.queue or self.scheduler.by_slot)

    def step(self) -> bool:
        """One scheduler iteration; returns True when any device work
        ran (a prefill chunk or a decode tick)."""
        self._steps += 1
        # the sweeps scan every live handle — skip them entirely on the
        # (typical) ticks where no deadline or cancellation exists
        if self._n_deadlines:
            now = self._clock()
            for h in self.scheduler.sweep_expired(now):
                self._finish(h, RequestStatus.EXPIRED)
        if self._any_cancel:
            self._any_cancel = False
            for h in self.scheduler.sweep_cancelled():
                self._finish(h, RequestStatus.CANCELLED)
        for h in self.scheduler.admit(self.pool):
            with tracing.span(
                "serve.admit", request=h.request.request_id
            ):
                self._configure_slot(h)
        did = self._run_prefill()
        did = self._run_decode() or did
        if self.config.telemetry_every and (
            self._steps % self.config.telemetry_every == 0
        ):
            self.telemetry.record_snapshot(
                queue_depth=self.scheduler.queue_depth(),
                slots_occupied=self.pool.num_occupied,
                slots_total=self.pool.num_slots,
                decode_ticks=self._decode_ticks,
            )
        return did

    def run_until_drained(self, max_steps: int = 1_000_000) -> None:
        """Step until every submitted request reaches a terminal state."""
        for _ in range(max_steps):
            if not self.has_work():
                return
            self.step()
        raise RuntimeError(
            f"engine did not drain within {max_steps} steps "
            f"({len(self.scheduler.live_handles())} requests live)"
        )

    # -- phase bodies ------------------------------------------------------
    def _run_prefill(self) -> bool:
        cfg = self.config
        plans = self.scheduler.plan_prefill(cfg.prefill_chunks_per_step)
        did = False
        for plan in plans:
            h = plan.handle
            if h.done:  # evicted earlier in this very step's plan list
                continue
            if faults.active():
                try:
                    faults.check("serve.prefill", path=h.request.request_id)
                except faults.InjectedFault as e:
                    self._finish(h, RequestStatus.FAILED, error=e)
                    continue
            ids = np.zeros((1, cfg.prefill_chunk), np.int32)
            ids[0, :plan.chunk_len] = plan.ids
            slot = h.slot
            # scalars pass as plain python values (weak-typed, no
            # retrace); ALL slot-row updates — per-chunk length cursor,
            # final-chunk key/token persist — happen inside the one
            # compiled program (eager .at[].set is ms-scale here)
            with tracing.span(
                "serve.prefill_chunk", request=h.request.request_id
            ):
                (
                    cache, tok, self._toks, self._lengths, self._keys,
                ) = self._prefill(
                    self.params, self.pool.cache, ids, slot, plan.start,
                    plan.chunk_len - 1, plan.final,
                    self._toks, self._lengths, self._keys,
                    self._temps, self._top_ks, self._top_ps,
                )
            tracing.note_compiles("serve.prefill", self.prefill_compiles)
            self.pool.cache = cache
            self.pool.lengths[slot] = plan.start + plan.chunk_len
            did = True
            if plan.final:
                self.scheduler.prefill_finished(h)
                self._decoding_dirty = True
                self._emit(h, int(tok))
        return did

    def _run_decode(self) -> bool:
        if self._decoding_dirty:
            self._decoding_cached = self.scheduler.decoding()
            active = np.zeros(self.config.num_slots, bool)
            for slot, _ in self._decoding_cached:
                active[slot] = True
            self._active_cached = jnp.asarray(active)
            self._decoding_dirty = False
        decoding = self._decoding_cached
        if not decoding:
            return False
        self._decode_ticks += 1
        # one jit call; toks/lengths/keys advance in-program for the
        # active rows, so the only per-tick host traffic is the sampled
        # tokens coming down
        # armed-only arg evaluation (PTD002): the steady-state tick is
        # the serving hot path — disarmed cost stays one is-None test
        span = (
            tracing._NULL_SPAN if tracing._tracer is None
            else tracing.span("serve.decode_tick", active=len(decoding))
        )
        with span:
            (
                self.pool.cache, nxt, self._toks, self._lengths,
                self._keys,
            ) = self._decode(
                self.params, self.pool.cache, self._toks, self._lengths,
                self._keys, self._temps, self._top_ks, self._top_ps,
                self._active_cached,
            )
        tracing.note_compiles("serve.decode", self.decode_compiles)
        with tracing.span("serve.token_fetch"):
            # the one per-tick device sync: every sampled token comes down
            nxt = np.asarray(nxt)
        fault_armed = faults.active()
        for slot, h in decoding:
            # the tick wrote this slot's token at lengths[slot]; mirror
            # the in-program length advance, then judge the token
            self.pool.lengths[slot] += 1
            if fault_armed:
                try:
                    faults.check("serve.decode", path=h.request.request_id)
                except faults.InjectedFault as e:
                    self._finish(h, RequestStatus.FAILED, error=e)
                    continue
            self._emit(h, int(nxt[slot]))
        return True

    # -- emission / retirement ---------------------------------------------
    def _emit(self, h: RequestHandle, token: int) -> None:
        now = self._clock()
        h.emit(token, now)
        req = h.request
        # continuing requests need no device write here: the decode tick
        # already advanced the slot's token/length/key rows in-program
        if req.eos_id is not None and token == req.eos_id:
            self._finish(h, RequestStatus.COMPLETED)
        elif len(h.tokens) >= req.max_new_tokens:
            self._finish(h, RequestStatus.COMPLETED)

    def _finish(
        self,
        h: RequestHandle,
        status: RequestStatus,
        error: Optional[BaseException] = None,
    ) -> None:
        h.status = status
        h.error = error
        h.finished_at = self._clock()
        if h.request.deadline_s is not None:
            self._n_deadlines -= 1
        self._decoding_dirty = True
        with tracing.span(
            "serve.evict",
            request=h.request.request_id, status=status.value,
        ):
            self.scheduler.release(h, self.pool)
        self.telemetry.record_done(h)
        if status is RequestStatus.FAILED:
            logger.warning(
                "serve: evicted request %s after fault: %s",
                h.request.request_id, error,
            )

    # -- admission-time slot setup ----------------------------------------
    def _configure_slot(self, h: RequestHandle) -> None:
        req = h.request
        (
            self._temps, self._top_ks, self._top_ps, self._keys,
            self._lengths,
        ) = self._admit_rows(
            self._temps, self._top_ks, self._top_ps, self._keys,
            self._lengths, h.slot,
            req.temperature,
            TOP_K_OFF if req.top_k is None else req.top_k,
            TOP_P_OFF if req.top_p is None else req.top_p,
            req.seed,
        )
