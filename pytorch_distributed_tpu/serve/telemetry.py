"""SLO telemetry for the serve engine — TTFT / throughput / occupancy.

Counters flow through the existing ``MetricsWriter`` JSONL protocol
(train/metrics.py): any object with ``write(step, metrics, split=...)``
works, so serve telemetry lands in the same durable, pandas/jq-loadable
stream as training scalars (TeeWriter fans it to TensorBoard too). Two
record kinds, both under ``split="serve"``:

* ``event="request"`` — one per finished request: status, prompt/new
  token counts, TTFT (submit -> first token, the user-facing latency
  SLO) and decode tokens/sec.
* ``event="snapshot"`` — periodic gauges: queue depth, slot occupancy,
  decode ticks so far — the saturation picture.

``summary()`` aggregates the run: p50/p99 TTFT (the two SLO percentiles
every serving paper reports), completed-token throughput, and terminal
status counts.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from pytorch_distributed_tpu.utils.timing import percentile


class ServeTelemetry:
    """Collects per-request timings; optionally streams via a
    MetricsWriter-protocol ``writer``. ``clock`` is injectable so tests
    drive deterministic time."""

    def __init__(self, writer=None, clock=time.monotonic,
                 engine_id: Optional[str] = None):
        self.writer = writer
        self.clock = clock
        #: fleet label stamped on every record (r18): merged multi-
        #: engine traces disambiguate emitters by it; None (the solo
        #: default) keeps the single-engine-implicit schema unchanged
        self.engine_id = engine_id
        self.started_at = clock()
        self.ttfts_s: List[float] = []
        self.status_counts: Dict[str, int] = {}
        self.completed_tokens = 0
        self.total_tokens = 0
        self.submitted = 0
        self._events = 0

    # -- per-request lifecycle --------------------------------------------
    def record_submit(self, handle) -> None:
        self.submitted += 1

    def record_done(self, handle) -> None:
        """Called once, after the handle reaches a terminal status."""
        status = handle.status.value
        self.status_counts[status] = self.status_counts.get(status, 0) + 1
        n = len(handle.tokens)
        self.total_tokens += n
        metrics = {
            "event": "request",
            "request_id": handle.request.request_id,
            "status": status,
            "prompt_tokens": handle.request.prompt_len,
            "new_tokens": n,
        }
        if handle.first_token_at is not None:
            ttft = handle.first_token_at - handle.submitted_at
            metrics["ttft_ms"] = ttft * 1e3
            # every request that GOT a first token counts toward the
            # TTFT percentiles, whatever happened to it afterwards —
            # under overload the slowest-to-first-token requests are
            # exactly the ones that later expire, and dropping them
            # would survivorship-bias the headline p99
            self.ttfts_s.append(ttft)
        if status == "completed":
            self.completed_tokens += n
            end = handle.finished_at or self.clock()
            # decode throughput: the clock starts at the FIRST token,
            # not at submit — a deep queue must inflate TTFT, not
            # deflate this number into an arrival-rate artifact
            start = handle.first_token_at or handle.submitted_at
            dt = end - start
            if n > 1 and dt > 0:
                metrics["tokens_per_sec"] = (n - 1) / dt
        self._write(metrics)

    # -- periodic gauges ---------------------------------------------------
    def record_snapshot(
        self, *, queue_depth: int, slots_occupied: int, slots_total: int,
        decode_ticks: int, **gauges,
    ) -> None:
        """Periodic saturation picture. ``gauges`` carries the paged
        pool's occupancy (``pages_in_use`` / ``pages_total`` /
        ``page_occupancy`` / ``prefix_hit_rate``) and, for speculative
        engines, the cumulative ``spec_verifies`` / ``spec_drafted`` /
        ``spec_accepted`` counters — all flat keys in the same snapshot
        record, so existing consumers (jq, obs_report) see them without
        a schema change."""
        self._write({
            "event": "snapshot",
            "queue_depth": queue_depth,
            "slots_occupied": slots_occupied,
            "slots_total": slots_total,
            "slot_occupancy": (
                slots_occupied / slots_total if slots_total else 0.0
            ),
            "decode_ticks": decode_ticks,
            **gauges,
        })

    def _write(self, metrics: Dict) -> None:
        if self.writer is not None:
            self._events += 1
            if self.engine_id is not None:
                metrics = {"engine_id": self.engine_id, **metrics}
            self.writer.write(self._events, metrics, split="serve")

    # -- aggregates --------------------------------------------------------
    def ttft_percentile_ms(self, q: float) -> Optional[float]:
        if not self.ttfts_s:
            return None
        # the shared linear-interpolated helper (utils/timing.py) — same
        # numbers the old private np.percentile path produced, same
        # computation every other percentile in the repo reports
        return percentile(self.ttfts_s, q) * 1e3

    def summary(self) -> Dict[str, float]:
        wall = max(self.clock() - self.started_at, 1e-9)
        out = {
            "submitted": self.submitted,
            "total_tokens": self.total_tokens,
            "completed_tokens": self.completed_tokens,
            "tokens_per_sec": self.completed_tokens / wall,
            "wall_s": wall,
        }
        for status, n in sorted(self.status_counts.items()):
            out[status] = n
        for q in (50, 99):
            p = self.ttft_percentile_ms(q)
            if p is not None:
                out[f"ttft_ms_p{q}"] = p
        return out
