"""Request lifecycle + FIFO admission scheduling for the serve engine.

All of continuous batching's dynamism lives here, on the host: which
request owns which slot, how far its prompt has prefilled, when its
deadline passes, whether it was cancelled. The device never sees any of
it — the engine turns this bookkeeping into fixed-shape array arguments
every tick.

Scheduling policy (deliberately simple, deterministic, and fair):

* **FIFO admission**: queued requests claim freed slots in arrival
  order; the free list hands out the lowest slot index first, so a
  seeded workload replays bit-exactly.
* **Chunked prefill**: a prompt prefills ``prefill_chunk`` tokens at a
  time, oldest admitted request first, at most
  ``prefill_chunks_per_step`` chunks per engine step — a 10k-token
  prompt cannot stall the decode tick of the requests already flowing
  (the vLLM/Sarathi chunked-prefill argument, restated for static
  shapes: the chunk IS the static shape).
* **Deadlines** are absolute wall-clock points checked every step:
  queued requests expire in place, in-flight requests are evicted and
  their slot freed. Cancellation follows the same eviction path.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np


class RequestStatus(enum.Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"
    DECODING = "decoding"
    COMPLETED = "completed"
    CANCELLED = "cancelled"
    EXPIRED = "expired"
    FAILED = "failed"
    #: terminal on a PREFILL-tier engine: the prompt's pages were packed
    #: and shipped; the request continues on a decode-tier peer (r18)
    MIGRATED = "migrated"


#: statuses a request can still make progress from
LIVE_STATUSES = (
    RequestStatus.QUEUED, RequestStatus.PREFILLING, RequestStatus.DECODING,
)

_req_counter = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request — the engine-facing analogue of a solo
    ``generate(prompt, max_new_tokens, temperature, top_k, top_p,
    eos_id, rng=PRNGKey(seed))`` call. The engine guarantees the token
    stream is bit-identical to that call, whatever else shares the
    batch."""

    prompt_ids: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    #: seconds from submit() until the request is abandoned (queued OR
    #: mid-flight); None = no deadline
    deadline_s: Optional[float] = None
    request_id: str = ""

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size < 1:
            raise ValueError("prompt_ids must hold at least one token")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if not self.request_id:
            self.request_id = f"req-{next(_req_counter)}"

    @property
    def prompt_len(self) -> int:
        return int(self.prompt_ids.size)


class RequestHandle:
    """Live view of a submitted request: streamed tokens + status.

    ``tokens`` grows as the engine emits (the streaming surface — read
    it live or attach ``on_token``); terminal ``status`` plus
    ``error``/timestamps tell the rest of the story.
    """

    def __init__(self, request: Request, submitted_at: float):
        self.request = request
        self.status = RequestStatus.QUEUED
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.slot: Optional[int] = None
        self.submitted_at = submitted_at
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.on_token = None  # optional callable(handle, token)
        # -- scheduler internals --
        self._prefill_done = 0  # prompt tokens written into the slot
        self._cancel = False
        # page-pool leases (target + optional draft), set at admission
        self._lease = None
        self._dlease = None
        # prompt chain-hash keys, computed ONCE at the first admission
        # attempt (a page-blocked head retries every engine step; the
        # prompt never changes, so neither do the keys)
        self._chain_keys = None

    @property
    def done(self) -> bool:
        return self.status not in LIVE_STATUSES

    @property
    def deadline_at(self) -> Optional[float]:
        d = self.request.deadline_s
        return None if d is None else self.submitted_at + d

    def emit(self, token: int, now: float) -> None:
        if self.first_token_at is None:
            self.first_token_at = now
        self.tokens.append(int(token))
        if self.on_token is not None:
            self.on_token(self, int(token))

    def __repr__(self):  # pragma: no cover - debugging nicety
        return (
            f"RequestHandle({self.request.request_id}, "
            f"{self.status.value}, tokens={len(self.tokens)})"
        )


@dataclasses.dataclass(frozen=True)
class PrefillChunk:
    """One planned prefill step: write ``ids[:chunk_len]`` (right-padded
    to the static chunk width by the engine) at buffer position
    ``start`` of ``handle.slot``; ``final`` chunks sample the request's
    first token from the chunk's last real logit column."""

    handle: RequestHandle
    start: int
    ids: np.ndarray  # [chunk_len] real prompt tokens (unpadded)
    final: bool

    @property
    def chunk_len(self) -> int:
        return int(self.ids.size)


class Scheduler:
    """FIFO queue + slot admission + chunk planning (host-only state)."""

    def __init__(self, num_slots: int, prefill_chunk: int):
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.prefill_chunk = prefill_chunk
        self.queue: Deque[RequestHandle] = deque()
        self.by_slot: Dict[int, RequestHandle] = {}
        self._prefilling: List[RequestHandle] = []  # admission order

    # -- intake ------------------------------------------------------------
    def enqueue(self, handle: RequestHandle) -> None:
        self.queue.append(handle)

    def queue_depth(self) -> int:
        return len(self.queue)

    def live_handles(self) -> List[RequestHandle]:
        return list(self.queue) + list(self.by_slot.values())

    def find(self, request_id: str) -> Optional[RequestHandle]:
        for h in self.live_handles():
            if h.request.request_id == request_id:
                return h
        return None

    # -- admission ---------------------------------------------------------
    def admit(
        self, pool, draft_pool=None, *, tail: int = 0
    ) -> List[RequestHandle]:
        """Move queued requests into freed slots + pages (strict FIFO —
        a head-of-line request that doesn't fit blocks the queue rather
        than being overtaken, so seeded workloads replay exactly and no
        request starves). Returns the newly admitted handles, already
        marked PREFILLING with their prefill cursor at the shared-prefix
        skip. ``draft_pool`` (speculative engines) is allocated in
        lockstep: one chunk stream drives both caches, so prefill can
        only skip the prefix BOTH pools can serve from shares."""
        admitted = []
        while self.queue:
            h = self.queue[0]
            req = h.request
            if h._chain_keys is None:
                # hash once per request (keys are shared by both pools
                # — same page geometry — and across blocked retries)
                h._chain_keys = pool.chain_keys(req.prompt_ids)
            kw = dict(
                max_new=req.max_new_tokens, chunk=self.prefill_chunk,
                tail=tail, keys=h._chain_keys,
            )
            if draft_pool is None:
                lease = pool.allocate(req.prompt_ids, **kw)
                if lease is None:
                    break
                dlease = None
            else:
                joint = min(
                    pool.shareable_skip(req.prompt_ids, **kw),
                    draft_pool.shareable_skip(req.prompt_ids, **kw),
                )
                lease = pool.allocate(
                    req.prompt_ids, max_skip=joint, **kw
                )
                if lease is None:
                    break
                dlease = draft_pool.allocate(
                    req.prompt_ids, max_skip=joint, **kw
                )
                if dlease is None:
                    pool.free(lease.slot)
                    break
                # both pools pop their lowest free slot and see the
                # same admit/free sequence — the ids cannot drift
                assert dlease.slot == lease.slot
                assert dlease.skip == lease.skip
            self.queue.popleft()
            h.slot = lease.slot
            h._lease = lease
            h._dlease = dlease
            h.status = RequestStatus.PREFILLING
            h._prefill_done = lease.skip
            self.by_slot[lease.slot] = h
            self._prefilling.append(h)
            admitted.append(h)
        return admitted

    def adopt(self, handle: RequestHandle, lease) -> None:
        """Bind an already-prefilled request to a slot, skipping the
        queue and the prefill plan entirely — the decode-tier half of a
        migration (``ServeEngine.inject_migration``): the pages arrive
        spliced from the prefill tier, so the handle enters the batch
        directly in DECODING with its whole prompt accounted for."""
        handle.slot = lease.slot
        handle._lease = lease
        handle._dlease = None
        handle.status = RequestStatus.DECODING
        handle._prefill_done = handle.request.prompt_len
        self.by_slot[lease.slot] = handle

    # -- prefill planning --------------------------------------------------
    def plan_prefill(self, budget: int) -> List[PrefillChunk]:
        """Up to ``budget`` chunks, oldest admitted request first (finish
        one request's prompt before starting the next — it is the one
        whose TTFT clock has been running longest)."""
        plans: List[PrefillChunk] = []
        for h in self._prefilling:
            if len(plans) >= budget:
                break
            p = h.request.prompt_ids
            while h._prefill_done < p.size and len(plans) < budget:
                start = h._prefill_done
                ids = p[start:start + self.prefill_chunk]
                # plan positions advance locally so one handle can get
                # several chunks within one budget
                plans.append(PrefillChunk(
                    handle=h, start=start, ids=ids,
                    final=start + ids.size >= p.size,
                ))
                h._prefill_done = start + ids.size
        return plans

    def prefill_finished(self, handle: RequestHandle) -> None:
        """The final chunk ran and the first token was emitted."""
        handle.status = RequestStatus.DECODING
        if handle in self._prefilling:
            self._prefilling.remove(handle)

    # -- decode view -------------------------------------------------------
    def decoding(self) -> List[Tuple[int, RequestHandle]]:
        return sorted(
            (s, h) for s, h in self.by_slot.items()
            if h.status is RequestStatus.DECODING
        )

    # -- retirement --------------------------------------------------------
    def release(self, handle: RequestHandle, pool, draft_pool=None) -> None:
        """Detach a handle from its slot (terminal status already set by
        the engine) and drop its page references in BOTH pools — shared
        pages survive for their other holders; private ones return to
        the free list."""
        if handle.slot is not None:
            self.by_slot.pop(handle.slot, None)
            pool.free(handle.slot)
            if draft_pool is not None:
                draft_pool.free(handle.slot)
            handle.slot = None
            handle._lease = None
            handle._dlease = None
        if handle in self._prefilling:
            self._prefilling.remove(handle)
        if handle in self.queue:
            self.queue.remove(handle)

    # -- deadline / cancellation sweeps ------------------------------------
    def sweep_expired(self, now: float) -> List[RequestHandle]:
        out = [
            h for h in self.live_handles()
            if h.deadline_at is not None and now >= h.deadline_at
        ]
        return out

    def sweep_cancelled(self) -> List[RequestHandle]:
        return [h for h in self.live_handles() if h._cancel]
