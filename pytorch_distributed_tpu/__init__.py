"""pytorch_distributed_tpu — a TPU-native distributed training framework.

A ground-up re-design of the capability surface of ``gheur/pytorch-distributed``
(a CUDA/NCCL multi-GPU recipe collection; see SURVEY.md — the reference tree
was unavailable, so the capability matrix comes from BASELINE.json:5-12) for
TPU hardware:

* single-controller SPMD over a ``jax.sharding.Mesh`` instead of
  process-per-GPU + NCCL process groups;
* XLA collectives (``psum`` / ``all_gather`` / ``reduce_scatter`` /
  ``ppermute``) over ICI/DCN instead of NCCL rings;
* DDP / ZeRO-1 / FSDP expressed as three sharding configurations of one
  mechanism (NamedSharding of params / optimizer state / batch) instead of
  three separate wrapper classes with gradient hooks;
* bf16 compute policy instead of CUDA AMP loss scaling (a
  GradScaler-compatible API is kept so recipe scripts read like the
  originals).

Public API is re-exported here so recipes can do::

    import pytorch_distributed_tpu as ptd
    ptd.init_process_group(backend="ici")
    mesh = ptd.current_mesh()
"""

from pytorch_distributed_tpu.runtime.device import (
    device_count,
    enable_compilation_cache,
    local_device_count,
    max_memory_allocated,
    memory_allocated,
    memory_stats,
    memory_summary,
    platform,
    is_tpu,
)
from pytorch_distributed_tpu.runtime.mesh import (
    MeshSpec,
    make_mesh,
    current_mesh,
    set_current_mesh,
    mesh_axis_size,
)
from pytorch_distributed_tpu.runtime.distributed import (
    init_process_group,
    destroy_process_group,
    is_initialized,
    get_world_size,
    get_rank,
    get_backend,
    all_reduce,
    all_gather,
    all_gather_object,
    all_to_all,
    reduce,
    reduce_scatter,
    broadcast,
    broadcast_object_list,
    scatter_object_list,
    all_gather_into_tensor,
    reduce_scatter_tensor,
    barrier,
    monitored_barrier,
    new_group,
    gather,
    scatter,
    permute,
    ReduceOp,
)
from pytorch_distributed_tpu.runtime.precision import (
    Policy,
    autocast,
    use_policy,
    GradScaler,
    current_policy,
)
from pytorch_distributed_tpu.runtime.prng import RngSeq, seed_all
from pytorch_distributed_tpu.generation import generate, generate_beam, sample_logits
from pytorch_distributed_tpu.speculative import generate_speculative
from pytorch_distributed_tpu.lora import (
    LoRAModel,
    lora_init,
    lora_merge,
    lora_param_count,
)
from pytorch_distributed_tpu import optim
from pytorch_distributed_tpu.launch import (
    ElasticAgent,
    init_multihost,
    spawn,
)

__version__ = "0.1.0"

__all__ = [
    "device_count",
    "local_device_count",
    "max_memory_allocated",
    "memory_allocated",
    "memory_stats",
    "memory_summary",
    "platform",
    "is_tpu",
    "MeshSpec",
    "make_mesh",
    "current_mesh",
    "set_current_mesh",
    "mesh_axis_size",
    "init_process_group",
    "destroy_process_group",
    "is_initialized",
    "get_world_size",
    "get_rank",
    "get_backend",
    "all_reduce",
    "all_gather",
    "all_gather_object",
    "all_to_all",
    "reduce",
    "reduce_scatter",
    "broadcast",
    "broadcast_object_list",
    "scatter_object_list",
    "all_gather_into_tensor",
    "reduce_scatter_tensor",
    "barrier",
    "monitored_barrier",
    "new_group",
    "gather",
    "scatter",
    "permute",
    "ReduceOp",
    "enable_compilation_cache",
    "generate",
    "generate_beam",
    "generate_speculative",
    "LoRAModel",
    "lora_init",
    "lora_merge",
    "lora_param_count",
    "optim",
    "sample_logits",
    "Policy",
    "autocast",
    "use_policy",
    "GradScaler",
    "current_policy",
    "RngSeq",
    "seed_all",
    "ElasticAgent",
    "init_multihost",
    "spawn",
]
