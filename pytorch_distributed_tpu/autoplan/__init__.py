"""Auto-parallel planner: cost-model-driven strategy search.

Given a model's abstract state (``jax.eval_shape`` — planning never
compiles), a :class:`~pytorch_distributed_tpu.autoplan.pricing.
ModelProfile` and the device fleet, the planner enumerates
(mesh shape x strategy class x shape-aware partition rules) candidates,
filters them against the per-device memory budget, prices each one's
per-step comms through the calibrated α–β cost model
(``scripts/collective_bench.py --fit``) plus a compute term, and emits
a ranked, auditable ``plan.json`` and one chosen strategy — the
machinery behind ``--strategy auto`` in the recipes.

The rule engine (autoplan/rules.py) is also the production partition-
rule substrate: ``llama_partition_rules`` / ``gpt2_partition_rules``
are thin declarative tables over it.
"""

from pytorch_distributed_tpu.autoplan.candidates import (
    STRATEGY_CLASSES,
    CandidateSpec,
    enumerate_candidates,
)
from pytorch_distributed_tpu.autoplan.memory import (
    MemoryBreakdown,
    PlanMesh,
    account_state,
    device_budget_bytes,
)
from pytorch_distributed_tpu.autoplan.planner import (
    Plan,
    PlanError,
    PricedCandidate,
    format_plan,
    param_count,
    plan,
    reference_sweep,
)
from pytorch_distributed_tpu.autoplan.pricing import (
    CommTerm,
    ComputeModel,
    ModelProfile,
    image_profile,
    transformer_profile,
)
from pytorch_distributed_tpu.autoplan.rules import (
    TensorRule,
    engine_rules,
    max_divisible_tp,
)

__all__ = [
    "STRATEGY_CLASSES",
    "CandidateSpec",
    "enumerate_candidates",
    "MemoryBreakdown",
    "PlanMesh",
    "account_state",
    "device_budget_bytes",
    "Plan",
    "PlanError",
    "PricedCandidate",
    "format_plan",
    "param_count",
    "plan",
    "reference_sweep",
    "CommTerm",
    "ComputeModel",
    "ModelProfile",
    "image_profile",
    "transformer_profile",
    "TensorRule",
    "engine_rules",
    "max_divisible_tp",
]
