"""Candidate enumeration: mesh shapes x strategy classes.

A candidate is (strategy class, data-parallel ways, tensor-parallel
ways, optional wire compression, pipeline stages). The data axes map
onto the mesh the way parallel/strategies.py expects them: dp/zero1
put the data ways on ``dp``, fsdp puts them on ``fsdp`` (so the batch
still shards — both are batch axes — while params/opt shard over the
fsdp axis). tp composes with any of the three via the model's
TensorRules, which the rule engine keeps valid on every enumerated
shape. pp (r20) stacks pipeline stages on the ``pp`` axis and composes
with ``dp`` only: zero1/fsdp shard optimizer/params over the data ways
a stage's gradient exchange already spans, and pricing that
composition honestly needs the per-stage re-gather model we don't
have — refusing beats underpricing a ghost. q8 wire compression is a
``ddp.sync_grads`` path property and never composes with pp either.

Enumeration is deterministic (sorted by strategy name, then pp, then
tp) so two runs of the planner on the same inputs produce
byte-identical plans. pp == 1 IS the plain candidate — the pp
dimension adds rows only for pp > 1, never a duplicate ``dp/dpN`` row
with a different name.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.runtime.mesh import AXES, MeshSpec

#: strategy-class names the planner knows how to build and price
STRATEGY_CLASSES: Tuple[str, ...] = ("dp", "zero1", "fsdp")


@dataclasses.dataclass(frozen=True)
class CandidateSpec:
    strategy: str  # one of STRATEGY_CLASSES
    data: int  # data-parallel ways (dp or fsdp axis size)
    tp: int = 1
    compress: Optional[str] = None  # None | "int8" (q8 grad wire)
    pp: int = 1  # pipeline stages (dp-only composition, r20)

    @property
    def name(self) -> str:
        n = f"{self.strategy}/dp{self.data}"
        if self.tp > 1:
            n += f"xtp{self.tp}"
        if self.pp > 1:
            n += f"xpp{self.pp}"
        if self.compress:
            n += "+q8"
        return n

    @property
    def n_devices(self) -> int:
        return self.data * self.tp * self.pp

    def mesh_sizes(self) -> dict:
        sizes = {a: 1 for a in AXES}
        sizes["fsdp" if self.strategy == "fsdp" else "dp"] = self.data
        sizes["tp"] = self.tp
        sizes["pp"] = self.pp
        return sizes

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec(**{
            a: s for a, s in self.mesh_sizes().items()
        })

    def strategy_class(self):
        from pytorch_distributed_tpu.parallel import (
            DataParallel,
            FSDP,
            ZeRO1,
        )

        return {"dp": DataParallel, "zero1": ZeRO1, "fsdp": FSDP}[
            self.strategy
        ]

    def build_strategy(self, *, extra_rules=(), mesh=None):
        """Construct the real Strategy — the CURRENT mesh must already
        match :meth:`mesh_spec` (recipes pass the spec to
        ``init_process_group`` first)."""
        if self.compress:
            # q8 lives on the multiprocess ddp.sync_grads wire path;
            # the SPMD strategies have no compressed-gradient mode, so
            # a q8 candidate is price-only — enumerate it only where
            # the consumer knows that (bench/analysis sweeps)
            raise ValueError(
                f"{self.name} prices q8 wire compression (ddp/hostring "
                "path); it cannot be built as an SPMD strategy"
            )
        if self.pp > 1:
            # the pipeline candidate builds the SPMD stage-sharded
            # strategy; the recipe also swaps in the pipelined loss
            # (pipelined_causal_lm_loss_fn) — PricedCandidate carries
            # the (pp, num_microbatches) the loss needs
            from pytorch_distributed_tpu.parallel.pipeline_lm import (
                PipelineParallel,
            )

            return PipelineParallel(mesh, extra_rules=extra_rules)
        return self.strategy_class()(mesh, extra_rules=extra_rules)


def enumerate_candidates(
    n_devices: int,
    *,
    strategies: Sequence[str] = STRATEGY_CLASSES,
    tp_candidates: Optional[Sequence[int]] = None,
    max_tp: Optional[int] = None,
    include_q8: bool = False,
    pp_candidates: Optional[Sequence[int]] = None,
    max_pp: Optional[int] = None,
) -> List[CandidateSpec]:
    """All (strategy, mesh shape) candidates for ``n_devices``.

    ``tp_candidates`` restricts tensor-parallel widths (recipes pass
    the divisors of the model's head count via
    ``rules.max_divisible_tp``); default is every divisor of the device
    count. ``pp_candidates``/``max_pp`` open the pipeline dimension the
    same way (dp-only composition, module docstring) — pp == 1 yields
    the plain candidates exactly once, never a renamed duplicate.
    Degenerate duplicates are collapsed: at data==1 the three strategy
    classes place identically, so only the ``dp`` form is emitted.
    ``include_q8`` adds an int8-compressed-gradient variant of each
    unpipelined dp candidate (the hostring/ddp wire-compression path).
    """
    unknown = set(strategies) - set(STRATEGY_CLASSES)
    if unknown:
        raise ValueError(f"unknown strategy classes {sorted(unknown)}")
    pps = [
        s for s in range(1, n_devices + 1)
        if n_devices % s == 0
        and (pp_candidates is None or s in pp_candidates or s == 1)
        and (max_pp is None or s <= max_pp or s == 1)
    ]
    out: List[CandidateSpec] = []
    for strategy in sorted(strategies):
        for pp in pps:
            if pp > 1 and strategy != "dp":
                continue  # dp-only composition (module docstring)
            rest = n_devices // pp
            tps = [
                t for t in range(1, rest + 1)
                if rest % t == 0
                and (tp_candidates is None or t in tp_candidates)
                and (max_tp is None or t <= max_tp)
            ]
            for tp in tps:
                data = rest // tp
                if data == 1 and strategy != "dp" and pp == 1:
                    continue  # replicated==sharded-over-1: same placement
                out.append(CandidateSpec(strategy, data, tp, pp=pp))
                if include_q8 and strategy == "dp" and data > 1 \
                        and pp == 1:
                    out.append(CandidateSpec(strategy, data, tp,
                                             compress="int8"))
    return out
