"""Candidate enumeration: mesh shapes x strategy classes.

A candidate is (strategy class, data-parallel ways, tensor-parallel
ways, optional wire compression). The data axes map onto the mesh the
way parallel/strategies.py expects them: dp/zero1 put the data ways on
``dp``, fsdp puts them on ``fsdp`` (so the batch still shards — both
are batch axes — while params/opt shard over the fsdp axis). tp
composes with any of the three via the model's TensorRules, which the
rule engine keeps valid on every enumerated shape.

Enumeration is deterministic (sorted by strategy name, then tp) so two
runs of the planner on the same inputs produce byte-identical plans.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.runtime.mesh import AXES, MeshSpec

#: strategy-class names the planner knows how to build and price
STRATEGY_CLASSES: Tuple[str, ...] = ("dp", "zero1", "fsdp")


@dataclasses.dataclass(frozen=True)
class CandidateSpec:
    strategy: str  # one of STRATEGY_CLASSES
    data: int  # data-parallel ways (dp or fsdp axis size)
    tp: int = 1
    compress: Optional[str] = None  # None | "int8" (q8 grad wire)

    @property
    def name(self) -> str:
        n = f"{self.strategy}/dp{self.data}"
        if self.tp > 1:
            n += f"xtp{self.tp}"
        if self.compress:
            n += "+q8"
        return n

    @property
    def n_devices(self) -> int:
        return self.data * self.tp

    def mesh_sizes(self) -> dict:
        sizes = {a: 1 for a in AXES}
        sizes["fsdp" if self.strategy == "fsdp" else "dp"] = self.data
        sizes["tp"] = self.tp
        return sizes

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec(**{
            a: s for a, s in self.mesh_sizes().items()
        })

    def strategy_class(self):
        from pytorch_distributed_tpu.parallel import (
            DataParallel,
            FSDP,
            ZeRO1,
        )

        return {"dp": DataParallel, "zero1": ZeRO1, "fsdp": FSDP}[
            self.strategy
        ]

    def build_strategy(self, *, extra_rules=(), mesh=None):
        """Construct the real Strategy — the CURRENT mesh must already
        match :meth:`mesh_spec` (recipes pass the spec to
        ``init_process_group`` first)."""
        if self.compress:
            # q8 lives on the multiprocess ddp.sync_grads wire path;
            # the SPMD strategies have no compressed-gradient mode, so
            # a q8 candidate is price-only — enumerate it only where
            # the consumer knows that (bench/analysis sweeps)
            raise ValueError(
                f"{self.name} prices q8 wire compression (ddp/hostring "
                "path); it cannot be built as an SPMD strategy"
            )
        return self.strategy_class()(mesh, extra_rules=extra_rules)


def enumerate_candidates(
    n_devices: int,
    *,
    strategies: Sequence[str] = STRATEGY_CLASSES,
    tp_candidates: Optional[Sequence[int]] = None,
    max_tp: Optional[int] = None,
    include_q8: bool = False,
) -> List[CandidateSpec]:
    """All (strategy, mesh shape) candidates for ``n_devices``.

    ``tp_candidates`` restricts tensor-parallel widths (recipes pass
    the divisors of the model's head count via
    ``rules.max_divisible_tp``); default is every divisor of the device
    count. Degenerate duplicates are collapsed: at data==1 the three
    strategy classes place identically, so only the ``dp`` form is
    emitted. ``include_q8`` adds an int8-compressed-gradient variant of
    each dp candidate (the hostring/ddp wire-compression path).
    """
    unknown = set(strategies) - set(STRATEGY_CLASSES)
    if unknown:
        raise ValueError(f"unknown strategy classes {sorted(unknown)}")
    tps = [
        t for t in range(1, n_devices + 1)
        if n_devices % t == 0
        and (tp_candidates is None or t in tp_candidates)
        and (max_tp is None or t <= max_tp)
    ]
    out: List[CandidateSpec] = []
    for strategy in sorted(strategies):
        for tp in tps:
            data = n_devices // tp
            if data == 1 and strategy != "dp":
                continue  # replicated==sharded-over-1: same placement
            out.append(CandidateSpec(strategy, data, tp))
            if include_q8 and strategy == "dp" and data > 1:
                out.append(CandidateSpec(strategy, data, tp,
                                         compress="int8"))
    return out
