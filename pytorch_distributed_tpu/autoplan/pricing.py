"""Per-step pricing: comms volume x calibrated α–β model + compute term.

The comms side is deliberately the SAME arithmetic the tracer records
and the bench measures: payloads are priced through
``CostModel.predict``, which computes NCCL-convention wire bytes via
``hostring.algo_wire_bytes`` — the bytes the planner prices are the
bytes a ``comm.*`` span would record for the run it predicts. q8
gradient compression is priced at its REAL wire occupancy
(``hostring.q8_wire_payload``: int8 + one f32 scale per 256 elems,
~0.254x f32), so the candidate table shows the ~4x wire reduction as a
number, not a slogan.

Per-step collective volume per strategy class, per optimizer step
(accumulation microbatches share one gradient exchange by construction
— train/trainer.py scans them inside the jitted step):

=========  ==============================================================
dp (DDP)   1x all_reduce(grad_bytes) over the data axes
zero1      reduce_scatter(grads) + all_gather(updated params)
           (cross-replica weight-update sharding, arxiv 2004.13336)
fsdp       2x all_gather(params) [fwd + bwd re-gather] +
           reduce_scatter(grads), over the fsdp axis
tp (any)   4 x layers x all_reduce(per-device activation slab) over tp
           (Megatron f/g pairs, forward + backward)
=========  ==============================================================

With tp>1 the gradient payload is the per-tp-shard slice (each tp group
reduces only its own shard). Honest limits, also printed on the plan:
remat, overlap (compute/comms), and FSDP's per-layer pipelining are not
modeled — this prices serialized collectives, an upper bound that ranks
candidates correctly when they differ by volume or call count.

The compute term is flops / effective-flops, with effective flops
either calibrated from a measured step (``ComputeModel.from_measured_
step`` — the trainer's ``step`` span or bench history) or an assumed
per-platform default that marks the whole plan ``uncalibrated``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence

from pytorch_distributed_tpu.runtime.costmodel import CostModel
from pytorch_distributed_tpu.runtime.hostring import q8_wire_payload


@dataclasses.dataclass(frozen=True)
class ModelProfile:
    """What pricing needs to know about the model, beyond its param tree.

    ``flops_per_sample`` is the TRAIN step cost (forward + backward) per
    sample; ``activation_bytes_per_sample`` feeds the memory filter.
    ``layers``/``hidden``/``seq_len`` drive the tensor-parallel
    activation-collective terms; leave them 0 for models without a TP
    rule set (conv nets) and tp candidates simply price no tp comms.
    """

    flops_per_sample: float
    activation_bytes_per_sample: float
    layers: int = 0
    hidden: int = 0
    seq_len: int = 0
    act_dtype_bytes: int = 4


def transformer_profile(*, num_layers: int, hidden_size: int,
                        seq_len: int, param_count: int,
                        act_dtype_bytes: int = 4,
                        act_coeff: float = 16.0) -> ModelProfile:
    """Decoder-LM profile: 6·N flops per trained token (fwd 2N + bwd 4N,
    the PaLM/Chinchilla accounting), activations ≈ ``act_coeff`` x
    hidden slab per layer per token (~16 covers the block's
    residual/norm/attention/MLP intermediates without remat)."""
    return ModelProfile(
        flops_per_sample=6.0 * float(param_count) * seq_len,
        activation_bytes_per_sample=(
            float(num_layers) * seq_len * hidden_size
            * act_coeff * act_dtype_bytes
        ),
        layers=num_layers, hidden=hidden_size, seq_len=seq_len,
        act_dtype_bytes=act_dtype_bytes,
    )


def image_profile(*, flops_per_sample: float,
                  activation_bytes_per_sample: float) -> ModelProfile:
    """Conv-net profile: caller supplies the two totals (e.g. ResNet-50
    at 224²: ~3x4.1 GFLOPs trained, ~64 MB of f32 feature maps)."""
    return ModelProfile(
        flops_per_sample=float(flops_per_sample),
        activation_bytes_per_sample=float(activation_bytes_per_sample),
    )


#: assumed effective per-device flops when nothing measured is available
#: — deliberately conservative; using one marks the plan `uncalibrated`
ASSUMED_FLOPS_PER_S = {"cpu": 5e9, "tpu": 100e12, "gpu": 50e12}


@dataclasses.dataclass(frozen=True)
class ComputeModel:
    flops_per_s_per_device: float
    source: str  # "measured-step" | "assumed-<platform>"

    @property
    def calibrated(self) -> bool:
        return self.source == "measured-step"

    @classmethod
    def assumed(cls, platform: str) -> "ComputeModel":
        f = ASSUMED_FLOPS_PER_S.get(platform, ASSUMED_FLOPS_PER_S["cpu"])
        return cls(f, f"assumed-{platform}")

    @classmethod
    def from_measured_step(cls, step_seconds: float, flops_per_step: float,
                           n_devices: int) -> "ComputeModel":
        """Effective flops from one measured reference step — folds the
        real MFU of this model on this backend into every candidate."""
        if step_seconds <= 0 or flops_per_step <= 0 or n_devices <= 0:
            raise ValueError("need positive step time, flops and devices")
        return cls(flops_per_step / n_devices / step_seconds,
                   "measured-step")


@dataclasses.dataclass
class CommTerm:
    """One collective in a candidate's step, priced."""

    op: str
    payload_bytes: int
    world: int
    count: int  # issues per step
    seconds: float = 0.0  # count x predicted per-call seconds
    wire_bytes: int = 0  # count x per-participant wire bytes
    extrapolated: bool = False
    note: str = ""
    #: for q8 terms: the f32 bytes the quantization REPLACED — the
    #: quantize/dequant passes sweep this domain, so the analytic
    #: quantize-cost term below prices against it, not the wire bytes
    f32_bytes: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


#: analytic quantize-cost passes for an UNCALIBRATED q8 fallback: the
#: native q8 ring (native/hostring.cpp) sweeps the f32 domain ~3x per
#: participant beyond the wire bytes (quantize the contribution,
#: dequant-accumulate the owned segment across peers, requantize +
#: dequant-copy the result), priced at the transport's own per-byte β.
#: Calibrated ON the measured shm numbers: at 6.4 MB / world 4 this
#: reproduces the recorded "q8 ~2x SLOWER than f32"
#: (runtime/hostring.py's measured trade-off) instead of the wire-bytes-
#: only model that predicted 0.25x — the mispricing that made
#: `--strategy auto` prefer a measured regression. A model with a real
#: all_reduce_q8 fit never uses this (the fit carries the true cost).
Q8_QUANTIZE_PASSES = 3.0


def q8_quantize_seconds(f32_bytes: int, beta_s_per_byte: float,
                        count: int = 1) -> float:
    """Analytic per-step quantize/dequant cost of a q8 collective whose
    f32 payload is ``f32_bytes`` — used ONLY when the cost model has no
    ``all_reduce_q8`` fit (which would already include it)."""
    return Q8_QUANTIZE_PASSES * float(f32_bytes) * beta_s_per_byte * count


def exposed_comm_seconds(comm_seconds: float,
                         overlappable_compute_seconds: float) -> float:
    """The round-14 overlap model: comm that fits under concurrently
    schedulable compute is hidden; only the excess extends the step.
    ``max(0, comm - overlappable)`` — an UPPER bound on hiding (perfect
    pipelining, no interference), the planner's usual serialized-bound
    honesty inverted, so candidates are compared by the same optimistic
    rule and the plan records which assumption priced them."""
    return max(0.0, float(comm_seconds)
               - float(overlappable_compute_seconds))


def grad_comm_terms(strategy: str, grad_payload_bytes: int,
                    grad_elems: int, data_world: int, *,
                    compress: Optional[str] = None) -> List[CommTerm]:
    """The gradient/param exchange for one optimizer step (table above)."""
    if data_world <= 1:
        return []
    if strategy == "dp":
        if compress == "int8":
            return [CommTerm("all_reduce_q8",
                             q8_wire_payload(grad_elems), data_world, 1,
                             note="q8 wire occupancy of the f32 grads",
                             f32_bytes=int(grad_payload_bytes))]
        return [CommTerm("all_reduce", grad_payload_bytes, data_world, 1)]
    if strategy == "zero1":
        return [
            CommTerm("reduce_scatter", grad_payload_bytes, data_world, 1),
            CommTerm("all_gather", grad_payload_bytes, data_world, 1,
                     note="updated params"),
        ]
    if strategy == "fsdp":
        return [
            CommTerm("all_gather", grad_payload_bytes, data_world, 2,
                     note="params, forward + backward re-gather"),
            CommTerm("reduce_scatter", grad_payload_bytes, data_world, 1),
        ]
    raise ValueError(f"unknown strategy class {strategy!r}")


def tp_comm_terms(profile: ModelProfile, micro_batch: int,
                  tp_world: int, accum_steps: int = 1) -> List[CommTerm]:
    """Megatron activation collectives: 4 all_reduce per layer per
    microbatch — an accumulating step pays them ``accum_steps`` times
    (same total volume as the unaccumulated step, more α calls)."""
    if tp_world <= 1 or profile.layers <= 0 or profile.hidden <= 0:
        return []
    slab = (micro_batch * max(profile.seq_len, 1) * profile.hidden
            * profile.act_dtype_bytes)
    return [CommTerm("all_reduce", int(slab), tp_world,
                     4 * profile.layers * max(accum_steps, 1),
                     note="tp activation slabs")]


def pipeline_comm_terms(profile: ModelProfile, micro_batch: int,
                        pp: int, num_microbatches: int) -> List[CommTerm]:
    """The r20 host-pipeline link traffic: every interior stage boundary
    moves one activation slab forward and one grad slab back per
    microbatch (``HostPipelineStep``'s tagged send/recv pairs). Priced
    at world=2 — the ordered P2P pair — and SERIALIZED (the planner's
    usual upper bound: the host loop issues them between compute ops,
    and on the steady-state critical path each link's transfers add
    up). Models without layer/hidden info (conv nets) price no pp
    links, same convention as :func:`tp_comm_terms`."""
    if pp <= 1 or profile.hidden <= 0:
        return []
    slab = (micro_batch * max(profile.seq_len, 1) * profile.hidden
            * profile.act_dtype_bytes)
    return [CommTerm(
        "send", int(slab), 2,
        2 * num_microbatches * (pp - 1),
        note="pp activation/grad handoffs (fwd + bwd per boundary)",
    )]


def pipeline_compute_split(
    profile: ModelProfile,
    global_batch: int,
    compute: ComputeModel,
    *,
    data: int,
    tp: int,
    pp: int,
    num_microbatches: int,
    stage_rates: Optional[Sequence[float]] = None,
):
    """(compute_seconds, bubble_seconds, stage_depths) for a pp
    candidate.

    The slowest stage's total work is the steady-state critical path:
    ``max over stages of (depth share / stage rate)`` applied to the
    per-(data x tp)-way flops. The warm-up/drain bubble adds
    ``(S-1)/M`` of that on top (the analytic ``(S-1)/(M+S-1)`` fraction
    of the whole step, bench-measurable from merged traces via
    ``parallel.pipeline_schedule.pipeline_trace_stats``). Homogeneous
    even splits reproduce the flat term exactly: ``max_stage =
    flops / (data*tp*pp) / rate``.

    ``stage_rates`` (one relative rate per stage: the MIN over the
    stage's device group — a stage's data ways commit in lockstep)
    makes the depth split the hetero apportionment
    (``pipeline_schedule.stage_depths`` -> ``train/balance.py``): a
    slow stage gets proportionally fewer layers, and the price reflects
    the discrete split the executor would actually build. Raises
    ValueError when ``profile.layers`` cannot fill/split the stages —
    the planner turns that into the candidate's infeasibility reason.
    """
    from pytorch_distributed_tpu.parallel.pipeline_schedule import (
        stage_depths,
    )

    if num_microbatches < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}"
        )
    layers = profile.layers
    if layers <= 0:
        raise ValueError(
            "pipeline candidates need profile.layers > 0 (the stage "
            "split is a layer split)"
        )
    rates = None
    if stage_rates is not None:
        rates = [float(r) for r in stage_rates]
        if len(set(rates)) == 1:
            rates = None  # homogeneous: use the even split
    depths = stage_depths(
        layers, pp,
        rank_rates=rates,
    )
    flops = profile.flops_per_sample * global_batch
    per_way = compute.flops_per_s_per_device * max(data, 1) * max(tp, 1)
    stage_seconds = [
        (flops * d / layers) / (per_way * (rates[s] if rates else 1.0))
        for s, d in enumerate(depths)
    ]
    slowest = max(stage_seconds)
    bubble = slowest * (pp - 1) / num_microbatches
    return slowest, bubble, depths


def price_comm_terms(terms: Sequence[CommTerm], model: CostModel,
                     fallback: Optional[CostModel] = None) -> List[CommTerm]:
    """Fill in seconds/wire_bytes/extrapolated from the cost model.

    Two degradation steps, both flagged in the term's note, never
    silent: q8 falls back to the plain all_reduce fit (β is a
    per-wire-byte transport property; the payload already carries the
    compression) when the model was never calibrated on
    ``all_reduce_q8``; any op the model has NO fit for at all (a
    partial calibration — ``collective_bench`` keeps later collectives
    running when one fails, so a model missing e.g. reduce_scatter is
    reachable) is priced on ``fallback`` (the planner passes the
    analytic guess) and marked ``extrapolated``. With no fallback the
    KeyError becomes an actionable :class:`CostModelUnavailable`.
    """
    from pytorch_distributed_tpu.runtime.costmodel import (
        CostModelUnavailable,
        calibration_command,
    )

    priced = []
    for t in terms:
        op = t.op
        note = t.note
        forced_extrapolated = False
        quantize_s = 0.0
        try:
            p = model.predict(op, t.payload_bytes, t.world)
        except KeyError:
            if op == "all_reduce_q8" and any(
                o == "all_reduce" for o, _ in model.fits
            ):
                p = model.predict("all_reduce", t.payload_bytes, t.world)
                # the wire-bytes-only fallback UNDERPRICED q8: on the
                # shm transport the quantize compute outweighs the byte
                # savings (measured ~2x slower — hostring.py). Add the
                # per-transport quantize-cost term at the fit's own β,
                # flagged: only a real q8 calibration removes the guess.
                quantize_s = q8_quantize_seconds(
                    t.f32_bytes, p.fit.beta_s_per_byte, t.count
                )
                forced_extrapolated = True
                note = (note + "; " if note else "") + (
                    "priced on the all_reduce fit (no q8 calibration) "
                    "+ analytic quantize cost "
                    f"(~{Q8_QUANTIZE_PASSES:g} f32 passes at the fit's "
                    "β)"
                )
            elif fallback is not None:
                p = fallback.predict(op, t.payload_bytes, t.world)
                forced_extrapolated = True
                note = (note + "; " if note else "") + (
                    f"priced analytically ({op} missing from the "
                    f"calibrated model)"
                )
            else:
                raise CostModelUnavailable(
                    f"cost model ({model.transport}) has no fit for "
                    f"{op!r} and no fallback — recalibrate: "
                    f"`{calibration_command()}`"
                ) from None
        priced.append(dataclasses.replace(
            t,
            seconds=p.seconds * t.count + quantize_s,
            wire_bytes=p.wire_bytes * t.count,
            extrapolated=p.extrapolated or forced_extrapolated,
            note=note,
        ))
    return priced


def compute_seconds(profile: ModelProfile, global_batch: int,
                    n_devices: int, compute: ComputeModel) -> float:
    """Per-step compute: total trained flops over the fleet's effective
    rate (tp/fsdp partition the same flops across devices; their
    efficiency loss is not modeled — see module docstring)."""
    flops = profile.flops_per_sample * global_batch
    return flops / max(n_devices, 1) / compute.flops_per_s_per_device


def hetero_compute_seconds(
    profile: ModelProfile,
    global_batch: int,
    compute: ComputeModel,
    rank_rates: Sequence[float],
    *,
    tp: int = 1,
    microshards: Optional[int] = None,
    balanced: bool = True,
) -> float:
    """Per-step compute on a MIXED-SPEED fleet: the step commits when
    the slowest rank finishes, so the term is ``max over data ways of
    (assigned work / way rate)`` — the r15 balancing model
    (train/balance.py), priced with the engine's OWN discrete
    apportionment so the plan reproduces what the balancer will
    actually assign, quantization and all.

    ``rank_rates`` are RELATIVE per-device speed multipliers on
    ``compute.flops_per_s_per_device`` (1.0 = nominal, 0.5 = half
    speed). With ``tp > 1`` consecutive devices form one tp group that
    computes in lockstep, so a way's rate is the MIN over its members —
    mixing speeds inside a tp group wastes the fast members, and the
    price says so. ``balanced=False`` prices the even split (the
    balance=off baseline; its max is governed by the slowest way);
    ``balanced=True`` prices the proportional split over ``microshards``
    units (default ``MIN_SHARDS_PER_RANK x ways`` — the granularity
    floor ``train/balance.granularity_ok`` warns below).
    """
    from pytorch_distributed_tpu.train.balance import (
        MIN_SHARDS_PER_RANK,
        apportion,
        counts_of,
        even_assignment,
        quantize_rates,
    )

    n = len(rank_rates)
    tp = max(int(tp), 1)
    if n % tp:
        raise ValueError(
            f"{n} device rate(s) do not form tp={tp} groups"
        )
    ways = [
        min(float(r) for r in rank_rates[g * tp:(g + 1) * tp])
        for g in range(n // tp)
    ]
    D = len(ways)
    flops = profile.flops_per_sample * global_batch
    S = int(microshards) if microshards else MIN_SHARDS_PER_RANK * D
    if balanced and S >= D:
        counts = apportion(S, quantize_rates(ways), floor=1)
    else:
        counts = counts_of(even_assignment(S, D), D)
    per_way_flops_per_s = compute.flops_per_s_per_device * tp
    return max(
        (flops * c / S) / (per_way_flops_per_s * r)
        for c, r in zip(counts, ways)
    )


def wire_ratio(terms_a: Sequence[CommTerm],
               terms_b: Sequence[CommTerm]) -> float:
    """Total-wire-bytes ratio a/b — the q8-vs-f32 comparison number."""
    a = sum(t.wire_bytes for t in terms_a)
    b = sum(t.wire_bytes for t in terms_b)
    return a / b if b else math.inf


@dataclasses.dataclass(frozen=True)
class HierPrice:
    """A priced hierarchical allreduce (runtime/hierarchy.py): the three
    sequential legs, each on its own transport's fit."""

    intra_reduce_s: float
    inter_exchange_s: float
    intra_bcast_s: float
    #: per-leader bytes over the slow link — 2(H-1)/H x payload for the
    #: f32 leg (q8 inter shrinks the payload first); THE number the
    #: bench multihost phase verifies against the measured counter
    inter_wire_bytes: int
    extrapolated: bool
    terms: List[CommTerm] = dataclasses.field(default_factory=list)

    @property
    def seconds(self) -> float:
        return (self.intra_reduce_s + self.inter_exchange_s
                + self.intra_bcast_s)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["seconds"] = self.seconds
        d["terms"] = [t.to_dict() for t in self.terms]
        return d


def hierarchical_allreduce_seconds(
    payload_bytes: int,
    grad_elems: int,
    domain_sizes: Sequence[int],
    intra_model: CostModel,
    inter_model: CostModel,
    *,
    q8_inter: bool = False,
    fallback: Optional[CostModel] = None,
) -> HierPrice:
    """Price one hierarchical allreduce: intra-domain reduce -> one
    inter-domain leader exchange -> intra-domain broadcast
    (``runtime/hierarchy.py``'s decomposition), each leg on ITS OWN
    transport's α–β fit — the per-transport discipline
    ``CostModel.load(expected_transport=...)`` enforces is exactly what
    makes this sum meaningful (an shm β under the inter leg would
    underprice the slow link ~an order of magnitude).

    The legs are sequential (a leg cannot start before the previous
    completes), so the total is their SUM; within a leg every domain
    runs concurrently, so each leg's price is the MAX over its domains'
    sizes (equal-size domains — the only shape the group supports for
    all_gather — collapse to one prediction). ``q8_inter=True`` prices
    the quantized inter leg at its real wire occupancy
    (``q8_wire_payload``), falling back through
    :func:`price_comm_terms`'s flagged q8 path when the inter model has
    no ``all_reduce_q8`` fit. Degenerate shapes price honestly: one
    domain -> no inter leg; all domains singleton -> only the inter leg.
    """
    doms = [int(d) for d in domain_sizes]
    if not doms or any(d < 1 for d in doms):
        raise ValueError(f"bad domain sizes {domain_sizes!r}")
    H = len(doms)

    def leg_max(op: str, note: str) -> List[CommTerm]:
        sizes = sorted({d for d in doms if d > 1})
        terms = price_comm_terms(
            [CommTerm(op, int(payload_bytes), d, 1, note=note)
             for d in sizes],
            intra_model, fallback=fallback,
        )
        return terms

    intra_reduce = leg_max("all_reduce", "hier intra reduce")
    intra_bcast = leg_max("broadcast", "hier intra broadcast")
    inter_terms: List[CommTerm] = []
    if H > 1:
        if q8_inter:
            t = CommTerm("all_reduce_q8", q8_wire_payload(int(grad_elems)),
                         H, 1, note="hier inter exchange (q8)",
                         f32_bytes=int(payload_bytes))
        else:
            t = CommTerm("all_reduce", int(payload_bytes), H, 1,
                         note="hier inter exchange")
        inter_terms = price_comm_terms([t], inter_model,
                                       fallback=fallback)
    all_terms = intra_reduce + inter_terms + intra_bcast
    return HierPrice(
        intra_reduce_s=max((t.seconds for t in intra_reduce), default=0.0),
        inter_exchange_s=sum(t.seconds for t in inter_terms),
        intra_bcast_s=max((t.seconds for t in intra_bcast), default=0.0),
        inter_wire_bytes=sum(t.wire_bytes for t in inter_terms),
        extrapolated=any(t.extrapolated for t in all_terms),
        terms=all_terms,
    )
