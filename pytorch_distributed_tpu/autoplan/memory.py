"""Per-candidate memory accounting — abstract shapes only, zero compiles.

The planner must price a candidate BEFORE anything is placed, let
alone compiled, on meshes that may not even be buildable on this host
(price a v5p-64 fleet from a laptop). So accounting runs on the
``jax.eval_shape`` state (the kv_slots precedent: shape math, no
throwaway compiles) against a :class:`PlanMesh` — a duck-typed stand-in
carrying only the axis-size mapping, which is all the rule callables
(autoplan/rules.py, ``shard_along``, ``_augment_spec_with_axis``)
ever read. The per-leaf spec resolution is the STRATEGY'S OWN rule
assembly (``param_rules()`` / ``opt_rules()`` + the same
``best_param_suffix`` mismatch routing as ``infer_opt_tree_shardings``),
so the bytes priced here are the bytes the real placement produces.

Buckets, per device:

* ``param_bytes`` — params + batch_stats (replicated) + the EMA shadow
  (placed like params, by the same by-construction rule);
* ``opt_bytes`` — optimizer state, shape-mismatched (factored) leaves
  routed to the strategy's shape-generic fallback;
* ``grad_bytes`` — gradients are param-shaped and live at the params'
  placement inside the step (honest limit: FSDP's transient per-layer
  full gradient before its reduce-scatter is NOT modeled — this is the
  steady-state figure, same convention as the torch memory estimators);
* ``activation_bytes`` — the model profile's per-sample estimate times
  the per-device batch (honest limit: a coarse proxy; remat shrinks it
  and is not modeled).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from pytorch_distributed_tpu.autoplan.rules import axes_size
from pytorch_distributed_tpu.parallel.sharding import (
    PartitionRules,
    best_param_suffix,
    path_str,
)


class PlanMesh:
    """Duck-typed ``jax.sharding.Mesh`` stand-in for rule evaluation.

    Everything the rule machinery touches is ``mesh.shape`` (a mapping
    axis -> size); a real Mesh needs that many actual devices, which a
    planner pricing hypothetical fleets does not have.
    """

    def __init__(self, sizes: Dict[str, int]):
        self.shape = dict(sizes)

    def __repr__(self) -> str:  # shows up in candidate reprs/logs
        return f"PlanMesh({self.shape})"


def leaf_device_bytes(shape: Tuple[int, ...], itemsize: int, spec,
                      sizes: Dict[str, int]) -> int:
    """Bytes one device holds of a leaf placed under ``spec``.

    Mirrors NamedSharding's shard math for the divisible specs the rule
    engine guarantees; a non-divisible entry (only reachable through a
    hand-written rule) conservatively counts the full dim.
    """
    elems = 1
    entries = tuple(spec) if spec is not None else ()
    for i, dim in enumerate(shape):
        entry = entries[i] if i < len(entries) else None
        ways = axes_size(entry, sizes)
        elems *= dim // ways if ways > 1 and dim % ways == 0 else dim
    return int(elems) * int(itemsize)


def _leaf_meta(leaf) -> Tuple[Tuple[int, ...], int]:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    dtype = getattr(leaf, "dtype", None)
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    return shape, itemsize


def tree_device_bytes(tree, rules: PartitionRules,
                      mesh_like: PlanMesh) -> Tuple[int, int]:
    """(global_bytes, per_device_bytes) over a pytree of abstract leaves."""
    total = dev = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        shape, itemsize = _leaf_meta(leaf)
        spec = rules.spec_for(path_str(path), shape, mesh_like)
        total += math.prod(shape) * itemsize if shape else itemsize
        dev += leaf_device_bytes(shape, itemsize, spec, mesh_like.shape)
    return total, dev


def opt_device_bytes(opt_state, params, rules: PartitionRules,
                     mismatch_rules: PartitionRules,
                     mesh_like: PlanMesh) -> int:
    """Per-device optimizer-state bytes with the mismatch routing of
    ``infer_opt_tree_shardings``: param-shaped leaves take the path
    rules, rank-reduced (factored) leaves take the shape-generic
    fallback — same split, same suffix matcher."""
    param_shapes = {
        path_str(p): tuple(l.shape)
        for p, l in jax.tree_util.tree_leaves_with_path(params)
        if hasattr(l, "shape")
    }
    dev = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(opt_state):
        shape, itemsize = _leaf_meta(leaf)
        p = path_str(path)
        best = best_param_suffix(param_shapes, p)
        r = (
            mismatch_rules
            if best is not None and shape != param_shapes[best]
            else rules
        )
        spec = r.spec_for(p, shape, mesh_like)
        dev += leaf_device_bytes(shape, itemsize, spec, mesh_like.shape)
    return dev


@dataclasses.dataclass(frozen=True)
class MemoryBreakdown:
    """Per-device bytes for one candidate (see module docstring)."""

    param_bytes: int
    opt_bytes: int
    grad_bytes: int
    activation_bytes: int
    params_global_bytes: int  # unsharded model size, for reference

    @property
    def total_bytes(self) -> int:
        return (self.param_bytes + self.opt_bytes + self.grad_bytes
                + self.activation_bytes)

    def to_dict(self) -> dict:
        return {
            "param_bytes": self.param_bytes,
            "opt_bytes": self.opt_bytes,
            "grad_bytes": self.grad_bytes,
            "activation_bytes": self.activation_bytes,
            "total_bytes": self.total_bytes,
            "params_global_bytes": self.params_global_bytes,
        }


def account_state(abstract_state, strategy, mesh_like: PlanMesh,
                  activation_bytes: int) -> MemoryBreakdown:
    """Memory breakdown for ``abstract_state`` under ``strategy``.

    ``strategy`` is a real Strategy instance constructed over
    ``mesh_like`` — its ``param_rules()``/``opt_rules()`` are the
    production rule assembly, evaluated here without any placement.
    """
    param_rules = strategy.param_rules()
    opt_rules = strategy.opt_rules()
    mismatch = PartitionRules([(".*", strategy._fallback_opt_spec())])

    params_total, params_dev = tree_device_bytes(
        abstract_state.params, param_rules, mesh_like
    )
    param_bytes = params_dev
    # batch_stats / scaler_state replicate under every strategy
    for aux in (abstract_state.batch_stats, abstract_state.scaler_state):
        if aux is not None:
            aux_total, _ = tree_device_bytes(
                aux, PartitionRules([(".*", None)]), mesh_like
            )
            param_bytes += aux_total
    # the EMA shadow shards exactly like params (strategies.py pins this
    # by construction) — account it the same way
    if getattr(abstract_state, "ema_params", None) is not None:
        _, ema_dev = tree_device_bytes(
            abstract_state.ema_params, param_rules, mesh_like
        )
        param_bytes += ema_dev

    opt_dev = opt_device_bytes(
        abstract_state.opt_state, abstract_state.params,
        opt_rules, mismatch, mesh_like,
    )
    return MemoryBreakdown(
        param_bytes=int(param_bytes),
        opt_bytes=int(opt_dev),
        grad_bytes=int(params_dev),
        activation_bytes=int(activation_bytes),
        params_global_bytes=int(params_total),
    )


def device_budget_bytes() -> Optional[int]:
    """Per-device memory capacity, or None when no backend reports one.

    TPU/GPU allocators expose ``memory_stats()['bytes_limit']`` (the
    same source as ``compat.live_buffer_bytes``'s in-use reading);
    XLA:CPU reports nothing — the planner then skips the feasibility
    filter unless the caller passes an explicit budget.
    """
    limits = []
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
        except Exception:  # pragma: no cover - backend-dependent
            s = None
        if s and "bytes_limit" in s:
            limits.append(int(s["bytes_limit"]))
    return min(limits) if limits else None
