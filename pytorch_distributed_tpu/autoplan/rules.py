"""Shape-aware partition-rule engine: per-model rules become data.

Before this module every model family hand-rolled its TP rules, and
every mesh/shape corner became a per-model patch: the gemma MQA fix,
the qwen2 ragged-GQA fix (4 kv heads on tp=8 must replicate, not
crash), the scan-stacked leading layer dim. Those were all the SAME
rule — "shard THIS dim over THESE mesh axes, but only when the dim
divides them; otherwise replicate that dim and say so" — applied by
hand in N places. Here it is applied by an engine, once, so partition
rules are declarative :class:`TensorRule` rows and divisibility safety
is a property of the engine rather than of whichever author remembered
the incident.

The auto-parallel planner (autoplan/planner.py) builds its whole
candidate space on this: every (mesh shape x strategy) candidate gets
valid specs by construction, for any model whose rules are expressed
as TensorRules — no candidate can crash placement on an unshardable
axis, it can only (warn and) replicate.

Engine semantics, matching the hand-written rules they replace:

* ``spec`` names the TRAILING dims (like the old ``stacked()`` wrap):
  when ``stacked=True`` and the tensor has exactly one extra leading
  dim, that dim is the scan layer axis and stays unsharded.
* An entry naming mesh axes is KEPT when the axes' total size is 1
  (size-1 axes live in every mesh so specs stay valid) and DROPPED —
  replicating that dim, with a once-per-shape warning — when the dim
  does not divide the axes' size. That is the generic form of the
  gemma/qwen2 kv-head fallback.
* A rank mismatch that is not the stacked +1 case applies the spec
  as-is: for params that must fail loudly downstream (bad rule), and
  rank-reduced optimizer states are routed around path rules by
  ``infer_opt_tree_shardings`` (parallel/sharding.py).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, List, Optional, Sequence, Tuple, Union

from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: one spec entry: unsharded, one mesh axis, or several mesh axes
Entry = Union[None, str, Tuple[str, ...]]


@dataclasses.dataclass(frozen=True)
class TensorRule:
    """One declarative partition rule: path pattern -> trailing-dim spec."""

    pattern: str  # path regex (PartitionRules semantics: search, first wins)
    spec: Tuple[Entry, ...]  # entries for the trailing dims
    stacked: bool = True  # tolerate one extra leading (scan layer) dim
    note: str = ""  # appended to the replication warning for context


def _axes_of(entry: Entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def axes_size(entry: Entry, sizes) -> int:
    """Total ways an entry shards over, given mesh axis sizes."""
    return math.prod(sizes.get(a, 1) for a in _axes_of(entry)) if entry else 1


# once per (pattern, dim size, entry, axes size): spec_for runs per LEAF
# per placement pass — an unrolled 32-layer model would otherwise repeat
# the same warning 64+ times (the original kv-replication dedup, kept)
_warned: set = set()


def reset_warned() -> None:
    """Clear the warning dedup set (tests asserting the warning fires)."""
    _warned.clear()


def compile_rule(rule: TensorRule) -> Callable[[Tuple[int, ...], object], P]:
    """A ``(shape, mesh) -> PartitionSpec`` callable for PartitionRules.

    ``mesh`` only needs a ``.shape`` mapping of axis sizes, so the same
    compiled rule serves a real ``jax.sharding.Mesh`` at placement time
    and the planner's :class:`~pytorch_distributed_tpu.autoplan.memory.
    PlanMesh` stand-in when pricing a mesh that is not built yet.
    """

    def spec_fn(shape: Tuple[int, ...], mesh) -> P:
        entries: List[Entry] = list(rule.spec)
        if rule.stacked and len(shape) == len(entries) + 1:
            entries = [None] + entries
        sizes = dict(mesh.shape)
        out: List[Entry] = []
        for i, entry in enumerate(entries):
            size = axes_size(entry, sizes)
            if (
                entry is not None
                and size > 1
                and i < len(shape)
                and shape[i] % size != 0
            ):
                key = (rule.pattern, entry, shape[i], size)
                if key not in _warned:
                    _warned.add(key)
                    logger.warning(
                        "partition rule %r: dim %d (size %d) does not "
                        "divide mesh axes %r (%d ways) — replicating "
                        "that dim (tensor shape %s)%s",
                        rule.pattern, i, shape[i], _axes_of(entry), size,
                        tuple(shape),
                        f"; {rule.note}" if rule.note else "",
                    )
                out.append(None)
            else:
                out.append(entry)
        return P(*out)

    return spec_fn


def engine_rules(
    rules: Sequence[TensorRule],
) -> List[Tuple[str, Callable[[Tuple[int, ...], object], P]]]:
    """Compile TensorRules into the ``(pattern, spec)`` pairs every
    ``extra_rules=`` consumer (parallel/strategies.py) takes."""
    return [(r.pattern, compile_rule(r)) for r in rules]


def replicated_rule(pattern: str, ndim: int, *, stacked: bool = True,
                    note: str = "") -> TensorRule:
    """A rule that pins ``pattern`` replicated (the forced-MQA form)."""
    return TensorRule(pattern, (None,) * ndim, stacked=stacked, note=note)


def max_divisible_tp(dims: Sequence[int], n_devices: int) -> List[int]:
    """Candidate tp widths: divisors of ``n_devices`` that also divide
    every dim in ``dims`` (e.g. a model's head count) — the enumeration
    helper candidates.py uses so the candidate space stays inside what
    the rule engine can shard without falling back to replication."""
    out = []
    for t in range(1, n_devices + 1):
        if n_devices % t:
            continue
        if all(d % t == 0 for d in dims if d):
            out.append(t)
    return out
