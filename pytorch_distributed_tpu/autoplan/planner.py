"""The auto-parallel planner: enumerate, account, price, rank, report.

AMP-style strategy search (arxiv 2210.07297) over this repo's own
ingredients: candidates from candidates.py, per-device memory from
memory.py (eval_shape only — planning NEVER compiles), comms priced
through the calibrated α–β model from ``collective_bench --fit``
(runtime/costmodel.py) and a compute term (pricing.py). The output is
a :class:`Plan`: every candidate with its memory/comms/compute
breakdown and why the losers lost, a ranked ``plan.json`` artifact, a
``split="plan"`` MetricsWriter stream, and one chosen, constructible
strategy — what ``--strategy auto`` in the recipes runs.

The plan is an AUDIT DOCUMENT first: a planner whose choice cannot be
interrogated is folklore with extra steps. Extrapolated predictions
and uncalibrated fallbacks are flagged on every record they touch.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import List, Optional, Sequence, Tuple

import jax

from pytorch_distributed_tpu.autoplan.candidates import (
    STRATEGY_CLASSES,
    CandidateSpec,
    enumerate_candidates,
)
from pytorch_distributed_tpu.autoplan.memory import (
    MemoryBreakdown,
    PlanMesh,
    account_state,
    device_budget_bytes,
)
from pytorch_distributed_tpu.autoplan.pricing import (
    CommTerm,
    ComputeModel,
    ModelProfile,
    compute_seconds,
    exposed_comm_seconds,
    grad_comm_terms,
    hetero_compute_seconds,
    pipeline_comm_terms,
    pipeline_compute_split,
    price_comm_terms,
    tp_comm_terms,
)
from pytorch_distributed_tpu.runtime.costmodel import (
    ANALYTIC_TRANSPORT,
    CostModel,
    CostModelUnavailable,
    analytic_cost_model,
    calibration_command,
)
from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: plan.json schema version
PLAN_FORMAT_VERSION = 1

_AUTO = object()  # budget sentinel: "detect from the backend"


class PlanError(RuntimeError):
    """No feasible candidate (or the planner was misconfigured)."""


def param_count(params) -> int:
    """Leaf-element count of an (abstract or concrete) param tree."""
    return sum(
        math.prod(l.shape) if getattr(l, "shape", ()) else 1
        for l in jax.tree_util.tree_leaves(params)
    )


@dataclasses.dataclass
class PricedCandidate:
    spec: CandidateSpec
    memory: MemoryBreakdown
    comm_terms: List[CommTerm]
    comm_seconds: float
    compute_seconds: float
    feasible: bool
    reason: str = ""  # why infeasible (empty when feasible)
    why_not: str = ""  # vs the winner (empty for the winner)
    rank: Optional[int] = None  # 1-based among feasible candidates
    extrapolated: bool = False  # any comm term off the calibrated range
    #: round-14 overlap pricing: grad-sync comm hidden under the step's
    #: overlappable compute (0 when the plan priced serialized comms)
    hidden_comm_seconds: float = 0.0
    #: round-15 heterogeneous pricing (rank_rates given): the even and
    #: balanced splits' compute terms, BOTH always recorded whichever
    #: one compute_seconds carried (plan(balanced=False) prices the
    #: even baseline but must still report the balancer's gain) — the
    #: delta is the balancer's priced gain
    compute_seconds_even: Optional[float] = None
    compute_seconds_balanced: Optional[float] = None
    #: round-20 pipeline pricing: warm-up/drain bubble seconds — the
    #: analytic (S-1)/(M+S-1) fraction of the pipelined step, on the
    #: critical path like compute, never overlappable (0 for pp == 1)
    bubble_seconds: float = 0.0
    #: round-20: the pp audit record — {"pp", "num_microbatches",
    #: "bubble_fraction", "bubble_seconds", "link_seconds",
    #: "stage_depths"}; None for unpipelined candidates
    pipeline: Optional[dict] = None

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def step_seconds(self) -> float:
        return self.comm_seconds + self.compute_seconds \
            + self.bubble_seconds - self.hidden_comm_seconds

    # recipe-facing conveniences: the chosen candidate IS the thing a
    # recipe needs to build (mesh spec first, then the strategy)
    def mesh_spec(self):
        return self.spec.mesh_spec()

    def build_strategy(self, *, extra_rules=(), mesh=None):
        return self.spec.build_strategy(extra_rules=extra_rules, mesh=mesh)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "strategy": self.spec.strategy,
            "mesh": {k: v for k, v in self.spec.mesh_sizes().items()
                     if v > 1} or {"dp": 1},
            "compress": self.spec.compress,
            "feasible": self.feasible,
            "reason": self.reason,
            "why_not": self.why_not,
            "rank": self.rank,
            "memory": self.memory.to_dict(),
            "comms": {
                "seconds": self.comm_seconds,
                "hidden_seconds": self.hidden_comm_seconds,
                "exposed_seconds": (
                    self.comm_seconds - self.hidden_comm_seconds
                ),
                "terms": [t.to_dict() for t in self.comm_terms],
            },
            "compute_seconds": self.compute_seconds,
            "step_seconds": self.step_seconds,
            "extrapolated": self.extrapolated,
            **({"pipeline": dict(self.pipeline)}
               if self.pipeline is not None else {}),
            **(
                {
                    "hetero": {
                        "compute_seconds_even": self.compute_seconds_even,
                        "compute_seconds_balanced":
                            self.compute_seconds_balanced,
                        "balance_gain": (
                            self.compute_seconds_even
                            / self.compute_seconds_balanced
                            if (self.compute_seconds_balanced or 0) > 0
                            else 1.0
                        ),
                    }
                }
                if self.compute_seconds_even is not None
                else {}
            ),
        }


@dataclasses.dataclass
class Plan:
    candidates: List[PricedCandidate]  # ranked: feasible first, by price
    n_devices: int
    global_batch: int
    budget_bytes: Optional[int]
    cost_model_transport: str
    cost_model_path: Optional[str]
    uncalibrated: bool  # analytic comms model and/or assumed compute
    compute_source: str
    #: True when candidates were priced with the round-14 overlapped
    #: grad sync (exposed-comm = max(0, comm - overlappable compute))
    overlap_grad_sync: bool = False
    #: round-15: the per-device relative speed vector the compute terms
    #: were priced with (None = homogeneous fleet assumed)
    rank_rates: Optional[List[float]] = None
    #: whether the heterogeneous compute term priced the BALANCED split
    #: (train/balance.py's apportionment) or the even baseline
    balanced: bool = True

    @property
    def chosen(self) -> Optional[PricedCandidate]:
        for c in self.candidates:
            if c.feasible:
                return c
        return None

    def best(self) -> PricedCandidate:
        c = self.chosen
        if c is None:
            # diagnose from the ACTUAL rejection reasons: "raise the
            # budget" is wrong advice when every candidate fell to
            # batch divisibility
            reasons = sorted({c.reason for c in self.candidates
                              if c.reason})
            detail = "; ".join(reasons[:3]) or "no candidates enumerated"
            hint = ""
            if any("budget" in r for r in reasons):
                budget = (f"{self.budget_bytes / 1e9:.2f} GB"
                          if self.budget_bytes else "unknown")
                smallest = min(
                    self.candidates,
                    key=lambda c: c.memory.total_bytes, default=None,
                )
                hint = (
                    f" — budget {budget}/device, smallest candidate "
                    f"{smallest.name} needs "
                    f"{smallest.memory.total_bytes / 1e9:.2f} GB/device"
                    if smallest else ""
                )
            raise PlanError(
                f"no feasible candidate for {self.n_devices} "
                f"device(s): {detail}{hint}"
            )
        return c

    def to_dict(self) -> dict:
        return {
            "format_version": PLAN_FORMAT_VERSION,
            "generated_by": "pytorch_distributed_tpu.autoplan",
            "n_devices": self.n_devices,
            "global_batch": self.global_batch,
            "budget_bytes_per_device": self.budget_bytes,
            "cost_model": {
                "transport": self.cost_model_transport,
                "path": self.cost_model_path,
                "source": (
                    "analytic-guess"
                    if self.cost_model_transport == ANALYTIC_TRANSPORT
                    else "calibrated"
                ),
            },
            "compute_model": {"source": self.compute_source},
            "uncalibrated": self.uncalibrated,
            "overlap_grad_sync": self.overlap_grad_sync,
            **(
                {"rank_rates": list(self.rank_rates),
                 "balanced": self.balanced}
                if self.rank_rates is not None else {}
            ),
            "chosen": self.chosen.name if self.chosen else None,
            "candidates": [c.to_dict() for c in self.candidates],
        }

    def save(self, path: str) -> str:
        """Atomic plan.json write (same discipline as costmodel.save)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path

    def write_metrics(self, writer, *, step: int = 0) -> None:
        """One ``split="plan"`` record per candidate + a summary record
        through the MetricsWriter JSONL protocol — plan history becomes
        greppable data like every other measurement here."""
        for c in self.candidates:
            writer.write(step, {
                "event": "candidate",
                "candidate": c.name,
                "strategy": c.spec.strategy,
                "rank": -1 if c.rank is None else c.rank,
                "feasible": int(c.feasible),
                "chosen": int(self.chosen is c),
                "step_ms": c.step_seconds * 1e3,
                "comm_ms": c.comm_seconds * 1e3,
                "compute_ms": c.compute_seconds * 1e3,
                "mem_per_device_mb": c.memory.total_bytes / 1e6,
                "extrapolated": int(c.extrapolated),
            }, split="plan")
        writer.write(step, {
            "event": "plan_summary",
            "n_candidates": len(self.candidates),
            "n_feasible": sum(1 for c in self.candidates if c.feasible),
            "chosen": self.chosen.name if self.chosen else "<none>",
            "n_devices": self.n_devices,
            "global_batch": self.global_batch,
            "uncalibrated": int(self.uncalibrated),
        }, split="plan")

    def table(self) -> str:
        return "\n".join(format_plan(self.to_dict()))


def format_plan(doc: dict) -> List[str]:
    """Render a plan.json dict as the audit table (shared by
    ``Plan.table`` and the obs_report Plan section)."""
    lines = []
    cm = doc.get("cost_model", {})
    budget = doc.get("budget_bytes_per_device")
    lines.append(
        f"auto-parallel plan: {doc.get('n_devices')} device(s), global "
        f"batch {doc.get('global_batch')}, budget "
        + (f"{budget / 1e9:.2f} GB/device" if budget else "unknown")
    )
    lines.append(
        f"  comms model: {cm.get('source')} "
        f"(transport={cm.get('transport')}); compute: "
        f"{doc.get('compute_model', {}).get('source')}"
    )
    if doc.get("uncalibrated"):
        lines.append(
            "  UNCALIBRATED: prices are analytic guesses — run "
            f"`{calibration_command()}` for a real ranking"
        )
    rates = doc.get("rank_rates")
    if rates:
        mode = "balanced" if doc.get("balanced", True) else "EVEN (off)"
        lines.append(
            "  fleet: heterogeneous per-device rates "
            f"{[round(float(r), 3) for r in rates]} — compute priced on "
            f"the {mode} microshard split (train/balance.py); each "
            "candidate's [bal ...x] is its balanced-vs-even compute gain"
        )
    header = ("rank", "candidate", "step_ms", "comm_ms", "compute_ms",
              "mem/dev_MB", "verdict")
    rows = doc.get("candidates", [])
    w0 = max([len("candidate")] + [len(c["name"]) for c in rows])
    widths = (4, w0, 9, 9, 10, 10, 44)
    lines.append("  " + "  ".join(
        str(h).ljust(w) for h, w in zip(header, widths)
    ))
    chosen = doc.get("chosen")
    for c in rows:
        if not c.get("feasible"):
            verdict = f"INFEASIBLE: {c.get('reason', '')}"
        elif c["name"] == chosen:
            verdict = "CHOSEN"
        else:
            verdict = c.get("why_not", "")
        if c.get("extrapolated"):
            verdict += " [extrapolated]"
        hetero = c.get("hetero")
        if hetero:
            verdict += f" [bal {hetero.get('balance_gain', 1.0):.2f}x]"
        lines.append("  " + "  ".join(str(v).ljust(w) for v, w in zip(
            ("-" if c.get("rank") is None else c["rank"],
             c["name"],
             f"{c['step_seconds'] * 1e3:.3f}",
             f"{c['comms']['seconds'] * 1e3:.3f}",
             f"{c['compute_seconds'] * 1e3:.3f}",
             f"{c['memory']['total_bytes'] / 1e6:.1f}",
             verdict),
            widths,
        )))
    return lines


def resolve_cost_model(
    cost_model: Optional[CostModel],
    cost_model_path: Optional[str],
    *,
    transport: Optional[str] = None,
    worlds: Sequence[int] = (),
) -> Tuple[CostModel, bool]:
    """(model, uncalibrated): the passed model, the loaded file, or —
    loudly — the analytic bandwidth guess."""
    if cost_model is not None:
        return cost_model, cost_model.transport == ANALYTIC_TRANSPORT
    if cost_model_path is not None:
        try:
            return CostModel.load(
                cost_model_path, expected_transport=transport
            ), False
        except CostModelUnavailable as e:
            logger.warning(
                "autoplan: %s — degrading to the analytic "
                "bandwidth-guess model; the plan will be flagged "
                "uncalibrated", e,
            )
    else:
        logger.warning(
            "autoplan: no cost model given — using the analytic "
            "bandwidth-guess model (uncalibrated); calibrate with "
            "`%s`", calibration_command(),
        )
    return analytic_cost_model(worlds), True


def plan(
    *,
    profile: ModelProfile,
    global_batch: int,
    accum_steps: int = 1,
    abstract_state=None,
    make_state_fn=None,
    state_args: Sequence = (),
    n_devices: Optional[int] = None,
    extra_rules: Sequence = (),
    strategies: Sequence[str] = STRATEGY_CLASSES,
    tp_candidates: Optional[Sequence[int]] = None,
    max_tp: Optional[int] = None,
    include_q8: bool = False,
    pp_candidates: Optional[Sequence[int]] = None,
    max_pp: Optional[int] = None,
    pp_microbatches: Optional[int] = None,
    cost_model: Optional[CostModel] = None,
    cost_model_path: Optional[str] = None,
    transport: Optional[str] = None,
    compute: Optional[ComputeModel] = None,
    budget_bytes=_AUTO,
    overlap_grad_sync: bool = False,
    rank_rates: Optional[Sequence[float]] = None,
    microshards: Optional[int] = None,
    balanced: bool = True,
) -> Plan:
    """Price every candidate and rank the feasible ones.

    ``rank_rates`` (r15) prices a HETEROGENEOUS fleet: one relative
    speed multiplier per device (1.0 = the compute model's nominal
    rate). The compute term becomes ``max over data ways of (assigned
    work / way rate)`` — ``pricing.hetero_compute_seconds``, using the
    engine's own microshard apportionment (``microshards`` units,
    default the granularity floor) so the plan predicts what
    ``train/balance.py`` will actually assign. ``balanced=False``
    prices the balance=off even split instead; every candidate records
    both numbers (``hetero.compute_seconds_even`` vs ``..._balanced``),
    so the table shows the balancer's priced gain per candidate.

    ``overlap_grad_sync=True`` prices the round-14 overlapped gradient
    sync instead of the serialized upper bound: the GRAD exchange terms
    (dp allreduce / zero1 / fsdp — never the tp activation collectives,
    which sit on the forward/backward critical path) hide under the
    step's overlappable compute window, ``compute x (accum-1)/accum``
    (the microbatch span a host-loop step can reduce under), and only
    ``pricing.exposed_comm_seconds`` of them extends the step. An
    optimistic bound where the default is a pessimistic one — both are
    recorded on plan.json (``overlap_grad_sync``, per-candidate
    ``comms.hidden_seconds``), so the audit trail says which assumption
    ranked the table.

    Pure host-side: ONE ``jax.eval_shape`` of the state constructor
    (when ``abstract_state`` is not passed directly) and shape/float
    arithmetic after that — no compile, no placement, no device work.
    """
    if abstract_state is None:
        if make_state_fn is None:
            raise ValueError("pass abstract_state or make_state_fn")
        abstract_state = jax.eval_shape(make_state_fn, *state_args)
    if n_devices is None:
        n_devices = len(jax.devices())
    if budget_bytes is _AUTO:
        budget_bytes = device_budget_bytes()
    if rank_rates is not None:
        rank_rates = [float(r) for r in rank_rates]
        if len(rank_rates) != n_devices:
            raise ValueError(
                f"rank_rates has {len(rank_rates)} entries for "
                f"{n_devices} device(s) — one relative rate per device"
            )
        if any(r <= 0 for r in rank_rates):
            raise ValueError(f"rank_rates must be positive: {rank_rates}")
    if tp_candidates is None and max_tp is None:
        # no model-dimension information: enumerating every tp divisor
        # would price tp widths the model's heads may not divide (the
        # engine replicates those kernels, but the grad-payload
        # arithmetic assumes tp-sharded grads — an underpriced ghost
        # candidate). Opening the tp dimension is an explicit opt-in:
        # pass tp_candidates=rules.max_divisible_tp(...) or max_tp.
        max_tp = 1
    if pp_candidates is None and max_pp is None:
        # same opt-in discipline for the pipeline dimension (r20): a
        # pp split is a LAYER split, and pricing one honestly needs the
        # caller to confirm the recipe can actually run the pipelined
        # loss (recipes pass --pp through as max_pp). Default stays
        # the unpipelined search space.
        max_pp = 1
    specs = enumerate_candidates(
        n_devices, strategies=strategies, tp_candidates=tp_candidates,
        max_tp=max_tp, include_q8=include_q8,
        pp_candidates=pp_candidates, max_pp=max_pp,
    )
    # pipeline handoffs are P2P: the cost-model world for a send/recv
    # term is the ordered 2-rank pair, whatever the stage count
    worlds = sorted(
        {s.data for s in specs} | {s.tp for s in specs}
        | ({2} if any(s.pp > 1 for s in specs) else set())
    )
    model, uncalibrated = resolve_cost_model(
        cost_model, cost_model_path, transport=transport, worlds=worlds,
    )
    if compute is None:
        compute = ComputeModel.assumed(jax.default_backend())
    uncalibrated = uncalibrated or not compute.calibrated
    # a PARTIALLY calibrated model (collective_bench keeps later ops
    # running when one fails) must not crash pricing: ops it lacks are
    # priced on the analytic guess, flagged per term
    fallback = (
        None if model.transport == ANALYTIC_TRANSPORT
        else analytic_cost_model(worlds)
    )

    priced: List[PricedCandidate] = []
    for spec in specs:
        mesh_like = PlanMesh(spec.mesh_sizes())
        if spec.pp > 1:
            # memory must be accounted against the strategy the recipe
            # would actually build: the pp-sharded layer stack
            # (pipeline_lm's rules duck-type over PlanMesh like the
            # others — planning still never compiles)
            from pytorch_distributed_tpu.parallel.pipeline_lm import (
                PipelineParallel,
            )

            strategy = PipelineParallel(
                mesh_like, extra_rules=tuple(extra_rules)
            )
        else:
            strategy = spec.strategy_class()(
                mesh_like, extra_rules=tuple(extra_rules)
            )
        data = spec.data
        feasible, reason = True, ""
        if global_batch % data != 0 or global_batch < data:
            feasible = False
            reason = (f"global batch {global_batch} does not split over "
                      f"{data} data way(s)")
        per_dev_batch = max(global_batch // data, 1)
        # r20: the pipelined step's microbatch count plays the accum
        # role (the executor folds grads across M microbatches); the
        # recipe default keeps >= 2*S in flight so 1F1B has a steady
        # state to amortize the (S-1)-tick bubble over
        num_mb = max(accum_steps, 1)
        if spec.pp > 1:
            num_mb = pp_microbatches or max(accum_steps, 2 * spec.pp)
            if feasible and per_dev_batch % num_mb != 0:
                feasible = False
                reason = (
                    f"per-device batch {per_dev_batch} does not split "
                    f"into {num_mb} microbatch(es) "
                    f"(HostPipelineStep splits the batch dim evenly)"
                )
        # live activations are per MICROBATCH: grad accumulation scans
        # accum_steps slices inside the jitted step, one slice resident
        # — a pipeline stage instead holds its 1/pp layer share of up
        # to min(pp, M) in-flight microbatches (1F1B's peak at stage 0)
        micro_batch = max(-(-per_dev_batch // num_mb), 1)
        act_scale = min(spec.pp, num_mb) / spec.pp
        memory = account_state(
            abstract_state, strategy, mesh_like,
            activation_bytes=int(
                profile.activation_bytes_per_sample * micro_batch
                * act_scale
            ),
        )
        if feasible and budget_bytes is not None \
                and memory.total_bytes > budget_bytes:
            feasible = False
            reason = (f"needs {memory.total_bytes / 1e9:.2f} GB/device "
                      f"> budget {budget_bytes / 1e9:.2f} GB")
        # gradient exchange payload: with tp the grads are already
        # tp-sharded, so each tp group reduces only its shard; with pp
        # each stage's data ways reduce only the stage's layer share
        grad_payload = memory.params_global_bytes // (spec.tp * spec.pp)
        grad_elems = grad_payload // 4  # f32 grads (param dtype)
        gterms = price_comm_terms(
            grad_comm_terms(
                spec.strategy, grad_payload, grad_elems, data,
                compress=spec.compress,
            ), model, fallback=fallback,
        )
        tterms = price_comm_terms(
            tp_comm_terms(profile, micro_batch, spec.tp,
                          accum_steps=accum_steps),
            model, fallback=fallback,
        )
        pterms = price_comm_terms(
            pipeline_comm_terms(profile, micro_batch, spec.pp, num_mb),
            model, fallback=fallback,
        )
        terms = gterms + tterms + pterms
        comm_s = sum(t.seconds for t in terms)
        link_s = sum(t.seconds for t in pterms)
        comp_even = comp_bal = None
        bubble_s = 0.0
        pipeline_doc = None
        if spec.pp > 1:
            # stage s owns the next data*tp consecutive devices; its
            # rate is the group MIN (a stage's data ways commit in
            # lockstep at the grad fold)
            stage_rates = None
            if rank_rates is not None:
                g = spec.data * spec.tp
                stage_rates = [
                    min(rank_rates[s * g:(s + 1) * g])
                    for s in range(spec.pp)
                ]
            depths = None
            comp_s = 0.0
            try:
                comp_s, bubble_s, depths = pipeline_compute_split(
                    profile, global_batch, compute,
                    data=data, tp=spec.tp, pp=spec.pp,
                    num_microbatches=num_mb, stage_rates=stage_rates,
                )
            except ValueError as e:
                if feasible:
                    feasible = False
                    reason = str(e)
            pipeline_doc = {
                "pp": spec.pp,
                "num_microbatches": num_mb,
                "bubble_fraction": (
                    (spec.pp - 1) / (num_mb + spec.pp - 1)
                ),
                "bubble_seconds": bubble_s,
                "link_seconds": link_s,
                "stage_depths": list(depths) if depths else None,
            }
        elif rank_rates is not None:
            comp_bal = hetero_compute_seconds(
                profile, global_batch, compute, rank_rates,
                tp=spec.tp, microshards=microshards, balanced=True,
            )
            comp_even = hetero_compute_seconds(
                profile, global_batch, compute, rank_rates,
                tp=spec.tp, microshards=microshards, balanced=False,
            )
            comp_s = comp_bal if balanced else comp_even
        else:
            comp_s = compute_seconds(profile, global_batch, n_devices,
                                     compute)
        hidden_s = 0.0
        if overlap_grad_sync:
            grad_s = sum(t.seconds for t in gterms)
            overlappable = comp_s * (accum_steps - 1) / max(accum_steps, 1)
            hidden_s = grad_s - exposed_comm_seconds(grad_s, overlappable)
        priced.append(PricedCandidate(
            spec=spec, memory=memory, comm_terms=terms,
            comm_seconds=comm_s, compute_seconds=comp_s,
            hidden_comm_seconds=hidden_s,
            compute_seconds_even=comp_even,
            compute_seconds_balanced=comp_bal,
            bubble_seconds=bubble_s,
            pipeline=pipeline_doc,
            feasible=feasible, reason=reason,
            extrapolated=any(t.extrapolated for t in terms),
        ))

    feasible = sorted(
        (c for c in priced if c.feasible),
        key=lambda c: (c.step_seconds, c.name),
    )
    infeasible = sorted(
        (c for c in priced if not c.feasible), key=lambda c: c.name
    )
    for i, c in enumerate(feasible):
        c.rank = i + 1
        if i > 0:
            w = feasible[0]
            delta = (c.step_seconds - w.step_seconds) * 1e3
            if c.spec.pp > 1:
                # a losing pipeline candidate must name its OWN price:
                # the warm-up/drain bubble and the per-link handoffs
                # are what the bubble-vs-parallelism trade bought
                link = (c.pipeline or {}).get("link_seconds", 0.0)
                bound = (f"bubble {c.bubble_seconds * 1e3:.3f} ms + "
                         f"links {link * 1e3:.3f} ms")
            elif c.comm_seconds - w.comm_seconds >= \
                    c.compute_seconds - w.compute_seconds:
                bound = (f"comms {c.comm_seconds * 1e3:.3f} vs "
                         f"{w.comm_seconds * 1e3:.3f} ms")
            else:
                bound = (f"compute {c.compute_seconds * 1e3:.3f} vs "
                         f"{w.compute_seconds * 1e3:.3f} ms")
            c.why_not = f"+{delta:.3f} ms vs {w.name} ({bound})"
    return Plan(
        candidates=feasible + infeasible,
        n_devices=n_devices,
        global_batch=global_batch,
        budget_bytes=budget_bytes,
        cost_model_transport=model.transport,
        # record the path only when the file actually priced this plan:
        # an analytic fallback next to path="costmodel.json" would read
        # as "that file was used" in the audit artifact
        cost_model_path=(
            cost_model_path
            if cost_model is None
            and model.transport != ANALYTIC_TRANSPORT
            else None
        ),
        uncalibrated=uncalibrated,
        compute_source=compute.source,
        overlap_grad_sync=overlap_grad_sync,
        rank_rates=rank_rates,
        balanced=balanced,
    )


def reference_sweep(n_devices: Optional[int] = None) -> dict:
    """Plan the two reference configs (GPT-2 LM, ResNet-50-shaped conv)
    end to end — the bench ``planning`` phase times this, and the wall
    clock covers ONLY planning (imports/model construction excluded).
    Returns chosen names, candidate counts and the planning wall time.
    """
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_tpu.autoplan.pricing import (
        image_profile,
        transformer_profile,
    )
    from pytorch_distributed_tpu.models import (
        GPT2Config,
        GPT2LMHead,
        ResNet50,
        gpt2_partition_rules,
    )
    from pytorch_distributed_tpu.train import TrainState

    if n_devices is None:
        n_devices = len(jax.devices())
    gpt_cfg = GPT2Config.tiny()
    seq_len = gpt_cfg.n_positions
    gpt = GPT2LMHead(gpt_cfg)

    def make_gpt_state(key):
        variables = gpt.init(key, jnp.zeros((1, seq_len), jnp.int32))
        return TrainState.create(
            apply_fn=gpt.apply, params=variables["params"],
            tx=optax.adamw(1e-3),
        )

    resnet = ResNet50(num_classes=1000)

    def make_resnet_state(key):
        variables = resnet.init(
            key, jnp.zeros((1, 64, 64, 3), jnp.float32), train=False
        )
        return TrainState.create(
            apply_fn=resnet.apply, params=variables["params"],
            tx=optax.sgd(0.1, momentum=0.9),
            batch_stats=variables["batch_stats"],
        )

    # abstract states OUTSIDE the timed window: eval_shape traces the
    # model once and is shared by every candidate; the planning wall
    # this sweep reports is the planner's own cost over a ready state
    key = jax.random.key(0)
    gpt_state = jax.eval_shape(make_gpt_state, key)
    resnet_state = jax.eval_shape(make_resnet_state, key)
    gpt_params = param_count(gpt_state.params)

    t0 = time.perf_counter()
    gpt_plan = plan(
        profile=transformer_profile(
            num_layers=gpt_cfg.num_layers, hidden_size=gpt_cfg.hidden_size,
            seq_len=seq_len, param_count=gpt_params,
        ),
        global_batch=32,
        abstract_state=gpt_state,
        n_devices=n_devices,
        extra_rules=gpt2_partition_rules(),
        max_tp=gpt_cfg.num_heads,
        include_q8=True,
    )
    resnet_plan = plan(
        profile=image_profile(
            flops_per_sample=3 * 4.1e9 * (64 / 224) ** 2,
            activation_bytes_per_sample=64e6 * (64 / 224) ** 2,
        ),
        global_batch=64,
        abstract_state=resnet_state,
        n_devices=n_devices,
        strategies=("dp", "zero1"),
        max_tp=1,
    )
    wall = time.perf_counter() - t0
    return {
        "wall_s": round(wall, 4),
        "n_devices": n_devices,
        "configs": {
            "gpt2_tiny": {
                "chosen": gpt_plan.best().name,
                "n_candidates": len(gpt_plan.candidates),
                "uncalibrated": gpt_plan.uncalibrated,
            },
            "resnet50": {
                "chosen": resnet_plan.best().name,
                "n_candidates": len(resnet_plan.candidates),
                "uncalibrated": resnet_plan.uncalibrated,
            },
        },
    }
