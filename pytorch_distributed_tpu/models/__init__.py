"""Model zoo: native TPU-first implementations of the reference's recipe
models (BASELINE.json:6-12) — ResNet-18/50, BERT-base, GPT-2-medium,
Llama-3-8B — plus beyond-reference families sharing the same machinery:
ViT, T5, and the Llama-body config variants (Llama-3.1/3.2, Mistral,
Qwen2, Gemma, sparse-MoE Mixtral; see docs/MIGRATION.md "Model zoo").
All NHWC / bf16-compute / f32-params by default, written against the
framework's precision policy and partition-rule system; every family is
HF-logit-parity pinned with import AND export (interop.py).
"""

from pytorch_distributed_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from pytorch_distributed_tpu.models.bert import (
    BertConfig,
    BertModel,
    BertForMaskedLM,
    BertForSequenceClassification,
    mask_tokens,
    bert_partition_rules,
)
from pytorch_distributed_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHead,
    gpt2_partition_rules,
)
from pytorch_distributed_tpu.models.vit import (
    ViT,
    ViTConfig,
    vit_partition_rules,
)
from pytorch_distributed_tpu.models.t5 import (
    T5Config,
    T5ForConditionalGeneration,
    generate_encdec,
    shift_right,
    t5_partition_rules,
)
from pytorch_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    RopeScaling,
    llama_partition_rules,
)
from pytorch_distributed_tpu.models.mistral import (
    MistralConfig,
    MistralForCausalLM,
    mistral_partition_rules,
)
from pytorch_distributed_tpu.models.gemma import (
    GemmaConfig,
    GemmaForCausalLM,
    gemma_partition_rules,
)
from pytorch_distributed_tpu.models.neox import (
    NeoXConfig,
    NeoXForCausalLM,
    neox_partition_rules,
)
from pytorch_distributed_tpu.models.phi3 import (
    Phi3Config,
    Phi3ForCausalLM,
    phi3_partition_rules,
)
from pytorch_distributed_tpu.models.qwen2 import (
    Qwen2Config,
    Qwen2ForCausalLM,
    qwen2_partition_rules,
)
from pytorch_distributed_tpu.models.qwen3 import (
    Qwen3Config,
    Qwen3ForCausalLM,
    qwen3_partition_rules,
)
from pytorch_distributed_tpu.models.mixtral import (
    MixtralConfig,
    MixtralForCausalLM,
    mixtral_partition_rules,
)

__all__ = [
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "BertConfig",
    "BertModel",
    "BertForMaskedLM",
    "BertForSequenceClassification",
    "mask_tokens",
    "bert_partition_rules",
    "GPT2Config",
    "GPT2LMHead",
    "gpt2_partition_rules",
    "LlamaConfig",
    "LlamaForCausalLM",
    "RopeScaling",
    "MistralConfig",
    "MistralForCausalLM",
    "mistral_partition_rules",
    "GemmaConfig",
    "GemmaForCausalLM",
    "gemma_partition_rules",
    "NeoXConfig",
    "NeoXForCausalLM",
    "neox_partition_rules",
    "Phi3Config",
    "Phi3ForCausalLM",
    "phi3_partition_rules",
    "Qwen2Config",
    "Qwen2ForCausalLM",
    "qwen2_partition_rules",
    "Qwen3Config",
    "Qwen3ForCausalLM",
    "qwen3_partition_rules",
    "MixtralConfig",
    "MixtralForCausalLM",
    "mixtral_partition_rules",
    "llama_partition_rules",
    "T5Config",
    "T5ForConditionalGeneration",
    "generate_encdec",
    "shift_right",
    "t5_partition_rules",
    "ViT",
    "ViTConfig",
    "vit_partition_rules",
]
