"""T5 encoder-decoder (Raffel et al. 2020), TPU-first.

The blueprint's recipes are all decoder-only or encoder-only; T5 is the
beyond-reference family that exercises the remaining generation
machinery — cross-attention with a once-computed encoder KV cache,
relative position buckets instead of absolute positions, and seq2seq
(prefix-LM-style) training. Faithful to HF ``T5ForConditionalGeneration``
semantics so the interop layer can pin logits both ways:

* **T5LayerNorm** is RMS-only (no mean subtraction, no bias), computed
  in f32.
* **No attention scaling** — T5 folds 1/sqrt(d) into its initializers,
  so QK^T logits go into softmax unscaled (``attention(scale=1.0)``).
* **Relative position bias**: one learned [num_buckets, heads] table per
  stack (owned by the stack, not block 0, so the scanned layers stay
  homogeneous — t5x's layout), bucketed log-distance, bidirectional in
  the encoder, causal-unidirectional in the decoder, broadcast to every
  layer. Cross-attention carries NO position bias (as in T5).
* **Tied embeddings**: one shared table embeds encoder input, decoder
  input, and (``tie_word_embeddings``) the LM head, with the decoder
  output scaled by ``d_model**-0.5`` before the tied projection —
  exactly HF's tying arithmetic.

Decode path: the decoder self-attention uses the same static-buffer
``decode_cache`` as GPT-2/Llama; cross-attention K/V are projected from
the encoder output ONCE (first decode call initializes them into the
flax ``cache`` collection) and reused every token — the t5x decode
layout. ``T5DecodeWrapper`` duck-types the ``model.apply`` surface
``generation.generate`` expects, so greedy/sampled/beam decoding reuse
the existing machinery unchanged (``generate_encdec`` below).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import attention, decode_cache
from pytorch_distributed_tpu.runtime.precision import current_policy


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32_128
    d_model: int = 512
    d_kv: int = 64  # per-head dim (NOT d_model // heads in general!)
    d_ff: int = 2_048
    num_layers: int = 6  # encoder layers == decoder layers (HF t5-small)
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-6
    feed_forward_proj: str = "relu"  # relu (t5) | gated-gelu (t5 v1.1)
    tie_word_embeddings: bool = True  # v1.1 unties
    pad_token_id: int = 0  # doubles as decoder_start_token_id
    eos_token_id: int = 1
    scan_layers: bool = True
    remat: bool = False
    remat_policy: str = "full"

    def __post_init__(self):
        if self.feed_forward_proj not in ("relu", "gated-gelu"):
            raise ValueError(
                f"feed_forward_proj must be 'relu' or 'gated-gelu', got "
                f"{self.feed_forward_proj!r}"
            )

    @classmethod
    def small(cls) -> "T5Config":
        return cls()

    @classmethod
    def base(cls) -> "T5Config":
        return cls(d_model=768, d_ff=3072, num_layers=12, num_heads=12)

    @classmethod
    def tiny(cls) -> "T5Config":
        return cls(
            vocab_size=512, d_model=64, d_kv=16, d_ff=128, num_layers=2,
            num_heads=4, relative_attention_num_buckets=8,
            relative_attention_max_distance=32,
        )


class T5LayerNorm(nn.Module):
    """RMS-only norm (no mean subtraction, no bias), f32 accumulation."""

    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        policy = current_policy()
        scale = self.param(
            "scale", nn.initializers.ones, (x.shape[-1],), policy.param_dtype
        )
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + self.eps) * scale).astype(x.dtype)


def relative_position_bucket(
    relative_position: jnp.ndarray,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jnp.ndarray:
    """T5's bucketed log-distance (HF ``_relative_position_bucket``,
    reimplemented from the paper's description): half the buckets are
    exact small distances, the other half log-spaced out to
    ``max_distance``; bidirectional splits the space by sign."""
    rp = relative_position
    bucket = jnp.zeros_like(rp)
    if bidirectional:
        num_buckets //= 2
        bucket = bucket + jnp.where(rp > 0, num_buckets, 0)
        rp = jnp.abs(rp)
    else:
        rp = -jnp.minimum(rp, 0)
    max_exact = num_buckets // 2
    is_small = rp < max_exact
    log_big = max_exact + (
        jnp.log(rp.astype(jnp.float32) / max_exact + 1e-9)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(rp.dtype)
    log_big = jnp.minimum(log_big, num_buckets - 1)
    return bucket + jnp.where(is_small, rp, log_big)


class RelativeBias(nn.Module):
    """Owns the per-stack [num_buckets, heads] table; returns it.

    The bias itself is computed lazily by
    :func:`relative_bias_from_table` — as a ``bias_fn(q_pos, k_pos)``
    handed to the shared attention op, so unsharded paths materialize
    it over their call's positions (exactly the old eager array) while
    ring sequence parallelism evaluates it per block from true global
    positions without anyone holding the full [S, T] bias (r5)."""

    config: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self):
        cfg = self.config
        policy = current_policy()
        return self.param(
            "embedding",
            nn.initializers.normal(stddev=1.0),
            (cfg.relative_attention_num_buckets, cfg.num_heads),
            policy.param_dtype,
        )


def relative_bias_from_table(
    table, q_positions, k_positions, *, bidirectional, num_buckets,
    max_distance,
):
    """[num_buckets, H] table + positions -> additive bias [H, S, T]."""
    rel = k_positions[None, :] - q_positions[:, None]  # [S, T]
    bucket = relative_position_bucket(
        rel, bidirectional=bidirectional, num_buckets=num_buckets,
        max_distance=max_distance,
    )
    # interop-loaded trees can carry raw numpy leaves; numpy indexing
    # with a TRACED bucket would try to concretize it
    bias = jnp.asarray(table)[bucket]  # [S, T, H]
    return jnp.transpose(bias, (2, 0, 1)).astype(jnp.float32)


def _bias_fn_from_table(cfg, table, bidirectional):
    def fn(q_pos, k_pos):
        return relative_bias_from_table(
            table, q_pos, k_pos, bidirectional=bidirectional,
            num_buckets=cfg.relative_attention_num_buckets,
            max_distance=cfg.relative_attention_max_distance,
        )

    return fn


def _dense(n, name):
    policy = current_policy()
    return nn.DenseGeneral(
        n, use_bias=False, dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype, name=name,
    )


class T5Attention(nn.Module):
    """Self- or cross-attention, T5 flavor (unscaled logits)."""

    config: T5Config
    causal: bool = False

    @nn.compact
    def __call__(
        self,
        x,
        kv_source=None,  # None = self-attention
        bias_fn=None,  # position-computed relative bias (stack-owned)
        mask=None,
        decode: bool = False,
        cache_len: Optional[int] = None,
        deterministic: bool = True,
    ):
        cfg = self.config
        H, D = cfg.num_heads, cfg.d_kv
        # HF T5 drops attention WEIGHTS (post-softmax) at dropout_rate,
        # on top of the block-level residual dropout
        drop_rate = 0.0 if deterministic else cfg.dropout_rate
        drop_rng = (
            self.make_rng("dropout") if drop_rate > 0.0 else None
        )
        q = _dense((H, D), "q")(x)
        cross = kv_source is not None
        if cross and decode:
            # encoder K/V never change during decode: project once (the
            # prefill call initializes the cache entries), reuse after
            is_init = not self.has_variable("cache", "cross_key")
            ck = self.variable(
                "cache", "cross_key", jnp.zeros,
                (x.shape[0], kv_source.shape[1], H, D), x.dtype,
            )
            cv = self.variable(
                "cache", "cross_value", jnp.zeros,
                (x.shape[0], kv_source.shape[1], H, D), x.dtype,
            )
            if is_init:
                ck.value = _dense((H, D), "k")(kv_source)
                cv.value = _dense((H, D), "v")(kv_source)
            k, v = ck.value, cv.value
            attn = attention(
                q, k, v, mask=mask, scale=1.0,
                dropout_rate=drop_rate, dropout_rng=drop_rng,
            )
        elif cross:
            k = _dense((H, D), "k")(kv_source)
            v = _dense((H, D), "v")(kv_source)
            attn = attention(
                q, k, v, mask=mask, scale=1.0,
                dropout_rate=drop_rate, dropout_rng=drop_rng,
            )
        elif decode:
            k = _dense((H, D), "k")(x)
            v = _dense((H, D), "v")(x)
            k, v, offset = decode_cache(self, k, v, cache_len)
            attn = attention(
                q, k, v, causal=self.causal, q_offset=offset, mask=mask,
                bias_fn=bias_fn, scale=1.0,
                dropout_rate=drop_rate, dropout_rng=drop_rng,
            )
        else:
            k = _dense((H, D), "k")(x)
            v = _dense((H, D), "v")(x)
            attn = attention(
                q, k, v, causal=self.causal, mask=mask, bias_fn=bias_fn,
                scale=1.0,
                dropout_rate=drop_rate, dropout_rng=drop_rng,
            )
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), use_bias=False,
            dtype=current_policy().compute_dtype,
            param_dtype=current_policy().param_dtype, name="o",
        )(attn)


class T5FFN(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        if cfg.feed_forward_proj == "gated-gelu":
            # HF's dense_act_fn here is gelu_new == tanh-approximate gelu
            h = nn.gelu(_dense(cfg.d_ff, "wi_0")(x), approximate=True)
            h = h * _dense(cfg.d_ff, "wi_1")(x)
        else:
            h = nn.relu(_dense(cfg.d_ff, "wi")(x))
        # HF DenseActDense/DenseGatedActDense: inner dropout between the
        # activation (or gate product) and wo, on top of the block-level
        # residual dropout
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return _dense(cfg.d_model, "wo")(h)


class T5EncoderBlock(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, bias_table, enc_mask, deterministic: bool):
        cfg = self.config
        drop = lambda h: nn.Dropout(cfg.dropout_rate)(  # noqa: E731
            h, deterministic=deterministic
        )
        h = T5LayerNorm(cfg.layer_norm_eps, name="attn_norm")(x)
        x = x + drop(
            T5Attention(cfg, name="attn")(
                h, bias_fn=_bias_fn_from_table(cfg, bias_table, True),
                mask=enc_mask, deterministic=deterministic,
            )
        )
        h = T5LayerNorm(cfg.layer_norm_eps, name="ffn_norm")(x)
        return x + drop(
            T5FFN(cfg, name="ffn")(h, deterministic=deterministic)
        )


class T5DecoderBlock(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(
        self, x, bias_table, enc_out, enc_mask, deterministic: bool,
        decode: bool = False, cache_len: Optional[int] = None,
    ):
        cfg = self.config
        drop = lambda h: nn.Dropout(cfg.dropout_rate)(  # noqa: E731
            h, deterministic=deterministic
        )
        h = T5LayerNorm(cfg.layer_norm_eps, name="attn_norm")(x)
        x = x + drop(
            T5Attention(cfg, causal=True, name="attn")(
                h, bias_fn=_bias_fn_from_table(cfg, bias_table, False),
                decode=decode, cache_len=cache_len,
                deterministic=deterministic,
            )
        )
        h = T5LayerNorm(cfg.layer_norm_eps, name="cross_norm")(x)
        x = x + drop(
            T5Attention(cfg, name="cross_attn")(
                h, kv_source=enc_out, mask=enc_mask, decode=decode,
                deterministic=deterministic,
            )
        )
        h = T5LayerNorm(cfg.layer_norm_eps, name="ffn_norm")(x)
        return x + drop(
            T5FFN(cfg, name="ffn")(h, deterministic=deterministic)
        )


def _stack(block_cls, cfg, name, static_argnums):
    if cfg.scan_layers:
        from pytorch_distributed_tpu.models.scan import scan_stack

        return scan_stack(
            block_cls, cfg, static_argnums=static_argnums, name=name
        )

    def apply_unrolled(x, *bcast):
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"{name}_{i}")(x, *bcast)
        return x

    return apply_unrolled


class T5Encoder(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, enc_mask, deterministic: bool):
        cfg = self.config
        table = RelativeBias(cfg, bidirectional=True, name="rel_bias")()
        x = _stack(T5EncoderBlock, cfg, "layers", static_argnums=(3,))(
            x, table, enc_mask, deterministic
        )
        x = T5LayerNorm(cfg.layer_norm_eps, name="final_norm")(x)
        return nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)


class T5Decoder(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(
        self, x, enc_out, enc_mask, deterministic: bool,
        decode: bool = False, cache_len: Optional[int] = None,
    ):
        cfg = self.config
        S = x.shape[1]
        if decode:
            from pytorch_distributed_tpu.ops.attention import (
                decode_positions,
            )

            # the counter is kept for cache-layout stability; the bias
            # positions now come from each block's decode q_offset (the
            # cache index — the same value), via bias_fn materialization
            decode_positions(self, S)
        table = RelativeBias(cfg, bidirectional=False, name="rel_bias")()
        x = _stack(
            T5DecoderBlock, cfg, "layers", static_argnums=(4, 5, 6)
        )(x, table, enc_out, enc_mask, deterministic, decode, cache_len)
        x = T5LayerNorm(cfg.layer_norm_eps, name="final_norm")(x)
        return nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)


class T5ForConditionalGeneration(nn.Module):
    """Returns [B, S_dec, vocab] logits.

    Train/eval: ``model.apply(vars, input_ids, decoder_input_ids,
    input_mask=..., train=...)``. Decode: see ``T5DecodeWrapper`` /
    ``generate_encdec`` — the encoder runs once via ``encode``.
    """

    config: T5Config

    def setup(self):
        cfg = self.config
        policy = current_policy()
        self.shared = nn.Embed(
            cfg.vocab_size, cfg.d_model, param_dtype=policy.param_dtype,
            name="shared",
        )
        self.encoder = T5Encoder(cfg, name="encoder")
        self.decoder = T5Decoder(cfg, name="decoder")
        self.dropout = nn.Dropout(cfg.dropout_rate)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(
                cfg.vocab_size, use_bias=False,
                dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype, name="lm_head",
            )

    def encode(self, input_ids, input_mask=None, train: bool = False):
        policy = current_policy()
        x = self.shared(input_ids).astype(policy.compute_dtype)
        x = self.dropout(x, deterministic=not train)
        return self.encoder(x, input_mask, not train)

    def decode(
        self,
        decoder_input_ids,
        enc_out,
        enc_mask=None,
        train: bool = False,
        decode: bool = False,
        cache_len: Optional[int] = None,
    ):
        cfg = self.config
        policy = current_policy()
        x = self.shared(decoder_input_ids).astype(policy.compute_dtype)
        x = self.dropout(x, deterministic=not train)
        x = self.decoder(
            x, enc_out, enc_mask, not train, decode, cache_len
        )
        if cfg.tie_word_embeddings:
            # HF's tying arithmetic: rescale then project through the
            # shared table (the train-time scale the init assumed)
            x = x * (cfg.d_model ** -0.5)
            logits = self.shared.attend(x.astype(policy.param_dtype))
        else:
            logits = self.lm_head(x)
        return logits.astype(policy.output_dtype)

    def __call__(
        self,
        input_ids,
        decoder_input_ids,
        *,
        input_mask=None,
        train: bool = False,
    ):
        enc_out = self.encode(input_ids, input_mask, train)
        return self.decode(
            decoder_input_ids, enc_out, input_mask, train=train
        )


class T5DecodeWrapper:
    """Duck-typed ``model.apply`` surface for ``generation.generate``.

    Closes over the encoder output (tracers are fine — construct it
    inside the caller's jit), exposes the decoder as a decoder-only LM:
    prefill initializes the self-attn cache AND the once-projected
    cross K/V; decode steps reuse both.
    """

    def __init__(self, model, enc_out, enc_mask=None):
        self.model = model
        self.enc_out = enc_out
        self.enc_mask = enc_mask

    @property
    def config(self):
        return None  # no absolute-position cap (relative buckets)

    def apply(self, variables, ids, *, decode=False, cache_len=None,
              mutable=(), **unexpected):
        if unexpected:
            # generate's ragged-prompt path (prompt_mask) hands the model
            # kv_mask/positions; silently dropping them would decode with
            # pad cache slots attended — T5 decoding always starts from
            # the 1-token start prompt, so refuse rather than mis-decode
            raise NotImplementedError(
                f"T5DecodeWrapper does not support {sorted(unexpected)} "
                "(ragged prompt_mask decoding is a decoder-only-LM "
                "feature; seq2seq raggedness lives in the encoder "
                "input_mask)"
            )
        return self.model.apply(
            variables, ids, self.enc_out, self.enc_mask,
            False, decode, cache_len,
            method=self.model.decode, mutable=mutable,
        )


def shift_right(labels: jnp.ndarray, start_id: int = 0) -> jnp.ndarray:
    """Teacher-forcing decoder input: [start, y0, y1, ...] (HF
    ``_shift_right``)."""
    return jnp.concatenate(
        [jnp.full_like(labels[:, :1], start_id), labels[:, :-1]], axis=1
    )


def generate_encdec(
    model: T5ForConditionalGeneration,
    params,
    input_ids: jnp.ndarray,
    *,
    max_new_tokens: int,
    input_mask: Optional[jnp.ndarray] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
) -> jnp.ndarray:
    """Seq2seq generation: encode once, decode autoregressively.

    Returns [B, max_new_tokens] (the decoder start token is stripped,
    matching HF ``generate`` output minus the leading pad). ``eos_id``
    defaults to the config's ``eos_token_id``; pass ``eos_id=-1`` to
    disable stopping.
    """
    from pytorch_distributed_tpu.generation import generate

    cfg = model.config
    if eos_id is None:
        eos_id = cfg.eos_token_id
    elif eos_id == -1:
        eos_id = None
    enc_out = model.apply(
        {"params": params}, input_ids, input_mask, False,
        method=model.encode,
    )
    dec = T5DecodeWrapper(model, enc_out, input_mask)
    start = jnp.full(
        (input_ids.shape[0], 1), cfg.pad_token_id, jnp.int32
    )
    out = generate(
        dec, params, start, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
        eos_id=eos_id, pad_id=cfg.pad_token_id,
    )
    return out[:, 1:]


def t5_partition_rules():
    """Megatron TP for both stacks: column-parallel q/k/v/wi, row-parallel
    o/wo; the shared embedding sharded on the model dim."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel.sharding import stacked

    return [
        (r"/(q|k|v)/kernel", stacked(P(None, "tp", None))),
        (r"/o/kernel", stacked(P("tp", None, None))),
        (r"/(wi|wi_0|wi_1)/kernel", stacked(P(None, "tp"))),
        (r"/wo/kernel", stacked(P("tp", None))),
        (r"shared/embedding", P(None, "tp")),
        (r"rel_bias/embedding", P(None, "tp")),
    ]
