"""Gemma — Llama body with Google's four deviations, beyond-reference.

Gemma (Mesnard et al. 2024) is the Llama decoder with: a zero-centered
RMSNorm scale applied as ``(1 + scale)``, a tanh-approximate-gelu gate
in the MLP (GeGLU), embeddings multiplied by ``sqrt(hidden)`` after
lookup, an explicit per-head dim decoupled from ``hidden/heads`` (256),
and an always-tied LM head. Every one of those is a config flag on the
shared Llama machinery (``rms_offset``, ``hidden_act``,
``scale_embedding``, ``override_head_dim``, ``tie_word_embeddings``),
so this module is pure configuration; the HF state_dict layout is
Llama's, and ``interop.load_gemma_weights`` is the Llama mapping (tied:
no lm_head leaf is produced).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pytorch_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_partition_rules,
)

def gemma_partition_rules(config=None, num_kv_heads=None):
    """Llama TP rules with the kv-head count taken from the CONFIG.

    The old loose ``num_kv_heads=1`` int default silently replicated
    gemma_7b's 16 kv heads (a throughput footgun); now the rules derive
    the decision from the config when given — ``GemmaConfig.gemma_2b``
    (MQA) replicates k/v, ``gemma_7b`` shards them — and with NO
    arguments defer to the shape/mesh-aware llama rules, which read the
    kv-head axis off the kernel itself at placement time (so even the
    bare call places both variants correctly). ``num_kv_heads`` stays
    for back-compat callers."""
    if isinstance(config, int):
        # the pre-r6 signature was gemma_partition_rules(num_kv_heads=1)
        # — a positional int caller still means the kv-head count
        config, num_kv_heads = None, config
    if config is not None and num_kv_heads is not None:
        raise ValueError("pass config or num_kv_heads, not both")
    if config is not None:
        num_kv_heads = config.num_kv_heads
    return llama_partition_rules(num_kv_heads=num_kv_heads)


@dataclasses.dataclass(frozen=True)
class GemmaConfig(LlamaConfig):
    # Gemma-2B geometry (the MQA variant: 1 kv head)
    vocab_size: int = 256_000
    hidden_size: int = 2_048
    num_layers: int = 18
    num_heads: int = 8
    num_kv_heads: int = 1
    intermediate_size: int = 16_384
    max_seq_len: int = 8_192
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-6
    override_head_dim: Optional[int] = 256
    rms_offset: bool = True
    hidden_act: str = "gelu"
    scale_embedding: bool = True
    tie_word_embeddings: bool = True

    @classmethod
    def gemma_2b(cls) -> "GemmaConfig":
        return cls()

    @classmethod
    def gemma_7b(cls) -> "GemmaConfig":
        return cls(
            hidden_size=3_072, num_layers=28, num_heads=16,
            num_kv_heads=16, intermediate_size=24_576,
        )

    @classmethod
    def tiny(cls) -> "GemmaConfig":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=1, intermediate_size=128, max_seq_len=128,
            override_head_dim=16,
        )


class GemmaForCausalLM(LlamaForCausalLM):
    """Llama machinery end to end; the config flags do the work."""

    config: GemmaConfig
