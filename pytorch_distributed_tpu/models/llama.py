"""Llama-3 — recipe 5, the stretch goal (BASELINE.json:11:
"Llama-3-8B, FSDP full-shard -> XLA SPMD").

Decoder with RMSNorm, rotary positions (theta 500k), grouped-query
attention (32 q / 8 kv heads at 8B) and SwiGLU MLP. Sequence length is an
explicit axis everywhere so the sequence-parallel strategies
(parallel/sequence.py) can shard it; ``positions`` plumb through to RoPE
for mid-sequence shards.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import (
    apply_rope,
    attention,
    rope_frequencies,
    validate_write_pos,
)
from pytorch_distributed_tpu.runtime.precision import current_policy


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Context-window extension for RoPE (ops/attention.py
    ``rope_frequencies``). ``type``: "linear" (position interpolation)
    or "llama3" (HF Llama-3.1 frequency-dependent scheme). Frozen so
    configs stay hashable."""

    type: str = "llama3"
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8_192


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    hidden_size: int = 4_096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14_336
    max_seq_len: int = 8_192
    rope_theta: float = 500_000.0
    rms_eps: float = 1e-5
    # sliding-window (Mistral) attention: position i sees keys in
    # (i - window, i] only; None = full causal (Llama)
    sliding_window: Optional[int] = None
    # context-window extension (Llama-3.1 long context): None = plain RoPE
    rope_scaling: Optional[RopeScaling] = None
    # biases on the q/k/v projections (Qwen2); o/gate/up/down never
    # carry biases in any Llama-body family
    attention_bias: bool = False
    # share the embedding table with the LM head (Llama-3.2-1B/3B,
    # Qwen2-0.5B/1.5B, Gemma); False = the untied Llama-3 layout
    tie_word_embeddings: bool = False
    # Gemma-isms, all defaulting to the Llama behavior:
    # explicit per-head dim (Gemma: 256, decoupled from hidden/heads)
    override_head_dim: Optional[int] = None
    # RMSNorm multiplies by (1 + scale) — zero-centered scale init
    rms_offset: bool = False
    # FFN gate activation: silu (Llama/Mistral/Qwen) | gelu (Gemma's
    # tanh-approximate gelu_pytorch_tanh)
    hidden_act: str = "silu"
    # multiply embeddings by sqrt(hidden_size) after lookup
    scale_embedding: bool = False
    # "int8" stores the decode KV cache quantized (~2x less HBM than a
    # bf16 cache, ~4x than f32 — the long-context serving ceiling);
    # None = exact bf16/f32.
    # Lossy: greedy decode agrees with the exact cache on most tokens
    # but is not bitwise identical.
    kv_cache_quantize: Optional[str] = None
    # per-head RMSNorm on q and k before RoPE (Qwen3 / OLMo-2 /
    # Gemma-3 idiom) — stabilizes attention logits at scale
    qk_norm: bool = False
    # scan over layers (models/scan.py): one compiled block, [L, ...]
    # stacked params. False restores the unrolled per-layer tree.
    scan_layers: bool = True
    remat: bool = False  # recompute block activations in backward
    scan_dequant: bool = False  # per-layer dequant of quantized block params
    # inside the scan (models/scan.py) — the single-chip big-model serving path

    remat_policy: str = "full"  # full | dots | dots_no_batch (models/scan.py)

    def __post_init__(self):
        if self.scan_dequant and not self.scan_layers:
            raise ValueError(
                "scan_dequant dequantizes inside the layer scan — it "
                "requires scan_layers=True (an unrolled stack would hand "
                "raw quantized dicts to the blocks)"
            )
        if self.hidden_act not in ("silu", "gelu"):
            raise ValueError(
                f"hidden_act must be 'silu' or 'gelu', got "
                f"{self.hidden_act!r}"
            )
        if self.kv_cache_quantize not in (None, "int8"):
            raise ValueError(
                f"kv_cache_quantize must be None or 'int8', got "
                f"{self.kv_cache_quantize!r}"
            )

    @property
    def head_dim(self) -> int:
        if self.override_head_dim is not None:
            return self.override_head_dim
        return self.hidden_size // self.num_heads

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def llama3_1_8b(cls) -> "LlamaConfig":
        """Llama-3.1-8B: the 3.0 geometry + llama3 rope scaling to 128k.
        Serve long contexts with an explicit ``cache_len`` — a
        max_seq_len-sized KV cache is ~16 GB at 128k."""
        return cls(
            max_seq_len=131_072,
            rope_scaling=RopeScaling(
                type="llama3", factor=8.0, low_freq_factor=1.0,
                high_freq_factor=4.0,
                original_max_position_embeddings=8_192,
            ),
        )

    @classmethod
    def llama3_2_1b(cls) -> "LlamaConfig":
        """Llama-3.2-1B: tied embeddings + factor-32 llama3 scaling."""
        return cls(
            hidden_size=2_048, num_layers=16, num_heads=32,
            num_kv_heads=8, intermediate_size=8_192,
            max_seq_len=131_072, tie_word_embeddings=True,
            rope_scaling=RopeScaling(
                type="llama3", factor=32.0, low_freq_factor=1.0,
                high_freq_factor=4.0,
                original_max_position_embeddings=8_192,
            ),
        )

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=128, max_seq_len=128,
        )


class RMSNorm(nn.Module):
    eps: float = 1e-5
    # Gemma stores a ZERO-centered scale and multiplies by (1 + scale);
    # init stays zeros so a fresh tied-Gemma init is the identity norm
    offset: bool = False

    @nn.compact
    def __call__(self, x):
        policy = current_policy()
        scale = self.param(
            "scale",
            nn.initializers.zeros if self.offset else nn.initializers.ones,
            (x.shape[-1],), policy.param_dtype,
        )
        x32 = x.astype(jnp.float32)
        rms = jnp.sqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + self.eps)
        mult = scale.astype(jnp.float32)
        if self.offset:
            mult = 1.0 + mult
        return (x32 / rms * mult).astype(x.dtype)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions, segment_ids, kv_mask,
                 write_pos, deterministic: bool, decode: bool = False,
                 cache_len: Optional[int] = None):
        cfg = self.config
        policy = current_policy()
        dense = lambda feats, name, axis=-1, use_bias=False: (  # noqa: E731
            nn.DenseGeneral(
                feats, axis=axis, use_bias=use_bias,
                dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype, name=name,
            )
        )
        h = RMSNorm(cfg.rms_eps, cfg.rms_offset, name="attn_norm")(x)
        ab = cfg.attention_bias
        q = dense((cfg.num_heads, cfg.head_dim), "q", use_bias=ab)(h)
        k = dense((cfg.num_kv_heads, cfg.head_dim), "k", use_bias=ab)(h)
        v = dense((cfg.num_kv_heads, cfg.head_dim), "v", use_bias=ab)(h)
        if cfg.qk_norm:
            # per-head RMSNorm over head_dim, BEFORE rotary (Qwen3's
            # q_norm/k_norm: one [head_dim] scale shared across heads)
            q = RMSNorm(cfg.rms_eps, cfg.rms_offset, name="q_norm")(q)
            k = RMSNorm(cfg.rms_eps, cfg.rms_offset, name="k_norm")(k)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        if decode:
            from pytorch_distributed_tpu.ops.attention import decode_cache

            k, v, offset = decode_cache(
                self, k, v, cache_len or cfg.max_seq_len,
                quantize=cfg.kv_cache_quantize, write_pos=write_pos,
            )
            attn = attention(
                q, k, v, causal=True, q_offset=offset, mask=kv_mask,
                window=cfg.sliding_window,
            )
        else:
            attn = attention(
                q, k, v, causal=True, segment_ids=segment_ids,
                window=cfg.sliding_window,
            )
        attn = dense(cfg.hidden_size, "o", axis=(-2, -1))(attn)
        x = x + attn

        h = RMSNorm(cfg.rms_eps, cfg.rms_offset, name="mlp_norm")(x)
        return x + self._ffn(h, dense)

    def _ffn(self, h, dense):
        """Gated MLP — the one piece variant decoders override (the
        Mixtral family swaps in a sparse-MoE expert layer). The gate
        activation is silu (Llama/Mistral/Qwen) or Gemma's
        tanh-approximate gelu per ``cfg.hidden_act``."""
        cfg = self.config
        if cfg.hidden_act == "silu":  # validated at config construction
            act = nn.silu
        else:  # "gelu": Gemma's tanh-approximate gate
            act = lambda a: nn.gelu(a, approximate=True)  # noqa: E731
        gate = dense(cfg.intermediate_size, "gate")(h)
        up = dense(cfg.intermediate_size, "up")(h)
        return dense(cfg.hidden_size, "down")(act(gate) * up)


class LlamaForCausalLM(nn.Module):
    """Returns [B, S, vocab] logits. Untied LM head (Llama-3 layout)."""

    config: LlamaConfig
    # subclasses (models/mixtral.py) swap the block while inheriting the
    # embed/RoPE/scan/decode/LM-head machinery unchanged
    block_cls = LlamaBlock

    @nn.compact
    def __call__(
        self,
        input_ids,
        positions: Optional[jnp.ndarray] = None,
        *,
        segment_ids: Optional[jnp.ndarray] = None,
        kv_mask: Optional[jnp.ndarray] = None,
        write_pos: Optional[jnp.ndarray] = None,
        train: bool = False,
        decode: bool = False,
        cache_len: Optional[int] = None,
        return_hidden: bool = False,
    ):
        cfg = self.config
        policy = current_policy()
        B, S = input_ids.shape
        if cache_len is not None and cache_len > cfg.max_seq_len:
            raise ValueError(
                f"cache_len {cache_len} > max_seq_len {cfg.max_seq_len}"
            )
        validate_write_pos(write_pos, decode, positions)
        embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, param_dtype=policy.param_dtype,
            dtype=policy.compute_dtype, name="embed",
        )
        x = embed(input_ids)  # dtype= already yields compute_dtype
        if cfg.scale_embedding:  # Gemma: sqrt(hidden) after lookup
            x = x * jnp.asarray(
                cfg.hidden_size ** 0.5, policy.compute_dtype
            )
        # size the tables to what this program can actually index — at
        # 128k max_seq_len (llama3_1_8b) the full table is ~67 MB of
        # constants that an S=8k step would bake in for nothing
        if decode:
            table_len = cache_len or cfg.max_seq_len
        elif positions is None:
            table_len = S
        else:
            # explicit positions (sequence-parallel shards, packed
            # batches) may index anywhere in the configured window
            table_len = cfg.max_seq_len
        cos, sin = rope_frequencies(
            cfg.head_dim, table_len, cfg.rope_theta,
            scaling=cfg.rope_scaling,
        )
        if decode:
            from pytorch_distributed_tpu.ops.attention import decode_positions

            # rotary positions continue from the decode offset; the
            # counter advances EVEN with explicit positions, so a
            # padded-prefill caller's later positions=None steps stay in
            # sync with the KV cache_index
            auto = jnp.broadcast_to(
                decode_positions(self, S)[None, :], (B, S)
            )
            if positions is None:
                positions = auto
        if segment_ids is not None and decode:
            raise ValueError(
                "segment_ids (packed training) and decode (KV cache) are "
                "mutually exclusive"
            )
        if kv_mask is not None and not decode:
            raise ValueError(
                "kv_mask is for KV-cache decode (left-padded prompts); "
                "training masks go through the loss/segment machinery"
            )
        block_cls = type(self).block_cls
        if cfg.scan_layers:
            from pytorch_distributed_tpu.models.scan import scan_stack

            x = scan_stack(
                block_cls, cfg, static_argnums=(7, 8, 9), name="layers"
            )(x, cos, sin, positions, segment_ids, kv_mask, write_pos,
              not train, decode, cache_len)
        else:
            for i in range(cfg.num_layers):
                x = block_cls(cfg, name=f"layer{i}")(
                    x, cos, sin, positions, segment_ids, kv_mask,
                    write_pos, deterministic=not train,
                    decode=decode, cache_len=cache_len,
                )
        x = RMSNorm(cfg.rms_eps, cfg.rms_offset, name="final_norm")(x)
        if return_hidden:
            # [B, S, D] for the chunked-vocab loss (ops/lm_loss.py); the
            # projection is params['lm_head']['kernel'] ([D, V]) untied,
            # or params['embed']['embedding'] ([V, D]) tied — the loss's
            # _lm_projection_weight resolves both
            return x.astype(policy.output_dtype)
        if cfg.tie_word_embeddings:
            logits = embed.attend(x)  # x is already compute_dtype
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype, name="lm_head",
            )(x)
        return logits.astype(policy.output_dtype)


def llama_partition_rules(num_kv_heads: Optional[int] = None):
    """Megatron TP: column-parallel q/k/v/gate/up, row-parallel o/down;
    embedding sharded on hidden, lm_head kernel on vocab (its dim 1).

    A thin declarative table over the shape-aware rule engine
    (autoplan/rules.py), which supplies the behavior this function used
    to hand-roll: any dim that does not divide its mesh axes replicates
    with a once-per-shape warning — decided from the KERNEL'S OWN SHAPE
    at placement time, so MQA (Gemma-2B's 1 kv head) and ragged GQA
    (Qwen2-7B's 4 kv heads on tp=8) both replicate k/v (the smallest
    projections; q/o and the MLP still shard) instead of crashing on an
    unshardable axis, and the scan-stacked leading layer dim is
    tolerated everywhere.

    ``num_kv_heads`` is retained for back-compat: an explicit ``1``
    forces the MQA replicate form without consulting shapes; other
    values defer to the shape-based decision."""
    from pytorch_distributed_tpu.autoplan.rules import (
        TensorRule,
        engine_rules,
        replicated_rule,
    )

    kv_note = "q/o and the MLP still shard"
    kv = (
        replicated_rule(r"/(k|v)/kernel", 3)
        if num_kv_heads == 1  # forced MQA form, shapes not consulted
        else TensorRule(r"/(k|v)/kernel", (None, "tp", None), note=kv_note)
    )
    return engine_rules([
        TensorRule(r"/q/kernel", (None, "tp", None)),
        kv,
        TensorRule(r"/o/kernel", ("tp", None, None)),
        TensorRule(r"/(gate|up)/kernel", (None, "tp")),
        TensorRule(r"/down/kernel", ("tp", None)),
        TensorRule(r"embed/embedding", (None, "tp"), stacked=False),
        TensorRule(r"lm_head/kernel", (None, "tp"), stacked=False),
    ])
