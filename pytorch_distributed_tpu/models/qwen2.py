"""Qwen2/Qwen2.5 — Llama body + QKV projection biases, beyond-reference.

Architecturally Qwen2 is the Llama decoder (RMSNorm, RoPE, GQA, SwiGLU)
with biases on the q/k/v projections only (``attention_bias=True`` on
the shared config; o/gate/up/down stay bias-free) and its own
vocab/theta. The block, scan, decode, and sharding machinery are
Llama's; ``interop.load_qwen2_weights`` is the Llama-body mapping with
the bias terms carried through.
"""

from __future__ import annotations

import dataclasses

from pytorch_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_partition_rules,
)

qwen2_partition_rules = llama_partition_rules


@dataclasses.dataclass(frozen=True)
class Qwen2Config(LlamaConfig):
    # Qwen2-7B geometry
    vocab_size: int = 152_064
    hidden_size: int = 3_584
    num_layers: int = 28
    num_heads: int = 28
    num_kv_heads: int = 4
    intermediate_size: int = 18_944
    max_seq_len: int = 32_768
    rope_theta: float = 1_000_000.0
    attention_bias: bool = True

    @classmethod
    def qwen2_7b(cls) -> "Qwen2Config":
        return cls()

    @classmethod
    def tiny(cls) -> "Qwen2Config":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=128, max_seq_len=128,
        )


class Qwen2ForCausalLM(LlamaForCausalLM):
    """Llama machinery end to end; the config's biases do the work."""

    config: Qwen2Config
