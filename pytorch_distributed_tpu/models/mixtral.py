"""Mixtral — sparse-MoE decoder LM (Jiang et al. 2024), beyond-reference.

Not in the blueprint (SURVEY.md §2: DDP/ZeRO-1/FSDP recipes only); built
as the model family that exercises expert parallelism end-to-end: a
Llama-3 body (RMSNorm, RoPE, GQA — inherited wholesale from
``models/llama.py``) whose FFN is the expert-parallel ``ops.moe.MoEMLP``
with Mixtral's per-expert SwiGLU (``w2(silu(w1 x) * w3 x)``) and top-2
renormalized routing. Faithful to HF ``MixtralForCausalLM`` semantics so
interop can pin logits (``interop.load_mixtral_weights``):

* router logits and gating in f32, selected gates renormalized to sum 1
  (HF ``norm_topk_prob`` behavior);
* ``capacity_factor=None`` (the default) makes dispatch DROP-FREE —
  HF computes every selected expert exactly, so parity requires no
  capacity dropping. Training recipes can set a finite factor for the
  Switch-style bounded-compute dispatch; the Switch load-balance aux
  loss is sown per layer either way
  (``train.causal_lm_loss_fn(moe_aux_weight=...)`` collects it through
  the scan).

Everything else — scan-over-layers, KV-cache decode (``ptd.generate``
works unchanged), remat, chunked-vocab loss via ``return_hidden``,
FSDP/TP sharding — is inherited from the Llama machinery through the
``block_cls`` hook; the only new sharding surface is the expert axis
(``mixtral_partition_rules``: experts over ``ep``, expert-FFN hidden
over ``tp``, composing with the attention TP rules).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pytorch_distributed_tpu.models.llama import (
    LlamaBlock,
    LlamaConfig,
    LlamaForCausalLM,
    llama_partition_rules,
)
from pytorch_distributed_tpu.ops.moe import MoEMLP


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    # Mixtral-8x7B geometry (vocab/theta differ from Llama-3)
    vocab_size: int = 32_000
    hidden_size: int = 4_096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14_336
    max_seq_len: int = 32_768
    rope_theta: float = 1_000_000.0
    num_experts: int = 8
    top_k: int = 2
    # None = drop-free dispatch (HF-exact, serving); finite = Switch
    # bounded-capacity dispatch for training throughput
    capacity_factor: Optional[float] = None

    @classmethod
    def mixtral_8x7b(cls) -> "MixtralConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "MixtralConfig":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=96, max_seq_len=128,
            num_experts=4, top_k=2,
        )


class MixtralBlock(LlamaBlock):
    """Llama block with the dense SwiGLU MLP swapped for sparse MoE."""

    config: MixtralConfig

    def _ffn(self, h, dense):
        cfg = self.config
        return MoEMLP(
            num_experts=cfg.num_experts,
            d_ff=cfg.intermediate_size,
            k=cfg.top_k,
            capacity_factor=cfg.capacity_factor,
            activation="swiglu",
            name="moe",
        )(h)


class MixtralForCausalLM(LlamaForCausalLM):
    """Returns [B, S, vocab] logits; ``ptd.generate`` works unchanged.

    Training with the load-balance aux loss:
    ``train.causal_lm_loss_fn(model, moe_aux_weight=0.01)`` — the loss
    machinery already opens the ``intermediates`` collection and sums
    the per-layer sown terms (``ops.moe.collect_aux_loss``).
    """

    config: MixtralConfig
    block_cls = MixtralBlock


def mixtral_partition_rules(ep_axis: str = "ep", tp_axis: str = "tp"):
    """Attention/embed/head rules from Llama + the expert tensors from
    ``ops.moe.moe_partition_rules`` (experts over ``ep``, each expert's
    FFN hidden over ``tp``, router replicated) — derived, not re-listed,
    so a new MoE param cannot be sharded in one place and missed in the
    other; ``stacked()`` prepends the scan-layer axis."""
    from pytorch_distributed_tpu.ops.moe import moe_partition_rules
    from pytorch_distributed_tpu.parallel.sharding import stacked

    return llama_partition_rules() + [
        (rf"/moe/{name}", stacked(spec))
        for name, spec in moe_partition_rules(ep_axis, tp_axis)
    ]
