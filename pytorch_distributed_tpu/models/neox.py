"""GPT-NeoX / Pythia — parallel-residual decoder, beyond-reference.

The Pythia suite (Biderman et al. 2023) is the research ecosystem's
standard scaling ladder; its GPT-NeoX architecture differs from both
GPT-2 and the Llama bodies, so it is a real third decoder block rather
than a config variant:

* **parallel residual**: ``x + attn(ln1(x)) + mlp(ln2(x))`` — attention
  and MLP read the SAME input and their outputs add (one residual
  junction per layer instead of two); ``use_parallel_residual=False``
  restores the sequential form (used by the smallest NeoX models);
* **partial rotary**: only the first ``rotary_pct`` of each head's dims
  rotate, the tail passes through position-free;
* **fused QKV in HF's per-head layout**: ``query_key_value`` packs
  [head, (q,k,v), head_dim] along its output axis — the DenseGeneral
  features ``(H, 3, hd)`` mirror it so interop is a reshape;
* LayerNorm (with bias) everywhere, exact (erf) gelu MLP, untied
  ``embed_out``.

Decode, scan-over-layers, remat, and sharding ride the same shared
machinery as every other family (``ops.attention``, ``models.scan``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.ops.attention import (
    apply_rope,
    attention,
    rope_frequencies,
    validate_write_pos,
)
from pytorch_distributed_tpu.runtime.precision import current_policy


@dataclasses.dataclass(frozen=True)
class NeoXConfig:
    vocab_size: int = 50_304
    hidden_size: int = 2_048
    num_layers: int = 16
    num_heads: int = 8
    intermediate_size: int = 8_192
    max_seq_len: int = 2_048
    rope_theta: float = 10_000.0
    rotary_pct: float = 0.25
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    scan_layers: bool = True
    remat: bool = False
    remat_policy: str = "full"

    def __post_init__(self):
        rot = int(self.head_dim * self.rotary_pct)
        if rot < 2 or rot % 2:
            raise ValueError(
                f"rotary_pct {self.rotary_pct} gives rotary dim {rot} "
                f"of head_dim {self.head_dim}; need an even dim >= 2"
            )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dim(self) -> int:
        return int(self.head_dim * self.rotary_pct)

    @classmethod
    def pythia_1b(cls) -> "NeoXConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "NeoXConfig":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_seq_len=128, rotary_pct=0.5,
        )


def _partial_rope(x, cos, sin, positions):
    """Rotate the first ``rot`` dims (the tables' width), pass the rest."""
    rot = cos.shape[-1] * 2
    if rot == x.shape[-1]:
        return apply_rope(x, cos, sin, positions)
    rotated = apply_rope(x[..., :rot], cos, sin, positions)
    return jnp.concatenate([rotated, x[..., rot:]], axis=-1)


class NeoXBlock(nn.Module):
    config: NeoXConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions, segment_ids, kv_mask,
                 write_pos, deterministic: bool, decode: bool = False,
                 cache_len: Optional[int] = None):
        cfg = self.config
        policy = current_policy()
        H, hd = cfg.num_heads, cfg.head_dim
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name=name,
        )
        h_attn = ln("ln1")(x)
        qkv = nn.DenseGeneral(
            (H, 3, hd), use_bias=True, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="qkv",
        )(h_attn)  # HF per-head (q, k, v) packing
        q, k, v = (qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :])
        q = _partial_rope(q, cos, sin, positions)
        k = _partial_rope(k, cos, sin, positions)
        if decode:
            from pytorch_distributed_tpu.ops.attention import decode_cache

            k, v, offset = decode_cache(
                self, k, v, cache_len or cfg.max_seq_len,
                write_pos=write_pos,
            )
            attn = attention(
                q, k, v, causal=True, q_offset=offset, mask=kv_mask
            )
        else:
            attn = attention(
                q, k, v, causal=True, segment_ids=segment_ids
            )
        attn = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), use_bias=True,
            dtype=policy.compute_dtype, param_dtype=policy.param_dtype,
            name="attn_out",
        )(attn)

        def mlp(h):
            h = nn.Dense(
                cfg.intermediate_size, use_bias=True,
                dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype, name="mlp_up",
            )(h)
            h = nn.gelu(h, approximate=False)  # HF NeoX: exact gelu
            return nn.Dense(
                cfg.hidden_size, use_bias=True,
                dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype, name="mlp_down",
            )(h)

        if cfg.use_parallel_residual:
            # attention and MLP both read x (through their own norms);
            # ONE residual junction: x + attn + mlp
            return x + attn + mlp(ln("ln2")(x))
        x = x + attn
        return x + mlp(ln("ln2")(x))


class NeoXForCausalLM(nn.Module):
    """Returns [B, S, vocab] logits; untied ``embed_out`` (Pythia)."""

    config: NeoXConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        positions: Optional[jnp.ndarray] = None,
        *,
        segment_ids: Optional[jnp.ndarray] = None,
        kv_mask: Optional[jnp.ndarray] = None,
        write_pos: Optional[jnp.ndarray] = None,
        train: bool = False,
        decode: bool = False,
        cache_len: Optional[int] = None,
    ):
        cfg = self.config
        policy = current_policy()
        B, S = input_ids.shape
        if cache_len is not None and cache_len > cfg.max_seq_len:
            raise ValueError(
                f"cache_len {cache_len} > max_seq_len {cfg.max_seq_len}"
            )
        validate_write_pos(write_pos, decode, positions)
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden_size,
            param_dtype=policy.param_dtype, dtype=policy.compute_dtype,
            name="embed",
        )(input_ids)
        if decode:
            from pytorch_distributed_tpu.ops.attention import (
                decode_positions,
            )

            auto = jnp.broadcast_to(
                decode_positions(self, S)[None, :], (B, S)
            )
            if positions is None:
                positions = auto
        if segment_ids is not None and decode:
            raise ValueError(
                "segment_ids (packed training) and decode (KV cache) are "
                "mutually exclusive"
            )
        if kv_mask is not None and not decode:
            raise ValueError(
                "kv_mask is for KV-cache decode (left-padded prompts); "
                "training masks go through the loss/segment machinery"
            )
        if decode:
            table_len = cache_len or cfg.max_seq_len
        elif positions is None:
            table_len = S
        else:
            table_len = cfg.max_seq_len
        cos, sin = rope_frequencies(
            cfg.rotary_dim, table_len, cfg.rope_theta
        )
        if cfg.scan_layers:
            from pytorch_distributed_tpu.models.scan import scan_stack

            x = scan_stack(
                NeoXBlock, cfg, static_argnums=(7, 8, 9), name="layers"
            )(x, cos, sin, positions, segment_ids, kv_mask, write_pos,
              not train, decode, cache_len)
        else:
            for i in range(cfg.num_layers):
                x = NeoXBlock(cfg, name=f"layer{i}")(
                    x, cos, sin, positions, segment_ids, kv_mask,
                    write_pos, deterministic=not train,
                    decode=decode, cache_len=cache_len,
                )
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="final_norm",
        )(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="embed_out",
        )(x)
        return logits.astype(policy.output_dtype)


def neox_partition_rules():
    """Megatron TP: the fused qkv shards on its head axis, attn_out on
    the same axis (its input side), the MLP on its hidden dim."""
    from pytorch_distributed_tpu.parallel.sharding import stacked

    return [
        (r"/qkv/kernel", stacked(P(None, "tp", None, None))),
        (r"/qkv/bias", stacked(P("tp", None, None))),
        (r"/attn_out/kernel", stacked(P("tp", None, None))),
        (r"/mlp_up/kernel", stacked(P(None, "tp"))),
        (r"/mlp_up/bias", stacked(P("tp"))),
        (r"/mlp_down/kernel", stacked(P("tp", None))),
        (r"embed/embedding", P(None, "tp")),
        (r"embed_out/kernel", P(None, "tp")),
    ]
