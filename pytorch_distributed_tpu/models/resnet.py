"""ResNet-18/50 — recipes 1 and 2 of the reference matrix
(BASELINE.json:7-8: ResNet-18/CIFAR-10 smoke test, ResNet-50/ImageNet DDP).

TPU-first choices:

* NHWC layout (XLA's native conv layout on TPU — NCHW would transpose on
  every conv) and bf16 compute / f32 params via the precision policy.
* BatchNorm statistics are computed over the *global* (sharded) batch:
  under jit the batch-axis mean lowers to a psum over the data axes, i.e.
  SyncBN semantics. The reference's DDP runs per-GPU local BN; global
  stats are the SPMD-natural equivalent and match or beat its accuracy.
* CIFAR stem (3x3, no maxpool) vs ImageNet stem (7x7/2 + maxpool) selected
  by ``stem``.
* ``stem="s2d"`` — the MLPerf-style space-to-depth stem: the 7x7/2 conv
  on 3-channel input keeps only 3 of the (padded) minor-dim lanes busy on
  the MXU; rearranging 2x2 pixel blocks into channels first
  ([N,224,224,3] -> [N,112,112,12]) and convolving 4x4/1 over 12 channels
  computes a function space that CONTAINS the original conv (pad the 7x7
  kernel to 8 taps with one zero row/col and reshuffle — see
  ``s2d_stem_kernel_from_conv7``) with 4x the lane utilization.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_tpu.runtime.precision import current_policy

ModuleDef = Any


def space_to_depth(x, block: int):
    """[N, H, W, C] -> [N, H/b, W/b, b*b*C]; channel index = (di*b+dj)*C+c."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    #                 i     di      j      dj      c  ->  i j (di dj c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def s2d_stem_kernel_from_conv7(k7):
    """Rewrite a [7,7,C,F] stride-2 conv kernel as the exactly-equivalent
    [4,4,4*C,F] kernel over space_to_depth(x, 2) input.

    Original tap offset u in [-3,3] maps to (du, di) with u = 2*du + di - 4
    (du in [0,4), di in {0,1}); the u=-4 tap is identically zero. Proof of
    equivalence is the unit test ``test_s2d_stem_exactly_matches_conv7``.
    """
    import numpy as np

    k7 = np.asarray(k7)
    c, f = k7.shape[2], k7.shape[3]
    out = np.zeros((4, 4, 4 * c, f), k7.dtype)
    for u in range(-3, 4):
        du, di = (u + 4) // 2, (u + 4) % 2
        for v in range(-3, 4):
            dv, dj = (v + 4) // 2, (v + 4) % 2
            out[du, dv, (di * 2 + dj) * c:(di * 2 + dj + 1) * c, :] = k7[
                u + 3, v + 3
            ]
    return out


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="proj")(
                residual
            )
            residual = self.norm(name="proj_bn")(residual)
        return self.act(residual + y)


class Bottleneck(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale so each block starts as identity —
        # standard trick for large-batch ResNet training
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="proj")(
                residual
            )
            residual = self.norm(name="proj_bn")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    width: int = 64
    stem: str = "imagenet"  # or "cifar"
    dtype: Optional[Any] = None  # default: precision policy compute dtype
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = True):
        policy = current_policy()
        dtype = self.dtype or policy.compute_dtype
        conv = functools.partial(
            nn.Conv,
            use_bias=False,
            dtype=dtype,
            param_dtype=policy.param_dtype,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=1e-5,
            dtype=dtype,
            param_dtype=policy.param_dtype,
        )
        act = nn.relu

        x = x.astype(dtype)
        if self.stem == "imagenet":
            x = conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="stem")(x)
            x = norm(name="stem_bn")(x)
            x = act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        elif self.stem == "s2d":
            x = space_to_depth(x, 2)
            # 4x4/1 over the 2x-downsampled grid == 8-tap/2 over pixels;
            # pad (2,1) puts the zero eighth tap at original offset -4
            x = conv(
                self.width, (4, 4), (1, 1), padding=[(2, 1), (2, 1)],
                name="stem",
            )(x)
            x = norm(name="stem_bn")(x)
            x = act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        elif self.stem == "cifar":
            x = conv(self.width, (3, 3), name="stem")(x)
            x = norm(name="stem_bn")(x)
            x = act(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")

        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.width * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            self.num_classes,
            dtype=dtype,
            param_dtype=policy.param_dtype,
            name="head",
        )(x)
        return x.astype(policy.output_dtype)


def ResNet18(num_classes: int = 10, stem: str = "cifar", **kw) -> ResNet:
    """Recipe-1 model (BASELINE.json:7): CIFAR smoke-test configuration."""
    return ResNet(
        stage_sizes=[2, 2, 2, 2],
        block_cls=BasicBlock,
        num_classes=num_classes,
        stem=stem,
        **kw,
    )


def ResNet50(num_classes: int = 1000, stem: str = "imagenet", **kw) -> ResNet:
    """Recipe-2 / north-star model (BASELINE.json:2,8)."""
    return ResNet(
        stage_sizes=[3, 4, 6, 3],
        block_cls=Bottleneck,
        num_classes=num_classes,
        stem=stem,
        **kw,
    )


def ResNet34(num_classes: int = 1000, stem: str = "imagenet", **kw) -> ResNet:
    """torchvision-family completeness (param counts pinned in tests)."""
    return ResNet(
        stage_sizes=[3, 4, 6, 3],
        block_cls=BasicBlock,
        num_classes=num_classes,
        stem=stem,
        **kw,
    )


def ResNet101(num_classes: int = 1000, stem: str = "imagenet", **kw) -> ResNet:
    return ResNet(
        stage_sizes=[3, 4, 23, 3],
        block_cls=Bottleneck,
        num_classes=num_classes,
        stem=stem,
        **kw,
    )


def ResNet152(num_classes: int = 1000, stem: str = "imagenet", **kw) -> ResNet:
    return ResNet(
        stage_sizes=[3, 8, 36, 3],
        block_cls=Bottleneck,
        num_classes=num_classes,
        stem=stem,
        **kw,
    )
