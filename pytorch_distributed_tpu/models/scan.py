"""Scan-over-layers: compile ONE transformer block, not ``num_layers``.

The reference's eager CUDA modules pay nothing for Python-unrolled layer
stacks; under XLA an unrolled stack multiplies trace/compile time by depth
(GPT-2-medium = 24 copies of the same HLO) and bloats the program. The
TPU-idiomatic layout is ``lax.scan`` over the depth axis — via ``nn.scan``
so the block's params stack to ``[L, ...]``:

* compile time is O(1) in depth,
* sharding rules see one stacked tensor per weight (FSDP shards a dim of
  it; TP rules adapt via ``parallel.sharding.stacked``),
* pipeline parallelism consumes the stacked layout directly (stage dim =
  groups of layers, ``parallel/pipeline.py``).

``remat=True`` wraps the block in ``nn.remat`` so the backward pass
recomputes each block's activations instead of storing them — the standard
HBM/FLOPs trade for long sequences (jax.checkpoint). ``cfg.remat_policy``
refines the trade: ``"full"`` recomputes everything; ``"dots"`` saves
matmul outputs and recomputes only the cheap elementwise/softmax work
(jax.checkpoint_policies) — faster backward, a few activations more HBM.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Type

import flax.linen as nn


def remat_policy(name: Optional[str]):
    """jax.checkpoint policy by short name: 'full' (recompute everything),
    'dots' (save all matmul results), 'dots_no_batch' (save weight-matmul
    results, recompute batched attention products)."""
    import jax

    if name in (None, "full"):
        return None  # nothing saved — maximum recompute
    if name == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if name == "dots_no_batch":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    raise ValueError(
        f"unknown remat_policy {name!r}; expected full | dots | "
        f"dots_no_batch"
    )


def scan_stack(
    block_cls: Type[nn.Module],
    cfg,
    *,
    length: Optional[int] = None,
    remat: Optional[bool] = None,
    static_argnums: Tuple[int, ...] = (),
    name: str = "blocks",
) -> Callable:
    """Build the scanned stack and return ``f(x, *bcast) -> x``.

    Must be called inside the parent module's ``@nn.compact`` ``__call__``
    (the scanned module attaches to the caller's scope under ``name``).
    ``block_cls(cfg).__call__(x, *bcast)`` takes the carried activation
    first; every further argument is broadcast unchanged to all layers.
    Under ``remat``, pass ``static_argnums`` (0 = ``x``) marking python-bool
    args like ``deterministic`` so they stay static.

    ``cfg.scan_dequant`` wraps the block in ``nn.map_variables`` so a
    QUANTIZED stacked param tree (ops/quant.py int8/int4 leaf dicts,
    leading ``[L]`` axis — exactly what ``quantize_tree_int8/int4``
    produce on the stacked kernels) dequantizes PER LAYER inside each
    scan iteration, never materializing the whole reconstructed stack:
    peak weight residency is quantized-tree + ONE layer's bf16 weights.
    This is what lets an int4 8B (~4.5 GB at rest) decode on a single
    16 GB chip — whole-tree ``quantized_apply_fn`` would transiently
    need the full ~16 GB bf16 reconstruction. Plain (unquantized)
    leaves pass through untouched, so initializing with the flag on
    still works and quantization stays a post-training transform.
    """
    use_remat = cfg.remat if remat is None else remat

    class Body(nn.Module):
        @nn.compact
        def __call__(self, x, *bcast):
            return block_cls(cfg, name="block")(x, *bcast), None

    if getattr(cfg, "scan_dequant", False):
        from pytorch_distributed_tpu.ops.quant import dequantize_tree
        from pytorch_distributed_tpu.runtime.precision import (
            current_policy,
        )

        def _dequant_in(vars_in):
            policy = current_policy()
            return dequantize_tree(vars_in, dtype=policy.param_dtype)

        Body = nn.map_variables(
            Body, "params",
            trans_in_fn=_dequant_in,
            # init path: params created inside are plain arrays; store
            # them unchanged (quantization happens outside, later)
            trans_out_fn=lambda v: v,
            mutable=True,
        )

    body = (
        nn.remat(
            Body,
            prevent_cse=False,
            static_argnums=static_argnums,
            policy=remat_policy(cfg.remat_policy),
        )
        if use_remat
        else Body
    )
    mod = nn.scan(
        body,
        # cache: per-layer KV decode caches stack [L, ...] like params;
        # intermediates: per-layer sown values (e.g. MoE aux losses)
        variable_axes={"params": 0, "cache": 0, "intermediates": 0},
        split_rngs={"params": True, "dropout": True},
        in_axes=nn.broadcast,
        length=length if length is not None else cfg.num_layers,
    )(name=name)

    def apply_stack(x, *bcast):
        y, _ = mod(x, *bcast)
        return y

    return apply_stack
