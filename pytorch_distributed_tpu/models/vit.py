"""Vision Transformer (ViT) — image classification, TPU-first.

Beyond the reference's recipe matrix (its vision workloads are ResNets),
but the natural stretch for a framework claiming model-family breadth: the
encoder reuses the same attention dispatch (``ops.attention``) every other
transformer here uses — so flash/sequence-parallel dispatch applies — and
the same Megatron-style TP rule shapes as BERT.

TPU notes:
* patch embedding is a single strided conv — one big MXU matmul per image
  rather than a host-side unfold;
* encoder blocks are pre-LN (ViT standard), GELU MLP;
* pooling: "cls" token (paper) or "mean" (common for small data).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import attention
from pytorch_distributed_tpu.runtime.precision import current_policy

from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    pooling: str = "cls"  # cls | mean
    layer_norm_eps: float = 1e-6  # HF ViT uses 1e-12

    @classmethod
    def base(cls) -> "ViTConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "ViTConfig":  # test/smoke configuration
        return cls(
            image_size=32, patch_size=8, num_classes=10, hidden_size=64,
            num_layers=2, num_heads=4, mlp_dim=128,
        )

    def __post_init__(self):
        if self.pooling not in ("cls", "mean"):
            raise ValueError(
                f"pooling must be 'cls' or 'mean', got {self.pooling!r}"
            )

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


class ViTBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool):
        cfg = self.config
        policy = current_policy()
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps,
            dtype=policy.compute_dtype, param_dtype=policy.param_dtype,
            name=name,
        )
        h = ln("attn_ln")(x)  # pre-LN
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.num_heads, cfg.hidden_size // cfg.num_heads),
            dtype=policy.compute_dtype, param_dtype=policy.param_dtype,
            name=name,
        )
        q, k, v = dense("query")(h), dense("key")(h), dense("value")(h)
        attn = attention(q, k, v)  # bidirectional, no mask
        attn = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1),
            dtype=policy.compute_dtype, param_dtype=policy.param_dtype,
            name="out",
        )(attn)
        attn = nn.Dropout(cfg.dropout_rate)(attn, deterministic=deterministic)
        x = x + attn
        h = ln("mlp_ln")(x)
        h = nn.Dense(
            cfg.mlp_dim, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="mlp_up",
        )(h)
        h = nn.gelu(h, approximate=False)  # HF ViT uses exact-erf gelu
        h = nn.Dense(
            cfg.hidden_size, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="mlp_down",
        )(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return x + h


class ViT(nn.Module):
    """ViT classifier: [B, H, W, 3] images -> [B, num_classes] logits."""

    config: ViTConfig

    @nn.compact
    def __call__(self, images, *, train: bool = False):
        cfg = self.config
        policy = current_policy()
        B, H, W, _ = images.shape
        if H != cfg.image_size or W != cfg.image_size:
            raise ValueError(
                f"expected {cfg.image_size}x{cfg.image_size} images, "
                f"got {H}x{W}"
            )
        x = nn.Conv(
            cfg.hidden_size,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name="patch_embed",
        )(images.astype(policy.compute_dtype))
        x = x.reshape(B, -1, cfg.hidden_size)  # [B, patches, D]
        n_tokens = cfg.num_patches + (1 if cfg.pooling == "cls" else 0)
        if cfg.pooling == "cls":
            cls = self.param(
                "cls_token", nn.initializers.zeros,
                (1, 1, cfg.hidden_size), policy.param_dtype,
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (B, 1, cfg.hidden_size)).astype(
                    x.dtype
                ), x], axis=1,
            )
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(stddev=0.02),
            (1, n_tokens, cfg.hidden_size),
            policy.param_dtype,
        )
        x = x + pos.astype(x.dtype)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=not train)
        for i in range(cfg.num_layers):
            x = ViTBlock(cfg, name=f"block_{i}")(x, deterministic=not train)
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps,
            dtype=policy.compute_dtype, param_dtype=policy.param_dtype,
            name="final_ln",
        )(x)
        pooled = x[:, 0] if cfg.pooling == "cls" else x.mean(axis=1)
        logits = nn.Dense(
            cfg.num_classes, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="head",
        )(pooled)
        # the AMP contract every model family here follows: logits leave
        # in output_dtype (f32) so loss/metrics don't reduce in bf16
        return logits.astype(policy.output_dtype)


def vit_partition_rules():
    """Megatron-style TP, same shapes as BERT's encoder rules."""
    return [
        (r"(query|key|value)/kernel", P(None, "tp", None)),
        (r"(query|key|value)/bias", P("tp", None)),
        (r"out/kernel", P("tp", None, None)),
        (r"mlp_up/kernel", P(None, "tp")),
        (r"mlp_up/bias", P("tp")),
        (r"mlp_down/kernel", P("tp", None)),
        (r"head/kernel", P(None, "tp")),
    ]
