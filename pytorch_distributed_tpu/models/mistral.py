"""Mistral-7B — Llama body + sliding-window attention, beyond-reference.

Architecturally Mistral IS the Llama decoder (RMSNorm, RoPE, GQA,
SwiGLU) with one semantic change: sliding-window attention — position
``i`` attends only to keys in ``(i - window, i]`` (Jiang et al. 2023).
The window lives in the shared attention op (``attention(window=)``,
a band mask composed with causal, valid under KV-cache decode), so this
module is exactly a config: the block, decode path, sharding rules, and
HF weight layout are Llama's, and ``interop.load_mistral_weights`` /
``export_mistral_weights`` are the Llama mappings verbatim (HF Mistral
state_dicts use identical names).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pytorch_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_partition_rules,
)

mistral_partition_rules = llama_partition_rules


@dataclasses.dataclass(frozen=True)
class MistralConfig(LlamaConfig):
    # Mistral-7B-v0.1 geometry
    vocab_size: int = 32_000
    hidden_size: int = 4_096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14_336
    max_seq_len: int = 32_768
    rope_theta: float = 10_000.0
    sliding_window: Optional[int] = 4_096

    @classmethod
    def mistral_7b(cls) -> "MistralConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "MistralConfig":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=128, max_seq_len=128,
            sliding_window=8,
        )


class MistralForCausalLM(LlamaForCausalLM):
    """Llama machinery end to end; the config's window does the work."""

    config: MistralConfig
