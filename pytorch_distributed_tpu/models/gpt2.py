"""GPT-2 — recipe 4 of the reference matrix (BASELINE.json:10:
"GPT-2-medium, DDP + grad-accum + ZeRO-1").

Pre-LN decoder with learned positions and a weight-tied LM head (logits
through the transposed token embedding — halves the largest tensor, which
matters for ZeRO-1 state sharding). Causal masking is closed-form inside
the fused attention op.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import (
    attention,
    validate_write_pos,
)
from pytorch_distributed_tpu.runtime.precision import current_policy


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50_257
    n_positions: int = 1_024
    hidden_size: int = 1_024
    num_layers: int = 24
    num_heads: int = 16
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-5
    # scan over layers: ONE block is traced/compiled instead of num_layers
    # copies — the TPU-idiomatic layout (compile time scales O(1) in depth;
    # block params stack to [L, ...], which sharding rules and pipeline
    # stages consume directly). False restores the unrolled per-layer tree.
    scan_layers: bool = True
    remat: bool = False  # rematerialize each block in backward (saves HBM)
    scan_dequant: bool = False  # per-layer dequant of quantized block params
    # inside the scan (models/scan.py) — the single-chip big-model serving path

    remat_policy: str = "full"  # full | dots | dots_no_batch (models/scan.py)
    # "int8" rests the decode KV cache quantized (~2x less HBM than a
    # bf16 cache; lossy — see ops/attention.decode_cache); None = exact
    kv_cache_quantize: "str | None" = None
    # > 0 turns every block's FFN into a mixture-of-experts (ops/moe.py):
    # experts shard over the ep mesh axis. Uniform across layers so the
    # scanned stack stays homogeneous.
    moe_experts: int = 0
    moe_k: int = 2
    # expert queue length = k*T*factor/E. NOTE: capacity dropping makes
    # routing depend on how many tokens share the call — a token dropped
    # at full-batch width may survive at decode width — so outputs are
    # only decode-vs-recompute identical when capacity is ample.
    moe_capacity_factor: float = 1.25

    def __post_init__(self):
        if self.scan_dequant and not self.scan_layers:
            raise ValueError(
                "scan_dequant dequantizes inside the layer scan — it "
                "requires scan_layers=True (an unrolled stack would hand "
                "raw quantized dicts to the blocks)"
            )

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    @classmethod
    def medium(cls) -> "GPT2Config":  # the recipe's size (355M params)
        return cls()

    @classmethod
    def small(cls) -> "GPT2Config":
        return cls(hidden_size=768, num_layers=12, num_heads=12)

    @classmethod
    def tiny(cls) -> "GPT2Config":
        return cls(
            vocab_size=512, n_positions=64, hidden_size=64, num_layers=2,
            num_heads=4,
        )


class GPT2Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, segment_ids, kv_mask, write_pos,
                 deterministic: bool, decode: bool = False,
                 cache_len: Optional[int] = None):
        cfg = self.config
        policy = current_policy()
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name=name,
        )
        h = ln("ln1")(x)
        qkv = nn.DenseGeneral(
            (3, cfg.num_heads, cfg.hidden_size // cfg.num_heads),
            dtype=policy.compute_dtype, param_dtype=policy.param_dtype,
            name="attn_qkv",
        )(h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if decode:
            from pytorch_distributed_tpu.ops.attention import decode_cache

            k, v, offset = decode_cache(
                self, k, v, cache_len or cfg.n_positions,
                quantize=cfg.kv_cache_quantize, write_pos=write_pos,
            )
            attn = attention(
                q, k, v, causal=True, q_offset=offset, mask=kv_mask
            )
        else:
            attn = attention(q, k, v, causal=True, segment_ids=segment_ids)
        attn = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="attn_out",
        )(attn)
        x = x + nn.Dropout(cfg.dropout_rate)(attn, deterministic=deterministic)

        h = ln("ln2")(x)
        if cfg.moe_experts > 0:
            from pytorch_distributed_tpu.ops.moe import MoEMLP

            h = MoEMLP(
                num_experts=cfg.moe_experts, d_ff=cfg.intermediate_size,
                k=cfg.moe_k, capacity_factor=cfg.moe_capacity_factor,
                name="moe",
            )(h)
        else:
            h = nn.Dense(
                cfg.intermediate_size, dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype, name="mlp_up",
            )(h)
            h = nn.gelu(h)
            h = nn.Dense(
                cfg.hidden_size, dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype, name="mlp_down",
            )(h)
        return x + nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)


class GPT2LMHead(nn.Module):
    """Causal LM: returns [B, S, vocab] logits (head tied to wte)."""

    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, positions=None, *,
                 segment_ids=None, kv_mask=None, write_pos=None,
                 train: bool = False,
                 decode: bool = False, cache_len: Optional[int] = None,
                 return_hidden: bool = False):
        cfg = self.config
        policy = current_policy()
        B, S = input_ids.shape
        if S > cfg.n_positions:
            raise ValueError(f"sequence {S} > n_positions {cfg.n_positions}")
        if cache_len is not None and cache_len > cfg.n_positions:
            raise ValueError(
                f"cache_len {cache_len} > n_positions {cfg.n_positions}"
            )
        wte = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, param_dtype=policy.param_dtype,
            name="wte",
        )
        wpe = nn.Embed(
            cfg.n_positions, cfg.hidden_size, param_dtype=policy.param_dtype,
            name="wpe",
        )
        if segment_ids is not None and decode:
            raise ValueError(
                "segment_ids (packed training) and decode (KV cache) are "
                "mutually exclusive"
            )
        if kv_mask is not None and not decode:
            raise ValueError(
                "kv_mask is for KV-cache decode (left-padded prompts); "
                "training masks go through the loss/segment machinery"
            )
        validate_write_pos(write_pos, decode, positions)
        if decode:
            from pytorch_distributed_tpu.ops.attention import (
                decode_positions,
            )

            # ALWAYS advance the cache position counter in decode mode —
            # a caller prefilling with explicit positions (left padding)
            # must not desync later positions=None decode steps from the
            # separately-advancing KV cache_index
            auto = decode_positions(self, S)[None, :]
            if positions is None:
                positions = auto
        elif positions is None:
            positions = jnp.arange(S)[None, :]
        x = wte(input_ids) + wpe(positions)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=not train)
        x = x.astype(policy.compute_dtype)
        if cfg.scan_layers:
            from pytorch_distributed_tpu.models.scan import scan_stack

            x = scan_stack(
                GPT2Block, cfg, static_argnums=(4, 5, 6), name="blocks"
            )(x, segment_ids, kv_mask, write_pos, not train, decode,
              cache_len)
        else:
            for i in range(cfg.num_layers):
                x = GPT2Block(cfg, name=f"block{i}")(
                    x, segment_ids, kv_mask, write_pos,
                    deterministic=not train,
                    decode=decode, cache_len=cache_len,
                )
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype, name="ln_f",
        )(x)
        if return_hidden:
            # [B, S, D] for the chunked-vocab loss (ops/lm_loss.py); the
            # tied projection weight is params['wte']['embedding']
            return x.astype(policy.output_dtype)
        # tied head in compute dtype (bf16 MXU path for the largest matmul),
        # f32 accumulation
        logits = jnp.einsum(
            "bsd,vd->bsv",
            x,
            wte.embedding.astype(policy.compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return logits.astype(policy.output_dtype)


def gpt2_partition_rules():
    """TP rules: qkv kernel [hidden, 3, heads, head_dim] — shard heads.

    A declarative table over the shape-aware rule engine
    (autoplan/rules.py): the engine adapts each spec to the scan
    layout's leading layer dim (so the same rules serve
    scan_layers=True and the unrolled tree) and replicates — with a
    warning — any dim that does not divide its mesh axes, so these
    rules stay valid on every mesh shape the auto-parallel planner
    enumerates. MoE expert weights (when ``moe_experts > 0``) shard
    over ``ep`` with the FFN hidden dim over ``tp``.
    """
    from pytorch_distributed_tpu.autoplan.rules import (
        TensorRule,
        engine_rules,
    )

    return engine_rules([
        TensorRule(r"attn_qkv/kernel", (None, None, "tp", None)),
        TensorRule(r"attn_qkv/bias", (None, "tp", None)),
        # attn_out kernel is [heads, hd, hidden]
        TensorRule(r"attn_out/kernel", ("tp", None, None)),
        TensorRule(r"mlp_up/kernel", (None, "tp")),
        TensorRule(r"mlp_up/bias", ("tp",)),
        TensorRule(r"mlp_down/kernel", ("tp", None)),
        TensorRule(r"moe/w_in", ("ep", None, "tp")),
        TensorRule(r"moe/w_out", ("ep", "tp", None)),
        TensorRule(r"wte/embedding", (None, "tp"), stacked=False),
    ])
