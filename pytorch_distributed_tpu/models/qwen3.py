"""Qwen3 — Llama body + per-head QK-RMSNorm, beyond-reference.

Qwen3 drops Qwen2's projection biases and instead RMS-normalizes each
head's query and key (one [head_dim] scale each, shared across heads)
before rotary — the ``qk_norm`` flag on the shared config. Everything
else is the Llama machinery; ``interop.load_qwen3_weights`` is the
shared body mapping with the two norm scales carried through.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pytorch_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_partition_rules,
)

qwen3_partition_rules = llama_partition_rules


@dataclasses.dataclass(frozen=True)
class Qwen3Config(LlamaConfig):
    # Qwen3-8B geometry (head_dim 128, decoupled from hidden/heads)
    vocab_size: int = 151_936
    hidden_size: int = 4_096
    num_layers: int = 36
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 12_288
    max_seq_len: int = 32_768
    rope_theta: float = 1_000_000.0
    rms_eps: float = 1e-6
    override_head_dim: Optional[int] = 128
    qk_norm: bool = True

    @classmethod
    def qwen3_8b(cls) -> "Qwen3Config":
        return cls()

    @classmethod
    def tiny(cls) -> "Qwen3Config":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=128, max_seq_len=128,
            override_head_dim=16,
        )


class Qwen3ForCausalLM(LlamaForCausalLM):
    """Llama machinery end to end; the config's QK norms do the work."""

    config: Qwen3Config
