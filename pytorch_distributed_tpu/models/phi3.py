"""Phi-3 — the Llama body behind fused HF projections, beyond-reference.

Architecturally Phi-3-mini IS the Llama decoder (RMSNorm, full rotary,
SwiGLU, untied head, no attention biases); HF just stores the q/k/v
projections fused as ``qkv_proj`` and the MLP gate/up fused as
``gate_up_proj``. The model here is therefore pure configuration, and
``interop.load_phi3_weights`` splits the fused tensors onto the shared
Llama mapping (export re-fuses).
"""

from __future__ import annotations

import dataclasses

from pytorch_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_partition_rules,
)

phi3_partition_rules = llama_partition_rules


@dataclasses.dataclass(frozen=True)
class Phi3Config(LlamaConfig):
    # Phi-3-mini-4k geometry (MHA: kv heads == heads)
    vocab_size: int = 32_064
    hidden_size: int = 3_072
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    intermediate_size: int = 8_192
    max_seq_len: int = 4_096
    rope_theta: float = 10_000.0

    @classmethod
    def phi3_mini(cls) -> "Phi3Config":
        return cls()

    @classmethod
    def tiny(cls) -> "Phi3Config":
        return cls(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=4, intermediate_size=128, max_seq_len=128,
        )


class Phi3ForCausalLM(LlamaForCausalLM):
    """Llama machinery end to end; only the HF weight layout differs."""

    config: Phi3Config
