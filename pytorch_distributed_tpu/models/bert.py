"""BERT-base — recipe 3 of the reference matrix (BASELINE.json:9:
"BERT-base fine-tune, DDP + amp.GradScaler -> XLA bf16").

Classic post-LN encoder. bf16 compute comes from the precision policy
(the recipe's GradScaler is a no-op in bf16 — see runtime.precision);
tensor-parallel partition rules ship with the model (column-parallel
QKV/up, row-parallel out/down — Megatron layout, expressed as sharding
specs instead of module surgery).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.ops.attention import attention
from pytorch_distributed_tpu.runtime.precision import current_policy


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3_072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":  # test/smoke configuration
        return cls(
            vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=128,
        )


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, deterministic: bool):
        cfg = self.config
        policy = current_policy()
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.num_heads, cfg.hidden_size // cfg.num_heads),
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name=name,
        )
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        out = attention(q, k, v, mask=attention_mask)
        out = nn.DenseGeneral(
            cfg.hidden_size,
            axis=(-2, -1),
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name="out",
        )(out)
        return nn.Dropout(cfg.dropout_rate)(out, deterministic=deterministic)


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, deterministic: bool):
        cfg = self.config
        policy = current_policy()
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps,
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name=name,
        )
        attn_out = BertSelfAttention(cfg, name="attn")(
            x, attention_mask, deterministic
        )
        x = ln("attn_ln")(x + attn_out)
        h = nn.Dense(
            cfg.intermediate_size,
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name="mlp_up",
        )(x)
        h = nn.gelu(h, approximate=False)  # BERT uses exact-erf gelu
        h = nn.Dense(
            cfg.hidden_size,
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name="mlp_down",
        )(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return ln("mlp_ln")(x + h)


class BertModel(nn.Module):
    """Encoder trunk: returns (sequence_output, pooled_output)."""

    config: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask: Optional[jnp.ndarray] = None,
        token_type_ids: Optional[jnp.ndarray] = None,
        *,
        train: bool = False,
        return_embed_table: bool = False,
    ):
        cfg = self.config
        policy = current_policy()
        B, S = input_ids.shape
        if S > cfg.max_position_embeddings:
            raise ValueError(
                f"sequence {S} > max_position_embeddings "
                f"{cfg.max_position_embeddings}"
            )
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), jnp.bool_)
        attention_mask = attention_mask.astype(jnp.bool_)

        embed = lambda n, num: nn.Embed(  # noqa: E731
            num, cfg.hidden_size, param_dtype=policy.param_dtype, name=n
        )
        word_embed = embed("word_embeddings", cfg.vocab_size)
        x = (
            word_embed(input_ids)
            + embed("position_embeddings", cfg.max_position_embeddings)(
                jnp.arange(S)[None, :]
            )
            + embed("token_type_embeddings", cfg.type_vocab_size)(token_type_ids)
        )
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, param_dtype=policy.param_dtype,
            dtype=policy.compute_dtype, name="embed_ln",
        )(x)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=not train)
        x = x.astype(policy.compute_dtype)

        for i in range(cfg.num_layers):
            x = BertLayer(cfg, name=f"layer{i}")(
                x, attention_mask, deterministic=not train
            )

        pooled = nn.tanh(
            nn.Dense(
                cfg.hidden_size,
                dtype=policy.compute_dtype,
                param_dtype=policy.param_dtype,
                name="pooler",
            )(x[:, 0])
        )
        if return_embed_table:
            return (
                x.astype(policy.output_dtype),
                pooled.astype(policy.output_dtype),
                word_embed.embedding,
            )
        return x.astype(policy.output_dtype), pooled.astype(policy.output_dtype)


class BertForSequenceClassification(nn.Module):
    """Recipe-3 fine-tuning head (BASELINE.json:9)."""

    config: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 *, train: bool = False):
        policy = current_policy()
        _, pooled = BertModel(self.config, name="bert")(
            input_ids, attention_mask, token_type_ids, train=train
        )
        pooled = nn.Dropout(self.config.dropout_rate)(
            pooled.astype(policy.compute_dtype), deterministic=not train
        )
        logits = nn.Dense(
            self.num_labels,
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name="classifier",
        )(pooled)
        return logits.astype(policy.output_dtype)


class BertForMaskedLM(nn.Module):
    """MLM pretraining head (HF ``BertForMaskedLM`` shape): transform
    Dense + GELU + LayerNorm, then a decoder TIED to the word-embedding
    table (one [V, H] matrix serves embed and un-embed, the standard BERT
    tying) plus a free output bias. Logits return in f32 (policy output
    dtype) for a stable softmax over the 30k vocab."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 *, train: bool = False):
        policy = current_policy()
        cfg = self.config
        x, _, table = BertModel(cfg, name="bert")(
            input_ids, attention_mask, token_type_ids, train=train,
            return_embed_table=True,
        )
        h = nn.Dense(
            cfg.hidden_size,
            dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
            name="mlm_dense",
        )(x.astype(policy.compute_dtype))
        h = nn.gelu(h, approximate=False)
        h = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, param_dtype=policy.param_dtype,
            dtype=policy.compute_dtype, name="mlm_ln",
        )(h)
        logits = h @ table.astype(policy.compute_dtype).T
        bias = self.param(
            "mlm_bias", nn.initializers.zeros, (cfg.vocab_size,),
            policy.param_dtype,
        )
        return (logits + bias).astype(jnp.float32)


def mask_tokens(
    rng,
    input_ids,
    *,
    mask_token_id: int,
    vocab_size: int,
    mask_prob: float = 0.15,
    special_mask=None,
):
    """BERT's 80/10/10 dynamic masking, ON DEVICE (jit-safe, static
    shapes) — the host ships raw token ids and every step draws a fresh
    masking from the step rng (RoBERTa-style dynamic masking, free on
    TPU where the alternative is host-side preprocessing).

    Returns ``(masked_ids, labels)`` with ``labels == -100`` (the HF
    ignore index) at unselected positions. ``special_mask`` ([B, S]
    bool, True = never mask) protects CLS/SEP/PAD.
    """
    k_sel, k_op, k_rand = jax.random.split(rng, 3)
    sel = jax.random.uniform(k_sel, input_ids.shape) < mask_prob
    if special_mask is not None:
        sel = sel & ~special_mask
    labels = jnp.where(sel, input_ids, -100)
    op = jax.random.uniform(k_op, input_ids.shape)
    random_ids = jax.random.randint(
        k_rand, input_ids.shape, 0, vocab_size, dtype=input_ids.dtype
    )
    masked = jnp.where(op < 0.8, jnp.asarray(mask_token_id,
                                             input_ids.dtype),
                       jnp.where(op < 0.9, random_ids, input_ids))
    return jnp.where(sel, masked, input_ids), labels


def bert_partition_rules():
    """Megatron-style TP: column-parallel QKV/up, row-parallel out/down.

    DenseGeneral QKV kernels have shape [hidden, heads, head_dim]; the
    heads dim is the column-parallel axis. Embeddings shard the hidden dim.
    """
    return [
        (r"attn/(query|key|value)/kernel", P(None, "tp", None)),
        (r"attn/(query|key|value)/bias", P("tp", None)),
        (r"attn/out/kernel", P("tp", None, None)),
        (r"mlp_up/kernel", P(None, "tp")),
        (r"mlp_up/bias", P("tp")),
        (r"mlp_down/kernel", P("tp", None)),
        (r"_embeddings/embedding", P(None, "tp")),
    ]
