"""LoRA fine-tuning — low-rank adapters as a pure params-pytree transform.

The peft/`LoraConfig` idiom without module surgery (same philosophy as
``ops/quant.py``), INCLUDING the QLoRA composition: the frozen base may
be an int8/int4 quantized tree — adapters init from the reconstructed
kernel shapes, and the merge dequantizes transiently before adding the
full-precision delta (Dettmers et al.'s recipe shape). Otherwise: the
trainable state is a tiny adapter tree mirroring the matched kernels,
and a duck-typed wrapper merges ``W + (alpha/r) * A @ B`` inside the
jitted step. Because the wrapper exposes the same ``.apply`` surface the
framework's loss functions, Trainer, and ``generate`` already consume,
LoRA composes with everything — DP/FSDP sharding, grad accumulation,
checkpointing (the checkpoint is just the adapter tree), KV-cache
decode — with no special cases.

Why this is the TPU shape:

* **Gradients only flow to the adapters.** The loss closes over the
  frozen base tree, so ``jax.grad`` w.r.t. the adapter tree prices the
  backward at adapter size and the optimizer state (Adam moments) drops
  from O(params) to O(r * (in+out)) — the reason LoRA exists. An 8B
  base in bf16 plus full-rank Adam state does not fit one v5e; base +
  r=16 adapters + their moments does.
* **Merge-inside-jit, not hooked matmuls.** Computing ``x@W + (x@A)@B``
  needs per-layer forward hooks; merging materializes ``W_eff`` as a
  transient XLA buffer but keeps the model untouched and lets XLA fuse
  the rank-r update into the surrounding graph. For serving, merge once
  with :func:`lora_merge` and drop the wrapper entirely.
* **Scanned stacks get per-layer adapters.** Kernels under a scanned
  block carry a leading layer axis ([L, ...]); the adapters carry it
  too ([L, in, r] / [L, r, out]) so each layer trains its own subspace
  and the merge is one batched einsum under the same ``lax.scan``.

Kernel geometry: flax ``DenseGeneral`` kernels split dims as
``[scan?][*in][*out]`` with layer-type-specific arity (GPT-2's fused
qkv kernel is [L, D, 3, H, hd]; its attention-out is [L, H, hd, D]).
Target patterns therefore name their trailing out-axis count; the
defaults cover both model families' attention + MLP projections.

The reference is a training-recipes repo with no adapter-tuning story;
this is a beyond-parity capability (BASELINE.json:5).
"""

from __future__ import annotations

import math
import re
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.quant import (
    _is_qleaf,
    dequantize_tree,
    original_shape,
)

# pattern -> number of trailing OUT axes in the matched kernel.
# GPT-2: fused qkv [.., D, 3, H, hd] (out=3), attn_out [.., H, hd, D]
# (out=1), mlp_{up,down} [.., in, out] (out=1).
# Llama: q/k/v [.., D, H, hd] (out=2), o [.., H, hd, D] (out=1),
# gate/up/down [.., in, out] (out=1).
# BERT / ViT (unrolled layers): query/key/value [D, H, hd] (out=2),
# the attention out projection [H, hd, D] (out=1 — "attn/out" in BERT,
# bare "out" in ViT); their mlp_{up,down} share the GPT-2 row.
DEFAULT_TARGETS: Dict[str, int] = {
    r"attn_qkv/kernel$": 3,
    r"attn_out/kernel$": 1,
    r"mlp_(up|down)/kernel$": 1,
    r"/(q|k|v)/kernel$": 2,
    r"/o/kernel$": 1,
    r"/(gate|up|down)/kernel$": 1,
    r"/(query|key|value)/kernel$": 2,
    r"/out/kernel$": 1,
}

# kernels whose path contains this segment belong to a scanned layer
# stack and carry one leading layer axis (models/scan.py names the
# scanned module "block" in both families)
_SCAN_SEGMENT = "block"


def _walk(tree, prefix=""):
    for k in sorted(tree):
        v = tree[k]
        path = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict) and not _is_qleaf(v):
            yield from _walk(v, path)
        else:
            yield path, v





def _match(path: str, targets: Dict[str, int]) -> Optional[int]:
    hits = [n for pat, n in targets.items() if re.search(pat, "/" + path)]
    if len(hits) > 1:
        raise ValueError(
            f"kernel {path} matched {len(hits)} LoRA target patterns — "
            "make the patterns disjoint"
        )
    return hits[0] if hits else None


def _geometry(path: str, shape, n_out: int):
    """(scan_dims, in_dims, out_dims) for a matched kernel."""
    scan = 1 if f"/{_SCAN_SEGMENT}/" in f"/{path}/" else 0
    if len(shape) < scan + 1 + n_out:
        raise ValueError(
            f"kernel {path} has shape {shape} — too few axes for "
            f"{scan} scan + >=1 in + {n_out} out"
        )
    return shape[:scan], shape[scan:len(shape) - n_out], shape[
        len(shape) - n_out:
    ]


def lora_init(
    rng: jax.Array,
    params,
    rank: int,
    targets: Optional[Dict[str, int]] = None,
):
    """Build the trainable adapter tree for ``params``.

    Returns a pytree whose structure mirrors the matched kernels, each
    leaf replaced by ``{"a": [*scan, in, r], "b": [*scan, r, out]}`` —
    ``a`` fan-in-scaled normal, ``b`` zeros (the peft convention: the
    model starts EXACTLY at the base checkpoint; tests pin it).
    Raises if no kernel matches (a typo'd pattern should be loud).
    """
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    targets = DEFAULT_TARGETS if targets is None else targets
    adapters = {}
    n_matched = 0
    for path, leaf in _walk(params):
        n_out = _match(path, targets)
        if n_out is None:
            continue
        n_matched += 1
        scan_d, in_d, out_d = _geometry(path, original_shape(leaf), n_out)
        fan_in = math.prod(in_d)
        rng, sub = jax.random.split(rng)
        a = jax.random.normal(
            sub, (*scan_d, fan_in, rank), jnp.float32
        ) / math.sqrt(fan_in)
        b = jnp.zeros((*scan_d, rank, math.prod(out_d)), jnp.float32)
        node = adapters
        parts = path.split("/")
        for seg in parts[:-1]:
            node = node.setdefault(seg, {})
        node[parts[-1]] = {"a": a, "b": b}
    if n_matched == 0:
        raise ValueError(
            "no kernel matched any LoRA target pattern — patterns "
            f"{list(targets)} against paths like "
            f"{[p for p, _ in list(_walk(params))[:4]]}"
        )
    return adapters


def lora_merge(
    params, adapters, *, alpha: Optional[float] = None, dtype=None
):
    """``W + (alpha/r) * A @ B`` for every adapted kernel; other leaves
    pass through untouched. ``alpha`` defaults to the rank (scaling 1,
    the common starting point; peft's ``lora_alpha`` maps directly).

    Every adapter entry MUST find its kernel: an adapter tree built
    against a different param layout (e.g. scanned adapters onto an
    unrolled checkpoint) would otherwise merge into nothing and train
    as a silent no-op — that mismatch raises instead.
    """
    n_adapters = sum(1 for p, _ in _walk(adapters) if p.endswith("/a"))
    consumed = []

    def merge(path, leaf, node):
        sub = node.get("a") if isinstance(node, dict) else None
        if _is_qleaf(leaf):
            # QLoRA: the frozen base is int8/int4 at rest; reconstruct
            # transiently — EVERY quantized leaf, adapted or not (an
            # unadapted quantized embedding must still reach the model
            # as an array), then add the delta where one exists.
            # ``dtype`` bounds the transient cost: bf16 reconstruction
            # halves peak HBM vs the f32 default at 8B scale.
            leaf = dequantize_tree(leaf, dtype=dtype)
        if sub is None:
            return leaf
        consumed.append(path)
        a, b = node["a"], node["b"]
        r = a.shape[-1]
        scale = (alpha if alpha is not None else r) / r
        delta = jnp.einsum("...ir,...ro->...io", a, b) * scale
        return (leaf + delta.reshape(leaf.shape).astype(leaf.dtype))

    def rec(ptree, atree, prefix=""):
        out = {}
        for k, v in ptree.items():
            node = atree.get(k, {}) if isinstance(atree, dict) else {}
            if isinstance(v, dict) and not _is_qleaf(v):
                out[k] = rec(v, node, f"{prefix}/{k}")
            else:
                out[k] = merge(f"{prefix}/{k}", v, node)
        return out

    merged = rec(params, adapters)
    if len(consumed) != n_adapters:
        raise ValueError(
            f"adapter tree has {n_adapters} adapted kernels but only "
            f"{len(consumed)} found a matching param leaf — the adapter "
            "and param layouts disagree (scanned vs unrolled checkpoint, "
            "renamed modules?); merging would silently train nothing"
        )
    return merged


class LoRAModel:
    """Duck-typed model whose trainable params ARE the adapter tree.

    ``LoRAModel(model, base_params).apply({"params": adapters}, ...)``
    merges and forwards — signature-compatible with every consumer of
    the flax ``.apply`` surface in this framework (loss functions,
    ``build_train_step``, Trainer, ``generate``/``generate_beam``/
    ``generate_speculative``), so the adapter tree slots in anywhere a
    params tree does. The base tree is closed over and never receives
    gradients.
    """

    def __init__(self, model, base_params, *, alpha=None, dtype=None):
        self.model = model
        self.base_params = base_params
        self.alpha = alpha
        self.dtype = dtype  # quantized-base reconstruction dtype
        # (pass the compute dtype, e.g. jnp.bfloat16, to halve the
        # transient merged tree vs f32 — the QuantizedModel precedent)

    @property
    def config(self):  # generation length checks read model.config
        return getattr(self.model, "config", None)

    def apply(self, variables, *args, **kwargs):
        merged = lora_merge(
            self.base_params, variables["params"],
            alpha=self.alpha, dtype=self.dtype,
        )
        rest = {k: v for k, v in variables.items() if k != "params"}
        return self.model.apply(
            {"params": merged, **rest}, *args, **kwargs
        )

    def init(self, *a, **k):  # pragma: no cover - explicit guard
        raise TypeError(
            "LoRAModel wraps an already-initialized base; build adapters "
            "with lora_init(rng, base_params, rank)"
        )


def lora_param_count(adapters) -> int:
    """Trainable parameter count of an adapter tree."""
    return sum(x.size for _, x in _walk(adapters))
