"""ptdlint — AST-based static analysis for the repo's distributed-
correctness invariants.

The repo's hardest-won rules were, until this package, enforced only by
convention and prose: collectives must be issued in lockstep order
across ranks (``scripts/trace_merge.py`` and the
``PTD_DISTRIBUTED_DEBUG=DETAIL`` fingerprints *assume* it), every
tracing/fault site must be the one-``is None``-test disarmed form
(the <2% traced-overhead budget depends on it), fault-site names are
free strings, and eager ``.at[].set`` costs ~2.4 ms/dispatch on this
box. veScale (PAPERS.md) argues SPMD consistency is a *programming-model
property worth checking*; this package turns each convention into a
rule that fails the suite the moment a future PR breaks it.

Usage::

    from pytorch_distributed_tpu.analysis import Analyzer, default_rules
    findings = Analyzer(root, default_rules()).run(["pytorch_distributed_tpu"])

or the CLI: ``python scripts/ptd_lint.py [--json]``.

This package imports neither jax nor numpy: it must stay runnable as a
pre-test lint step on any host. Rules that need a runtime registry
(PTD003 reads ``runtime/faults.KNOWN_SITES``) parse it out of the
source AST rather than importing the module.
"""

from pytorch_distributed_tpu.analysis.core import (  # noqa: F401
    Analyzer,
    Baseline,
    BaselineEntry,
    Finding,
    ParsedModule,
    Rule,
)
from pytorch_distributed_tpu.analysis.rules import (  # noqa: F401
    ALL_RULES,
    default_rules,
)
