"""ptdlint framework: parse every file once, run pluggable rules.

Design (mirrors the repo's other subsystems: one substrate, pluggable
consumers):

* :class:`ParsedModule` — one file parsed once into an AST with parent
  links, source lines, and the line→rule suppression map from
  ``# ptdlint: disable=PTD00N`` comments. Every rule reads the same
  parse; a 40-file run costs 40 parses total, not 40 × rules.
* :class:`Rule` — ``rule_id`` + ``check(module) -> Iterable[Finding]``.
  Rules are pure functions of the AST: they never import or execute the
  code under analysis (a file that crashes on import still lints).
* :class:`Analyzer` — collects files, parses, runs rules, applies
  suppressions. An unparseable file is itself a finding (``PTD000``),
  never a silent skip — a syntax error in a collective-bearing module
  must not make the lockstep check vacuously pass.
* :class:`Baseline` — the checked-in grandfather list. Entries match on
  ``(rule, path, line_text)`` — the *content* of the flagged line, not
  its number, so unrelated edits above a baselined finding don't
  un-baseline it. The baseline may only shrink: entries that no longer
  match any finding are reported stale and fail the run until removed.

Suppression is explicit and auditable, never positional guesswork: a
``# ptdlint: disable=PTD001`` trailing comment suppresses that line; on
a line of its own it suppresses the next line. ``disable=all`` exists
for generated code but should never appear in this repo.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: reserved id for files the analyzer itself could not parse
PARSE_ERROR_RULE = "PTD000"

_SUPPRESS_RE = re.compile(r"#\s*ptdlint:\s*disable=([A-Za-z0-9,_ ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule_id: str
    path: str  # repo-root-relative, '/'-separated
    line: int  # 1-based
    message: str
    line_text: str = ""  # stripped source of the flagged line

    def fingerprint(self) -> Tuple[str, str, str]:
        """Baseline identity: content-addressed, line-number-free."""
        return (self.rule_id, self.path, self.line_text)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ParsedModule:
    """One source file, parsed once and shared by every rule."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # parent links let rules walk outward (enclosing function, guard
        # expressions) without each re-deriving the spine
        self._parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.suppressed = self._suppression_map()

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of enclosing function/lambda nodes."""
        return [
            a for a in self.ancestors(node)
            if isinstance(
                a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
        ]

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule_id: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule_id=rule_id,
            path=self.relpath,
            line=line,
            message=message,
            line_text=self.line_text(line),
        )

    def _suppression_map(self) -> Dict[int, Set[str]]:
        """line -> rule ids suppressed there (or {'all'}).

        A trailing comment suppresses its own line; a comment alone on a
        line suppresses the next line (the flake8 convention, so a long
        flagged expression can carry its suppression above itself).
        """
        out: Dict[int, Set[str]] = {}
        for i, raw in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            ids = {
                s.strip().upper() if s.strip().lower() != "all" else "all"
                for s in m.group(1).split(",")
                if s.strip()
            }
            target = i + 1 if raw.strip().startswith("#") else i
            out.setdefault(target, set()).update(ids)
        return out

    def is_suppressed(self, finding: Finding) -> bool:
        ids = self.suppressed.get(finding.line)
        return bool(ids) and ("all" in ids or finding.rule_id in ids)


class Rule:
    """Base class: subclasses set ``rule_id``/``title`` and implement
    :meth:`check`. ``path_filter`` (a regex, matched against the
    '/'-separated relpath) restricts a rule to a subtree — PTD004 only
    patrols ``serve/`` and ``train/`` hot paths. ``source_hints`` is a
    sound fast-path filter: an AST pattern built on an identifier can
    only exist where that identifier appears verbatim in the source, so
    a module containing none of the hint substrings is skipped without
    walking its tree (measured ~2-3x on the whole-repo sweep)."""

    rule_id: str = ""
    title: str = ""
    path_filter: Optional[str] = None
    source_hints: Tuple[str, ...] = ()

    def applies_to(self, module: ParsedModule) -> bool:
        if self.path_filter is not None and re.search(
            self.path_filter, module.relpath
        ) is None:
            return False
        if self.source_hints and not any(
            h in module.source for h in self.source_hints
        ):
            return False
        return True

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class BaselineEntry:
    rule: str
    path: str
    line_text: str
    justification: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.line_text)


class Baseline:
    """The grandfather list; shrink-only by construction.

    :meth:`apply` splits findings into (new, baselined) and reports the
    entries that matched nothing as stale — the caller fails the run on
    stale entries, so deleting the last instance of a grandfathered
    pattern forces the baseline entry to be deleted with it.
    """

    VERSION = 1

    def __init__(self, entries: Sequence[BaselineEntry] = ()):
        self.entries = list(entries)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.isfile(path):
            return cls()
        with open(path) as f:
            doc = json.load(f)
        if doc.get("version") != cls.VERSION:
            raise ValueError(
                f"baseline {path!r}: unsupported version "
                f"{doc.get('version')!r} (expected {cls.VERSION})"
            )
        entries = []
        for e in doc.get("entries", []):
            missing = {"rule", "path", "line_text", "justification"} - set(e)
            if missing:
                raise ValueError(
                    f"baseline {path!r}: entry {e!r} missing {sorted(missing)}"
                )
            just = e["justification"].strip()
            if not just or just.startswith("FILL-ME"):
                # an unjustified grandfather is just a hidden bug — the
                # --write-baseline placeholder counts as unjustified
                raise ValueError(
                    f"baseline {path!r}: entry for {e['rule']} at "
                    f"{e['path']} has an empty or FILL-ME justification"
                )
            if e["rule"] == PARSE_ERROR_RULE:
                # a grandfathered parse error would exempt the whole
                # file from EVERY rule forever — the one silent skip
                # this framework exists to refuse
                raise ValueError(
                    f"baseline {path!r}: {PARSE_ERROR_RULE} (parse "
                    f"error) entries cannot be baselined — fix the "
                    f"file at {e['path']}"
                )
            entries.append(BaselineEntry(
                rule=e["rule"], path=e["path"],
                line_text=e["line_text"], justification=e["justification"],
            ))
        return cls(entries)

    def save(self, path: str) -> None:
        doc = {
            "version": self.VERSION,
            "policy": (
                "shrink-only: entries are grandfathered findings with a "
                "one-line justification; stale entries fail the lint run "
                "and must be removed"
            ),
            "entries": [dataclasses.asdict(e) for e in self.entries],
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")

    def apply(
        self, findings: Sequence[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
        """-> (new_findings, baselined_findings, stale_entries)."""
        by_key: Dict[Tuple[str, str, str], BaselineEntry] = {
            e.key(): e for e in self.entries
        }
        used: Set[Tuple[str, str, str]] = set()
        new, grandfathered = [], []
        for f in findings:
            # parse errors are never grandfathered: an unparseable file
            # is unchecked by every rule, which must stay loud
            e = (
                None if f.rule_id == PARSE_ERROR_RULE
                else by_key.get(f.fingerprint())
            )
            if e is not None:
                used.add(e.key())
                grandfathered.append(f)
            else:
                new.append(f)
        stale = [e for e in self.entries if e.key() not in used]
        return new, grandfathered, stale


class Analyzer:
    """Parse every target file once; run every rule over the shared ASTs."""

    #: directory basenames never descended into
    SKIP_DIRS = {"__pycache__", ".git", "node_modules"}

    def __init__(self, root: str, rules: Sequence[Rule],
                 exclude: Sequence[str] = ()):
        self.root = os.path.abspath(root)
        self.rules = list(rules)
        # relpath prefixes to skip (the fixtures corpus is deliberately
        # full of violations — it must never lint the real tree red)
        self.exclude = tuple(e.rstrip("/") + "/" for e in exclude)

    def collect_files(self, paths: Sequence[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            absolute = p if os.path.isabs(p) else os.path.join(self.root, p)
            if os.path.isfile(absolute):
                out.append(absolute)
                continue
            for dirpath, dirnames, filenames in os.walk(absolute):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in self.SKIP_DIRS
                )
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.join(dirpath, name))
        uniq = sorted(set(out))
        return [f for f in uniq if not self._excluded(f)]

    def _excluded(self, path: str) -> bool:
        rel = self._rel(path) + "/"
        return any(rel.startswith(e) for e in self.exclude)

    def _rel(self, path: str) -> str:
        return os.path.relpath(path, self.root).replace(os.sep, "/")

    def parse(self, paths: Sequence[str]
              ) -> Tuple[List[ParsedModule], List[Finding]]:
        modules, errors = [], []
        for path in self.collect_files(paths):
            rel = self._rel(path)
            with open(path, encoding="utf-8") as f:
                source = f.read()
            try:
                modules.append(ParsedModule(path, rel, source))
            except SyntaxError as e:
                errors.append(Finding(
                    rule_id=PARSE_ERROR_RULE,
                    path=rel,
                    line=e.lineno or 1,
                    message=f"file does not parse: {e.msg}",
                    line_text=(e.text or "").strip(),
                ))
        return modules, errors

    def run(self, paths: Sequence[str]) -> List[Finding]:
        """All unsuppressed findings, parse errors included, ordered by
        (path, line, rule)."""
        modules, findings = self.parse(paths)
        for module in modules:
            for rule in self.rules:
                if not rule.applies_to(module):
                    continue
                for f in rule.check(module):
                    if not module.is_suppressed(f):
                        findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
        return findings


# -- small AST helpers shared by the rules ---------------------------------
def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain rooted at a Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def is_trivial_expr(node: ast.AST) -> bool:
    """Cheap enough to evaluate on the disarmed path: constants, bare
    names, attribute chains. Anything that *computes* — calls, subscripts,
    arithmetic, f-strings, displays — is not (runtime/tracing.py's
    documented kwarg-site discipline)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return True
    if isinstance(node, ast.Attribute):
        return is_trivial_expr(node.value)
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return is_trivial_expr(node.operand)
    return False
