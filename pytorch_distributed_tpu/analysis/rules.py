"""The ptdlint rule catalog — six invariants with real repo history.

Each rule documents its motivating incident (the convention it freezes)
and its detection envelope (what it can and cannot see — these are
syntactic checks over one module's AST, not a whole-program analysis;
every approximation errs toward silence, so a finding is worth reading
and a clean run is necessary-not-sufficient). The catalog's prose twin
is docs/DESIGN.md §14.

PTD001 lockstep-collectives     cross-rank deadlock under rank guards
PTD002 disarmed-cost-discipline span/fault args evaluated while disarmed
PTD003 fault-site-registry      free-string site names vs KNOWN_SITES
PTD004 eager-scatter-hot-path   .at[].set outside jit (2.4 ms/dispatch)
PTD005 prng-key-reuse           one key, two draws, no split between
PTD006 donation-after-use       donated buffer read after the call
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from pytorch_distributed_tpu.analysis.core import (
    Finding,
    ParsedModule,
    Rule,
    call_name,
    dotted_name,
    is_trivial_expr,
)

#: the HostRingGroup surface (runtime/hostring.py) plus its composed
#: helpers — all of them block until every participant arrives
COLLECTIVE_OPS = frozenset({
    "all_reduce", "all_reduce_q8", "all_gather", "reduce_scatter",
    "broadcast", "send", "recv", "barrier", "all_to_all", "scatter",
})
#: send/recv match each other across branches: `if rank == src: send
#: else: recv` is the correct P2P shape, not a divergence
_P2P_CANON = {"send": "p2p", "recv": "p2p"}

_RANK_CALL_SUFFIXES = ("get_rank", "process_index", "local_rank")


def _canon_op(op: str) -> str:
    return _P2P_CANON.get(op, op)


def _body_terminates(body: Sequence[ast.AST]) -> bool:
    """Every path through ``body`` leaves the enclosing block (return /
    raise / break / continue) — the statements after the If are then an
    implicit else branch (the repo's pervasive early-return style)."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return _body_terminates(last.body) and _body_terminates(last.orelse)
    return False


def _block_containing(module: ParsedModule, stmt: ast.AST
                      ) -> Optional[List[ast.AST]]:
    """The statement list ``stmt`` sits in (its parent's body/orelse/
    finalbody), for implicit-else lookups."""
    parent = module.parent(stmt)
    for field in ("body", "orelse", "finalbody"):
        blk = getattr(parent, field, None)
        if isinstance(blk, list) and any(s is stmt for s in blk):
            return blk
    return None


def _walk_no_functions(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/lambda/class
    bodies: defining code is not executing it."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


class LockstepCollectives(Rule):
    """PTD001 — a collective issued under rank-dependent control flow
    with no matching collective on the other branch.

    Motivation: every HostRingGroup collective blocks until all ranks
    arrive; ``scripts/trace_merge.py``'s straggler matching and the
    ``PTD_DISTRIBUTED_DEBUG=DETAIL`` fingerprints *assume* lockstep
    issue order. ``if rank == 0: ring.broadcast(...)`` deadlocks the
    peers until the group deadline. A collective in one branch is
    matched by the same op (send↔recv pair across branches) in the
    other; rank-dependence propagates through local assignments
    (``is_src = rank == src``).
    """

    rule_id = "PTD001"
    title = "lockstep-collectives"
    source_hints = tuple(COLLECTIVE_OPS)

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        flagged: Set[Tuple[int, int]] = set()
        taint_cache: Dict[int, Set[str]] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.If, ast.IfExp)):
                continue
            scope = self._scope(module, node)
            tainted = taint_cache.get(id(scope))
            if tainted is None:
                tainted = taint_cache[id(scope)] = self._tainted_names(scope)
            if not self._rank_dependent(node.test, tainted):
                continue
            if isinstance(node, ast.If):
                # an elif arm of a rank-dependent chain was already
                # evaluated as its parent's orelse — re-judging it alone
                # would see an empty other-branch and flag the correct
                # `if rank == 0: send elif rank == peer: recv` shape.
                # Only a TRUE elif (same column as the parent `if`)
                # skips: a rank guard nested under `else:` is indented
                # deeper and must be judged standalone — its own missing
                # arm is a real divergence the parent's set-level match
                # cannot see
                parent = module.parent(node)
                if (
                    isinstance(parent, ast.If)
                    and any(node is n for n in parent.orelse)
                    and node.col_offset == parent.col_offset
                    and self._rank_dependent(parent.test, tainted)
                ):
                    continue
                body, orelse = node.body, node.orelse
                if not orelse and _body_terminates(body):
                    # `if rank == 0: return ring.all_reduce(x)` followed
                    # by a fall-through collective: the trailing
                    # statements ARE the other branch
                    blk = _block_containing(module, node)
                    if blk is not None:
                        i = next(
                            j for j, s in enumerate(blk) if s is node
                        )
                        orelse = blk[i + 1:]
            else:
                body, orelse = [node.body], [node.orelse]
            body_calls = self._collectives(body)
            other_calls = self._collectives(orelse)
            for side, opposite in (
                (body_calls, other_calls), (other_calls, body_calls)
            ):
                opposite_ops = {c for c, _ in opposite}
                # a guarded group doing a full send+recv exchange among
                # its own members is pairwise-complete: P2P blocks only
                # its two endpoints, bystander ranks are free (hostring
                # send/recv contract), so no opposite-branch op is owed
                p2p_self_paired = (
                    "send" in {op for _, c in side
                               for op in [c.func.attr]}
                    and "recv" in {op for _, c in side
                                   for op in [c.func.attr]}
                )
                for canon, call in side:
                    if canon in opposite_ops:
                        continue
                    if canon == "p2p" and p2p_self_paired:
                        continue
                    key = (call.lineno, call.col_offset)
                    if key in flagged:
                        continue
                    flagged.add(key)
                    op = call.func.attr  # type: ignore[union-attr]
                    yield module.finding(
                        self.rule_id, call,
                        f"collective '{op}' issued under rank-dependent "
                        f"control flow with no matching collective on the "
                        f"other branch — ranks taking the other path never "
                        f"enter it: cross-rank deadlock (trace_merge and "
                        f"DETAIL fingerprints assume lockstep issue order)",
                    )

    @staticmethod
    def _scope(module: ParsedModule, node: ast.AST) -> ast.AST:
        fns = module.enclosing_functions(node)
        return fns[0] if fns else module.tree

    def _tainted_names(self, scope: ast.AST) -> Set[str]:
        """Names assigned from rank-dependent expressions, to fixpoint
        (``is_src = rank == src`` then ``owner = is_src and ...``)."""
        tainted: Set[str] = set()
        while True:
            grew = False
            for node in _walk_no_functions(scope):
                value = None
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AnnAssign) and node.value:
                    value, targets = node.value, [node.target]
                elif isinstance(node, ast.NamedExpr):
                    value, targets = node.value, [node.target]
                if value is None or not self._rank_dependent(value, tainted):
                    continue
                for t in targets:
                    if isinstance(t, ast.Name) and t.id not in tainted:
                        tainted.add(t.id)
                        grew = True
            if not grew:
                return tainted

    @staticmethod
    def _rank_dependent(expr: ast.AST, tainted: Set[str]) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and (
                n.id == "rank" or n.id in tainted
            ):
                return True
            if isinstance(n, ast.Attribute) and n.attr == "rank":
                return True
            if isinstance(n, ast.Call):
                dn = call_name(n)
                if dn and dn.split(".")[-1] in _RANK_CALL_SUFFIXES:
                    return True
        return False

    @staticmethod
    def _collectives(
        stmts: Sequence[ast.AST],
    ) -> List[Tuple[str, ast.Call]]:
        out = []
        for stmt in stmts:
            for n in _walk_no_functions(stmt):
                if not (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in COLLECTIVE_OPS
                ):
                    continue
                if any(
                    kw.arg == "group"
                    and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is None
                    )
                    for kw in n.keywords
                ):
                    # explicit-subgroup collective: only the group's
                    # members participate, so selecting them by rank IS
                    # the contract, not a divergence
                    continue
                out.append((_canon_op(n.func.attr), n))
        return out


class DisarmedCostDiscipline(Rule):
    """PTD002 — span/fault-site args computed before the is-None guard.

    Motivation: the production default is disarmed, and the pinned
    <2% traced-overhead budget (bench.py ``observability`` phase) holds
    because every site costs one module-global ``is None`` test. A site
    like ``tracing.span("x", n=len(batch))`` evaluates ``len(batch)``
    and builds a kwargs dict on EVERY disarmed pass. Trivial args
    (constants, names, attribute chains) are accepted on ms-grained
    sites per runtime/tracing.py's documented discipline; anything that
    computes must move behind a guard: the
    ``tracing._NULL_SPAN if tracing._tracer is None else ...`` ternary
    or an ``if tracing.active():`` / ``is not None`` block.

    Boundary: this discipline governs ARMED-ONLY instrumentation —
    paths that exist to be off in production. The flight recorder
    (runtime/flightrec.py) is deliberately the opposite: ALWAYS-ON
    with no disarmed state, so "disarmed cost" is not a concept there;
    its (recording) cost is pinned by bench.py's ``flightrec`` phase
    instead of by this rule, and the module is exempt below alongside
    the guard-implementing substrates.
    """

    rule_id = "PTD002"
    title = "disarmed-cost-discipline"
    source_hints = ("tracing.", "faults.")

    _TRACING_FNS = frozenset(
        {"span", "instant", "counter", "note_compiles"}
    )
    _FAULTS_FNS = frozenset({"check", "fires"})
    #: the substrate modules implement the guards; flightrec is
    #: always-on by design (no disarmed state — see docstring boundary)
    _EXEMPT = ("runtime/tracing.py", "runtime/faults.py",
               "runtime/flightrec.py")

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        if module.relpath.endswith(self._EXEMPT):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            site = self._site_kind(node)
            if site is None:
                continue
            costly = [
                a for a in self._arg_exprs(node) if not is_trivial_expr(a)
            ]
            if not costly or self._guarded(module, node):
                continue
            yield module.finding(
                self.rule_id, node,
                f"{site} site evaluates non-trivial args while disarmed "
                f"(e.g. `{ast.unparse(costly[0])}`) — every disarmed "
                f"pass pays the computation + kwargs dict, breaking the "
                f"one-is-None-test discipline (<2% traced-overhead "
                f"budget). Use trivial args, or gate the site: "
                f"`tracing._NULL_SPAN if tracing._tracer is None else "
                f"tracing.span(...)`.",
            )

    def _site_kind(self, call: ast.Call) -> Optional[str]:
        dn = call_name(call)
        if dn is None or "." not in dn:
            return None
        owner, fn = dn.rsplit(".", 2)[-2:]
        if owner == "tracing" and fn in self._TRACING_FNS:
            return f"tracing.{fn}"
        if owner == "faults" and fn in self._FAULTS_FNS:
            return f"faults.{fn}"
        return None

    @staticmethod
    def _arg_exprs(call: ast.Call) -> Iterable[ast.AST]:
        for a in call.args:
            yield a.value if isinstance(a, ast.Starred) else a
        for kw in call.keywords:
            yield kw.value

    @staticmethod
    def _guarded(module: ParsedModule, call: ast.Call) -> bool:
        child: ast.AST = call
        for anc in module.ancestors(call):
            side = None
            if isinstance(anc, ast.IfExp):
                if child is anc.body:
                    side = "body"
                elif child is anc.orelse:
                    side = "orelse"
            elif isinstance(anc, ast.If):
                if any(child is n for n in anc.body):
                    side = "body"
                elif any(child is n for n in anc.orelse):
                    side = "orelse"
            if side is not None:
                if side == "orelse" and _none_compare(anc.test, ast.Is):
                    return True  # _NULL_SPAN if tr is None else <site>
                if side == "body" and (
                    _none_compare(anc.test, ast.IsNot)
                    or _has_active_call(anc.test)
                ):
                    return True  # if tr is not None / if faults.active()
            child = anc
        return False


def _none_compare(test: ast.AST, op_cls) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Compare) and any(
            isinstance(o, op_cls) for o in n.ops
        ) and any(
            isinstance(c, ast.Constant) and c.value is None
            for c in n.comparators
        ):
            return True
    return False


def _has_active_call(test: ast.AST) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            dn = call_name(n)
            if dn and dn.split(".")[-1] == "active":
                return True
    return False


class FaultSiteRegistry(Rule):
    """PTD003 — every fault-site name must be in the canonical registry.

    Motivation: site names are free strings. ``faults.check("ckpt.writ_"
    "shard")`` at a production call site parses, runs, and never fires —
    a chaos drill "passes" while testing nothing. The registry is
    ``KNOWN_SITES`` in runtime/faults.py (the arming parser already
    refuses unknown names; this rule closes the *call-site* half).
    Checked literals: ``faults.check("...")`` / ``faults.fires("...")``
    / ``faults.throttle("...")`` / ``faults.hang_action("...")`` first
    args, ``faults.injected("spec")``
    / ``faults.configure`` specs, and ``PTD_FAULTS`` spec strings in
    env dicts/assignments —
    which is how tests and drills name sites, so tests/docs snippets
    using a dead name fail the lint too.
    """

    rule_id = "PTD003"
    title = "fault-site-registry"
    source_hints = ("faults.", "PTD_FAULTS")

    _registry_cache: Optional[Set[str]] = None

    def __init__(self, registry: Optional[Set[str]] = None):
        self._registry = registry

    @property
    def registry(self) -> Set[str]:
        if self._registry is not None:
            return self._registry
        if FaultSiteRegistry._registry_cache is None:
            FaultSiteRegistry._registry_cache = self._load_registry()
        return FaultSiteRegistry._registry_cache

    @staticmethod
    def _load_registry() -> Set[str]:
        """Parse KNOWN_SITES out of runtime/faults.py's AST — the same
        source the runtime arms from — without importing it (the
        analyzer must stay import-free over the code it checks)."""
        path = os.path.join(
            os.path.dirname(__file__), os.pardir, "runtime", "faults.py"
        )
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                for t in node.targets
            ):
                return {
                    n.value for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
        raise RuntimeError(
            "KNOWN_SITES assignment not found in runtime/faults.py"
        )

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        registry = self.registry
        for site, node in self._site_literals(module):
            if site not in registry:
                yield module.finding(
                    self.rule_id, node,
                    f"unknown fault site {site!r} — not in "
                    f"runtime/faults.KNOWN_SITES; a typo'd site name "
                    f"never fires and never tells you. Fix the name or "
                    f"register the site.",
                )

    def _site_literals(
        self, module: ParsedModule
    ) -> Iterable[Tuple[str, ast.AST]]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                dn = call_name(node)
                fn = dn.rsplit(".", 1)[-1] if dn else ""
                owner = dn.split(".")[-2] if dn and "." in dn else ""
                first = node.args[0] if node.args else None
                is_str = (
                    isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                )
                if (
                    owner == "faults"
                    and fn in ("check", "fires", "throttle",
                               "hang_action")
                    and is_str
                ):
                    yield first.value, node
                elif (
                    owner == "faults"
                    and fn in ("injected", "configure")
                    and is_str
                ):
                    for site in self._spec_sites(first.value):
                        yield site, node
                elif fn == "setdefault" and len(node.args) >= 2 and (
                    is_str and first.value == "PTD_FAULTS"
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)
                ):
                    for site in self._spec_sites(node.args[1].value):
                        yield site, node
            elif isinstance(node, ast.Assign):
                # env["PTD_FAULTS"] = "site:..." (drills, test harnesses)
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and t.slice.value == "PTD_FAULTS"
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)
                    ):
                        for site in self._spec_sites(node.value.value):
                            yield site, node
            elif isinstance(node, ast.Dict):
                # {"PTD_FAULTS": "site:..."} env dicts
                for k, v in zip(node.keys, node.values):
                    if (
                        isinstance(k, ast.Constant)
                        and k.value == "PTD_FAULTS"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                    ):
                        for site in self._spec_sites(v.value):
                            yield site, v

    @staticmethod
    def _spec_sites(spec: str) -> Iterable[str]:
        """Site names from the PTD_FAULTS grammar (site[:k=v,...];...)."""
        for part in spec.split(";"):
            name = part.partition(":")[0].strip()
            if name:
                yield name


class EagerScatterHotPath(Rule):
    """PTD004 — ``.at[...].set()`` on a serve/train hot path outside any
    jit-compiled function.

    Motivation: an eager scatter dispatch costs ~2.4 ms on this box
    (measured under cProfile — per-request slot updates were half the
    serving wall-clock until PR 3 fused them into jitted programs;
    serve/engine.py documents the incident). Inside jit the same update
    is a fused ~0.1 ms program. A function counts as jitted when it (or
    an enclosing function) carries a jit decorator, is wrapped via
    ``jax.jit(f)`` / ``jax.jit(self._f)`` anywhere in the module, or is
    called (by bare name or ``self.``) from a jitted function in the
    same module. Cross-module helpers are out of this envelope —
    syntactic, per-module, biased toward silence.
    """

    rule_id = "PTD004"
    title = "eager-scatter-hot-path"
    source_hints = (".at[",)
    # serve/ + train/ are the hot paths; ops/paged_attention.py joined
    # them in round 12 — its per-page write helper (paged_write) IS the
    # serving decode tick's KV write, traced inside the engine's jitted
    # programs, and an eager copy of it would be the same ~2.4 ms bug.
    # parallel/pipeline_schedule.py joined in round 20: the host 1F1B
    # loop dispatches per (microbatch, op) TICK — an eager scatter in
    # the fold/handoff path would pay the ~2.4 ms 2*M*S times per step
    path_filter = (r"(^|/)(serve|train)/|(^|/)ops/paged_attention\.py$"
                   r"|(^|/)parallel/pipeline_schedule\.py$")

    _SCATTER_METHODS = frozenset({
        "set", "add", "multiply", "mul", "divide", "div", "power",
        "min", "max", "apply", "get",
    })

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        jitted = self._jitted_functions(module)
        for node in ast.walk(module.tree):
            if not self._is_scatter_call(node):
                continue
            if self._under_jit(module, node, jitted):
                continue
            yield module.finding(
                self.rule_id, node,
                f"eager `.at[...].{node.func.attr}()` outside any "  # type: ignore[union-attr]
                f"jit-compiled function — ~2.4 ms per dispatch on this "
                f"box (the bug class PR 3 fixed by hand: fused row "
                f"updates are ~0.1 ms). Move the update into a jitted "
                f"program.",
            )

    @staticmethod
    def _is_scatter_call(node: ast.AST) -> bool:
        # x.at[...].set(...) == Call(Attribute(Subscript(Attribute 'at')))
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in EagerScatterHotPath._SCATTER_METHODS
            and isinstance(node.func.value, ast.Subscript)
            and isinstance(node.func.value.value, ast.Attribute)
            and node.func.value.value.attr == "at"
        )

    def _jitted_functions(self, module: ParsedModule) -> Set[str]:
        """Names of functions/methods the module jit-compiles."""
        jitted: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and any(self._is_jit_expr(d) for d in node.decorator_list):
                jitted.add(node.name)
            elif isinstance(node, ast.Call) and self._is_jit_name(
                call_name(node)
            ):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        jitted.add(arg.id)
                    elif isinstance(arg, ast.Attribute):
                        jitted.add(arg.attr)  # jax.jit(self._decode_fn)
        # one-module call-graph closure: helpers called from a jitted
        # function body are traced under the same jit
        fns = {
            n.name: n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        while True:
            grew = False
            for name in list(jitted):
                fn = fns.get(name)
                if fn is None:
                    continue
                for n in ast.walk(fn):
                    if isinstance(n, ast.Call):
                        dn = call_name(n)
                        callee = dn.rsplit(".", 1)[-1] if dn else None
                        if callee in fns and callee not in jitted:
                            jitted.add(callee)
                            grew = True
            if not grew:
                return jitted

    @staticmethod
    def _is_jit_name(dn: Optional[str]) -> bool:
        return bool(dn) and any(
            seg in ("jit", "pjit") for seg in dn.split(".")
        )

    @classmethod
    def _is_jit_expr(cls, expr: ast.AST) -> bool:
        """Decorator (possibly partial(jax.jit, ...)) mentioning jit."""
        for n in ast.walk(expr):
            if isinstance(n, (ast.Name, ast.Attribute)):
                if cls._is_jit_name(dotted_name(n)):
                    return True
        return False

    def _under_jit(
        self, module: ParsedModule, node: ast.AST, jitted: Set[str]
    ) -> bool:
        for fn in module.enclosing_functions(node):
            if isinstance(fn, ast.Lambda):
                # jax.jit(lambda ...: x.at[i].set(v)) — the lambda is
                # the jit call's direct argument
                parent = module.parent(fn)
                if isinstance(parent, ast.Call) and self._is_jit_name(
                    call_name(parent)
                ):
                    return True
                continue
            if fn.name in jitted:
                return True
            if any(self._is_jit_expr(d) for d in fn.decorator_list):
                return True
        return False


class PrngKeyReuse(Rule):
    """PTD005 — the same key fed to two ``jax.random`` consumers with no
    split/reassignment between them.

    Motivation: reusing a key makes two "independent" draws identical —
    correlated dropout masks, repeated sampling streams; the bug is
    silent (shapes/dtypes all check out). Consumers are the sampling
    functions plus ``split`` (after ``k1, k2 = split(key)``, using
    ``key`` again replays the stream); ``fold_in`` is a derivation, not
    a consumption (``fold_in(key, i)`` per step is the idiom). Tracked:
    bare-name keys within one function scope, in source order, killed
    by reassignment; two uses in mutually exclusive branches of the
    same ``if``/``try`` don't pair. A consumer inside a loop whose key
    is never reassigned in that loop is flagged as cross-iteration
    reuse. Attribute-held keys (``self._key``) are out of envelope.
    """

    rule_id = "PTD005"
    title = "prng-key-reuse"
    source_hints = ("random.",)

    _CONSUMERS = frozenset({
        "split", "normal", "uniform", "bernoulli", "categorical",
        "gumbel", "randint", "truncated_normal", "permutation", "choice",
        "beta", "gamma", "exponential", "laplace", "logistic", "poisson",
        "dirichlet", "multivariate_normal", "bits", "cauchy", "maxwell",
        "rademacher", "t", "weibull_min", "ball", "orthogonal", "shuffle",
        "binomial", "chisquare", "f", "geometric", "loggamma", "pareto",
        "rayleigh", "triangular", "wald",
    })

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        scopes: List[Tuple[ast.AST, Sequence[ast.AST]]] = [
            (module.tree, module.tree.body)
        ]
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node, node.body))
        for _, body in scopes:
            yield from self._check_scope(module, body)

    def _check_scope(
        self, module: ParsedModule, body: Sequence[ast.AST]
    ) -> Iterable[Finding]:
        live: Dict[str, List[Tuple[ast.AST, Tuple]]] = {}
        findings: List[Finding] = []

        def visit(stmts, branch, loops):
            for idx, stmt in enumerate(stmts):
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # nested scopes are checked on their own
                if isinstance(stmt, ast.If):
                    exprs(stmt.test, branch, loops)
                    visit(stmt.body, branch + ((id(stmt), 0),), loops)
                    if stmt.orelse:
                        visit(stmt.orelse, branch + ((id(stmt), 1),), loops)
                    elif _body_terminates(stmt.body):
                        # early-return style: the rest of this block is
                        # the implicit else arm — mutually exclusive
                        # with the body, not sequential after it
                        visit(stmts[idx + 1:],
                              branch + ((id(stmt), 1),), loops)
                        return
                    continue
                if isinstance(stmt, ast.Try):
                    visit(stmt.body, branch + ((id(stmt), 0),), loops)
                    for i, h in enumerate(stmt.handlers):
                        visit(h.body, branch + ((id(stmt), i + 1),), loops)
                    visit(stmt.orelse, branch + ((id(stmt), 0),), loops)
                    visit(stmt.finalbody, branch, loops)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    exprs(stmt.iter, branch, loops)
                    kill_target(stmt.target)
                    visit(stmt.body, branch, loops + (stmt,))
                    visit(stmt.orelse, branch, loops)
                    continue
                if isinstance(stmt, ast.While):
                    exprs(stmt.test, branch, loops + (stmt,))
                    visit(stmt.body, branch, loops + (stmt,))
                    visit(stmt.orelse, branch, loops)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        exprs(item.context_expr, branch, loops)
                        if item.optional_vars is not None:
                            kill_target(item.optional_vars)
                    visit(stmt.body, branch, loops)
                    continue
                # plain statement: uses in its expressions happen before
                # its own bindings kill (RHS evaluates first)
                exprs(stmt, branch, loops)
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        kill_target(t)
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    kill_target(stmt.target)

        def kill_target(target):
            for n in ast.walk(target):
                if isinstance(n, ast.Name):
                    live.pop(n.id, None)

        def exprs(node, branch, loops):
            for n in _walk_no_functions(node):
                if isinstance(n, ast.NamedExpr) and isinstance(
                    n.target, ast.Name
                ):
                    live.pop(n.target.id, None)
                if not isinstance(n, ast.Call):
                    continue
                dn = call_name(n)
                if not dn:
                    continue
                parts = dn.split(".")
                if not (
                    len(parts) >= 2
                    and parts[-2] == "random"
                    and parts[-1] in self._CONSUMERS
                    # numpy's Generator API shares the `random` segment
                    # but takes no key; never pair it
                    and parts[0] not in ("np", "numpy")
                ):
                    continue
                if not n.args or not isinstance(n.args[0], ast.Name):
                    continue
                key = n.args[0].id
                prior = live.setdefault(key, [])
                clash = next(
                    (p for p, pb in prior if not _diverged(pb, branch)),
                    None,
                )
                if clash is not None:
                    findings.append(module.finding(
                        self.rule_id, n,
                        f"key {key!r} already consumed by "
                        f"`{ast.unparse(clash)[:60]}` (line "
                        f"{clash.lineno}) and reused here with no "
                        f"split/reassignment between — the two draws "
                        f"are identical streams. split() first.",
                    ))
                elif loops and not any(
                    self._loop_kills(lp, key) for lp in loops
                ):
                    findings.append(module.finding(
                        self.rule_id, n,
                        f"key {key!r} is consumed inside a loop but "
                        f"never split/reassigned within it — every "
                        f"iteration replays the same stream. Derive a "
                        f"per-iteration key (split or fold_in).",
                    ))
                prior.append((n, branch))

        visit(body, (), ())
        return findings

    @staticmethod
    def _loop_kills(loop: ast.AST, name: str) -> bool:
        for n in _walk_no_functions(loop):
            targets: List[ast.AST] = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                targets = [n.target]
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                targets = [n.target]
            elif isinstance(n, ast.NamedExpr):
                targets = [n.target]
            for t in targets:
                if any(
                    isinstance(x, ast.Name) and x.id == name
                    for x in ast.walk(t)
                ):
                    return True
        return False


def _diverged(bp1: Tuple, bp2: Tuple) -> bool:
    """True when two branch paths pass through the same If/Try on
    different arms — the uses are mutually exclusive, not sequential."""
    for (n1, a1), (n2, a2) in zip(bp1, bp2):
        if n1 != n2:
            return False
        if a1 != a2:
            return True
    return False


class DonationAfterUse(Rule):
    """PTD006 — a buffer passed at a donated position, read again later
    in the same scope.

    Motivation: ``donate_argnums`` lets XLA reuse the input buffer for
    an output; afterwards the Python-side array is invalid, and reading
    it is use-after-free that surfaces as garbage values or a runtime
    error depending on backend (XLA:CPU doesn't alias, so the bug hides
    on this box and detonates on the chip). Tracked: callables bound in
    the same module via ``f = jax.jit(g, donate_argnums=(...))`` (or
    ``self._f = jax.jit(self._g, ...)``), call sites passing a bare
    name or dotted attribute at a donated index, then a read of that
    exact expression after the call before any rebinding. Conditional
    ``donate_argnums=(1,) if donate else ()`` counts its indices —
    conservative toward the donating configuration.
    """

    rule_id = "PTD006"
    title = "donation-after-use"
    source_hints = ("donate_argnums",)

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        donating = self._donating_bindings(module)
        if not donating:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            idxs = donating.get(callee or "")
            if not idxs:
                continue
            for i in sorted(idxs):
                if i >= len(node.args):
                    continue
                name = dotted_name(node.args[i])
                if name is None:
                    continue
                read = self._read_after(module, node, name)
                if read is not None:
                    yield module.finding(
                        self.rule_id, read,
                        f"`{name}` was donated to `{callee}` "
                        f"(donate_argnums includes {i}, line "
                        f"{node.lineno}) and is read again here — the "
                        f"donated buffer may already be invalidated "
                        f"(hidden on XLA:CPU, which never aliases; real "
                        f"on the chip). Rebind the callee's result "
                        f"instead.",
                    )

    @staticmethod
    def _donating_bindings(module: ParsedModule) -> Dict[str, Set[int]]:
        out: Dict[str, Set[int]] = {}
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            value = node.value
            if not (
                isinstance(value, ast.Call)
                and EagerScatterHotPath._is_jit_name(call_name(value))
            ):
                continue
            donate_kw = next(
                (
                    kw for kw in value.keywords
                    if kw.arg == "donate_argnums"
                ),
                None,
            )
            if donate_kw is None:
                continue
            idxs = {
                n.value for n in ast.walk(donate_kw.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, int)
                and not isinstance(n.value, bool)
            }
            target = dotted_name(node.targets[0])
            if idxs and target:
                out[target] = idxs
        return out

    def _read_after(
        self, module: ParsedModule, call: ast.Call, name: str
    ) -> Optional[ast.AST]:
        """First load of ``name`` after the donating call's statement,
        before any rebinding — linear source order within the scope."""
        fns = module.enclosing_functions(call)
        scope = fns[0] if fns else module.tree
        call_stmt = self._enclosing_stmt(module, call, scope)
        if call_stmt is None:
            return None
        stmts = self._linear_stmts(scope)
        try:
            start = stmts.index(call_stmt)
        except ValueError:
            return None
        # the call's own statement: assignment targets rebind (kill)
        # before any following statement runs
        if name in self._stores(call_stmt):
            return None
        for stmt in stmts[start + 1:]:
            read = self._first_load(stmt, name)
            stored = name in self._stores(stmt)
            if read is not None:
                return read  # RHS reads evaluate before the rebinding
            if stored:
                return None
        return None

    @staticmethod
    def _enclosing_stmt(
        module: ParsedModule, node: ast.AST, scope: ast.AST
    ) -> Optional[ast.stmt]:
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not scope:
            if isinstance(cur, ast.stmt):
                return cur  # innermost: the assignment holding the call
            cur = module.parent(cur)
        return None

    @staticmethod
    def _linear_stmts(scope: ast.AST) -> List[ast.stmt]:
        out = [
            n for n in _walk_no_functions(scope)
            if isinstance(n, ast.stmt) and n is not scope
        ]
        out.sort(key=lambda s: (s.lineno, s.col_offset))
        return out

    @staticmethod
    def _exprs_of(stmt: ast.stmt) -> Iterable[ast.AST]:
        """The statement's own expressions, not its nested block bodies
        (those are separate statements in the linear walk)."""
        for field, value in ast.iter_fields(stmt):
            vals = value if isinstance(value, list) else [value]
            for v in vals:
                if isinstance(v, ast.expr):
                    yield v
                elif isinstance(v, ast.withitem):
                    yield v.context_expr

    @classmethod
    def _first_load(cls, stmt: ast.stmt, name: str) -> Optional[ast.AST]:
        for expr in cls._exprs_of(stmt):
            for n in _walk_no_functions(expr):
                if dotted_name(n) == name and isinstance(
                    getattr(n, "ctx", None), ast.Load
                ):
                    return n
        return None

    @classmethod
    def _stores(cls, stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = [stmt.target]
        for t in targets:
            for n in ast.walk(t):
                dn = dotted_name(n)
                if dn and isinstance(getattr(n, "ctx", None), ast.Store):
                    out.add(dn)
        return out


ALL_RULES = (
    LockstepCollectives,
    DisarmedCostDiscipline,
    FaultSiteRegistry,
    EagerScatterHotPath,
    PrngKeyReuse,
    DonationAfterUse,
)


def default_rules() -> List[Rule]:
    """One instance of every shipped rule, default configuration."""
    return [cls() for cls in ALL_RULES]
