"""Sharding inference: map parameter-tree paths to PartitionSpecs.

This is the SPMD replacement for the reference wrappers' runtime machinery:
where DDP/FSDP decide *at runtime* which bucket/flat-param a tensor belongs
to, we decide *at trace time* which mesh axes each tensor's dims map onto,
and XLA materializes the data movement. Rules are (path-regex ->
PartitionSpec) pairs, first match wins — the same shape as flax's
logical-axis-rules idiom, but path-based so it works on any pytree
(params, optimizer state, EMA copies) without model cooperation.
"""

from __future__ import annotations

import re
from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.runtime.mesh import current_mesh

# re-export: the in-jit annotation primitive
with_sharding_constraint = jax.lax.with_sharding_constraint

SpecLike = Union[P, Callable[[Tuple[int, ...], Mesh], P], None]


def path_str(path) -> str:
    """Render a jax KeyPath as 'a/b/0/c' for regex matching."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # pragma: no cover - future key types
            parts.append(str(k))
    return "/".join(parts)


class PartitionRules:
    """Ordered (regex, spec) rules; first match wins.

    ``spec`` may be a PartitionSpec, ``None`` (replicate), or a callable
    ``(shape, mesh) -> PartitionSpec`` for shape/mesh-dependent placement.
    """

    def __init__(self, rules: Sequence[Tuple[str, SpecLike]] = ()):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def extended(self, rules: Sequence[Tuple[str, SpecLike]]) -> "PartitionRules":
        """New rule set with ``rules`` taking priority over existing ones."""
        out = PartitionRules()
        out._rules = [(re.compile(p), s) for p, s in rules] + list(self._rules)
        return out

    def spec_for(
        self,
        path: str,
        shape: Tuple[int, ...],
        mesh: Optional[Mesh] = None,
    ) -> Optional[P]:
        for pat, spec in self._rules:
            if pat.search(path):
                if callable(spec):
                    return spec(shape, mesh or current_mesh())
                return spec
        return None


def shard_along(
    axis: Union[str, Tuple[str, ...]],
    *,
    min_size: int = 2,
) -> Callable[[Tuple[int, ...]], P]:
    """Spec factory: shard the largest divisible dim over ``axis``.

    The generic per-tensor analogue of FSDP's flat-param sharding / ZeRO's
    optimizer shard: no model cooperation needed, replicates (returns P())
    when nothing divides evenly. Prefers the largest dim so the collective
    payload per device is smallest.
    """
    axes = (axis,) if isinstance(axis, str) else tuple(axis)

    def spec(shape: Tuple[int, ...], mesh: Mesh) -> P:
        size = int(np.prod([mesh.shape[a] for a in axes]))
        if size == 1:
            return P()
        candidates = [
            i
            for i, d in enumerate(shape)
            if d % size == 0 and d >= max(min_size, size)
        ]
        if not candidates:
            return P()
        best = max(candidates, key=lambda i: shape[i])
        entries: list = [None] * len(shape)
        entries[best] = axes if len(axes) > 1 else axes[0]
        return P(*entries)

    return spec


def stacked(spec: P) -> Callable[[Tuple[int, ...], Mesh], P]:
    """Adapt a per-layer TP spec to scan-stacked params.

    Models compiled with ``nn.scan`` over their blocks carry a leading
    layer dim on every block param ([L, ...]); the layer dim is never
    sharded by TP rules (it is the scan axis). When the tensor has exactly
    one more dim than the spec, prepend None; otherwise (unrolled layout)
    apply the spec as-is — so one rule set serves both layouts.
    """

    def f(shape: Tuple[int, ...], mesh: Mesh) -> P:
        if len(shape) == len(spec) + 1:
            return P(*([None] + list(spec)))
        return spec

    return f


def infer_sharding(
    rules: PartitionRules,
    path: str,
    shape: Tuple[int, ...],
    mesh: Optional[Mesh] = None,
) -> NamedSharding:
    mesh = mesh or current_mesh()
    spec = rules.spec_for(path, shape, mesh)
    # NOTE: a spec whose rank exceeds the leaf's is NOT silently dropped
    # here — for params that is a bad rule and must fail loudly at
    # NamedSharding/jit; rank-reduced OPTIMIZER states are routed around
    # the path rules by infer_opt_tree_shardings' shape validation.
    return NamedSharding(mesh, spec if spec is not None else P())


def infer_tree_shardings(tree, rules: PartitionRules, mesh: Optional[Mesh] = None):
    """Pytree of NamedShardings matching ``tree``'s structure.

    Works on concrete arrays or ShapeDtypeStructs (use with
    ``jax.eval_shape`` to plan placement before materializing anything).
    """
    mesh = mesh or current_mesh()

    def leaf_sharding(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        return infer_sharding(rules, path_str(path), shape, mesh)

    return jax.tree_util.tree_map_with_path(leaf_sharding, tree)


def best_param_suffix(param_paths, path: str) -> Optional[str]:
    """Segment-aligned suffix match, LONGEST param path wins.

    Optimizer-state leaves carry their parameter's path as a suffix
    (``mu/embed/embedding``); plain ``endswith`` would let
    ``dense/kernel`` claim ``.../decoder/dense/kernel`` (or even
    ``cond_dense/kernel``) and mis-classify an exactly-param-shaped
    moment. Shared by :func:`infer_opt_tree_shardings` and the
    planner's memory accounting (autoplan/memory.py), so both route
    shape-mismatched states identically.
    """
    best = None
    for param_path in param_paths:
        if path == param_path or path.endswith("/" + param_path):
            if best is None or len(param_path) > len(best):
                best = param_path
    return best


def infer_opt_tree_shardings(
    opt_state,
    params,
    rules: PartitionRules,
    mesh: Optional[Mesh] = None,
    *,
    mismatch_rules: Optional[PartitionRules] = None,
):
    """Shardings for optimizer state, validated against the PARAM shapes.

    Optimizer-state leaves carry their parameter's path (``mu/embed/
    embedding``), so path rules written for params match them — correct
    exactly when the state leaf is param-shaped (Adam moments). States at
    a DIFFERENT shape (adafactor's factored ``v_row``/``v_col``) must NOT
    inherit the param's path rules: the dims a TP spec names are gone,
    and a ``stacked()`` rule can even mis-apply cleanly when ranks
    collide. Those leaves fall back to ``mismatch_rules`` — typically the
    strategy's shape-generic ``shard_along`` fallback, which is safe on
    any rank — or replicate.
    """
    mesh = mesh or current_mesh()
    param_shapes = {
        path_str(p): tuple(l.shape)
        for p, l in jax.tree_util.tree_leaves_with_path(params)
        if hasattr(l, "shape")
    }

    def leaf_sharding(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        p = path_str(path)
        best = best_param_suffix(param_shapes, p)
        if best is not None and shape != param_shapes[best]:
            if mismatch_rules is None:
                return NamedSharding(mesh, P())
            return infer_sharding(mismatch_rules, p, shape, mesh)
        return infer_sharding(rules, p, shape, mesh)

    return jax.tree_util.tree_map_with_path(leaf_sharding, opt_state)


REPLICATED = PartitionRules([(".*", None)])


def device_put_per_shard(sharding: NamedSharding, x) -> jax.Array:
    """Place one host array as one async ``device_put`` PER addressable
    shard, stitched into the global Array without waiting.

    The feed analogue of the CUDA recipes' per-GPU pinned-memory copies:
    each shard's H2D transfer dispatches independently (no global-array
    staging copy first), so the copies overlap each other — and, driven
    from the DataLoader's prefetch thread, overlap the previous step's
    compute. Returns the same committed sharded Array a plain
    ``jax.device_put(x, sharding)`` would.
    """
    x = np.asarray(x)
    if x.ndim == 0:
        return jax.device_put(x, sharding)
    idx_map = sharding.addressable_devices_indices_map(x.shape)
    if len(idx_map) == 1:
        # one shard -> nothing to overlap; skip the slice-and-stitch
        # Python overhead and take the single C call
        return jax.device_put(x, sharding)
    shards = [jax.device_put(x[idx], d) for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(x.shape, sharding, shards)


def place_global_batch(sharding: NamedSharding, batch, *, local: bool = True):
    """Host batch pytree -> jax Arrays placed under ``sharding``.

    Single process: a plain sharded ``device_put``. Multi-process (pod),
    where no process can address every device:

    * ``local=True`` — each controller passes its PROCESS-LOCAL contiguous
      block of the global batch (the DistributedSampler contract);
      assembled with ``make_array_from_process_local_data``, which
      validates the blocks tile the global shape. No cross-host transfer.
    * ``local=False`` — every controller passes the FULL global batch;
      the global array is built by slicing this process's full copy per
      device. (Feeding a full copy through the ``local`` path would
      silently concatenate the copies into a world-times-duplicated
      batch — the one-true-helper exists so every caller gets this right.)
    """
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda x: device_put_per_shard(sharding, x)
            if isinstance(x, np.ndarray) and x.ndim
            else jax.device_put(x, sharding),
            batch,
        )

    def place(x):
        x = np.asarray(x)
        if local:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )

    return jax.tree_util.tree_map(place, batch)
