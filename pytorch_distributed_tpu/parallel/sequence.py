"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context training shards the *sequence* axis over the ``sp`` mesh axis.
Everything in a transformer is pointwise over sequence except attention, so
XLA's sharding propagation handles the whole model except the softmax over
keys — which, left to the compiler, becomes an all-gather of full K/V
(O(S) memory per chip again). The two standard fixes, both implemented
here as ``shard_map`` collectives over ``sp``:

* **Ring attention** (Liu et al. 2023 pattern): keep Q local, rotate K/V
  shards around the ring with ``lax.ppermute``, combining per-step partial
  attention with the online-softmax rule. Peak memory O(S/sp); the
  rotation overlaps with the block computation on ICI.
* **Ulysses / all-to-all** (DeepSpeed-Ulysses pattern): ``lax.all_to_all``
  re-shards [B, S/sp, H, D] -> [B, S, H/sp, D], runs ordinary (flash)
  attention per head subset, and transforms back. Cheaper collectives for
  moderate S; requires heads divisible by sp.

The reference (a DDP/FSDP recipe collection, SURVEY.md §2) has no
sequence parallelism; this is a first-class capability of the TPU-native
framework (long-context training is mesh-axis cheap under SPMD).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from pytorch_distributed_tpu.runtime.compat import shard_map

from pytorch_distributed_tpu.runtime.mesh import current_mesh, data_axes

_NEG_INF = -1e30


def _block_attn_parts(
    q: jnp.ndarray,  # [B, S, Hq, D] local queries
    k: jnp.ndarray,  # [B, T, Hkv, D] one ring step's keys
    v: jnp.ndarray,
    q_pos: jnp.ndarray,  # [S] global positions of local queries
    k_pos: jnp.ndarray,  # [T] global positions of this step's keys
    causal: bool,
    scale: float,
    window=None,
    bias=None,  # [Hkv*G(local), S, T] — already head-sliced by caller
):
    """Unnormalized block attention: (o=[B,S,Hkv,G,D] f32, m, l=[B,Hkv,G,S,1]).

    ``window``: sliding-window band on top of causal — the ring carries
    TRUE GLOBAL positions for both sides, so the band is exact across
    shard boundaries (slot-index banding would be wrong here).
    """
    B, S, Hq, D = q.shape
    _, T, Hkv, _ = k.shape
    G = Hq // Hkv
    qg = q.reshape(B, S, Hkv, G, D)
    logits = (
        jnp.einsum("bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32)
        * scale
    )  # [B, Hkv, G, S, T]
    if bias is not None:
        logits = logits + bias.reshape(Hkv, G, S, T)[None].astype(
            jnp.float32
        )
    mask = None
    if causal or window is not None:
        mask = q_pos[:, None] >= k_pos[None, :]  # [S, T]
        if window is not None:
            mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
        logits = jnp.where(mask[None, None, None], logits, _NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [B,Hkv,G,S,1]
    p = jnp.exp(logits - m)
    if mask is not None:
        # a fully-masked block has m == -inf and exp(0) == 1 everywhere;
        # re-apply the mask on p so it contributes nothing
        p = jnp.where(mask[None, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgst,btkd->bskgd", p, v.astype(jnp.float32))
    return o, m, l


def _ring_attention_local(
    q, k, v, *, axis_name: str, causal: bool, scale: float, window=None,
    bias_fn=None,
):
    """Runs inside shard_map: q/k/v are the local sequence shards."""
    B, S, Hq, D = q.shape
    T = k.shape[1]
    Hkv = k.shape[2]
    G = Hq // Hkv
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = my * S + jnp.arange(S)
    # heads may additionally be sharded over tp: bias_fn returns GLOBAL
    # heads, so slice this chip's subset once
    tp_i = lax.axis_index("tp")
    h_loc = Hq

    def block_bias(k_pos):
        if bias_fn is None:
            return None
        full = bias_fn(q_pos, k_pos)  # [Hq_global, S, T]
        return lax.dynamic_slice_in_dim(full, tp_i * h_loc, h_loc, 0)

    def accumulate(t, acc, k_t, v_t):
        o_acc, m_acc, l_acc = acc
        src = (my - t) % n  # whose K/V shard we hold at step t
        k_pos = src * T + jnp.arange(T)
        o_t, m_t, l_t = _block_attn_parts(
            q, k_t, v_t, q_pos, k_pos, causal, scale, window,
            block_bias(k_pos),
        )
        m_new = jnp.maximum(m_acc, m_t)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_t - m_new)
        l_new = l_acc * alpha + l_t * beta
        # o carries [B,S,Hkv,G,D]; scale factors are [B,Hkv,G,S,1]
        scale_o = lambda o, f: o * f[..., 0].transpose(0, 3, 1, 2)[..., None]
        o_new = scale_o(o_acc, alpha) + scale_o(o_t, beta)
        return o_new, m_new, l_new

    def step(t, carry):
        acc, k_t, v_t = carry
        acc = accumulate(t, acc, k_t, v_t)
        # rotate K/V to the next rank (overlaps with the next block's matmul)
        k_next = lax.ppermute(k_t, axis_name, perm)
        v_next = lax.ppermute(v_t, axis_name, perm)
        return acc, k_next, v_next

    o0 = jnp.zeros((B, S, Hkv, G, D), jnp.float32)
    m0 = jnp.full((B, Hkv, G, S, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, S, 1), jnp.float32)
    # n-1 compute+rotate steps, then a final compute on the last-held
    # shard — no rotation whose result nobody reads
    acc, k_last, v_last = lax.fori_loop(0, n - 1, step, ((o0, m0, l0), k, v))
    o, m, l = accumulate(n - 1, acc, k_last, v_last)
    l_bskg = l[..., 0].transpose(0, 3, 1, 2)[..., None]  # [B,S,Hkv,G,1]
    out = o / jnp.where(l_bskg > 0, l_bskg, 1.0)
    return out.reshape(B, S, Hq, D).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, S, Hq, D] globally; S sharded over ``axis``
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    axis: str = "sp",
    mesh: Optional[Mesh] = None,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    bias_fn=None,
) -> jnp.ndarray:
    """Exact attention with K/V rotated around the ``axis`` ring.

    Call on *global* arrays under jit; shard_map partitions S over ``axis``
    (batch over the data axes, heads over ``tp``) and the ring keeps every
    chip's K/V working set at S/sp. ``window`` adds the sliding-window
    band (Mistral) over true global positions — exact across shard
    boundaries.
    """
    mesh = mesh or current_mesh()
    if window is not None and window <= 0:
        raise ValueError(f"window must be positive, got {window}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(data_axes(), axis, "tp", None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis, causal=causal,
            scale=scale, window=window, bias_fn=bias_fn,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, inner):
    # (bias_fn head slicing happens in the inner closure — it needs the
    # sp index, bound here by shard_map)
    """all_to_all S<->H re-shard; runs inside shard_map."""
    # [B, S/sp, H, D] -> [B, S, H/sp, D]: after the re-shard each chip
    # holds the FULL sequence for its head subset, so any sequence-wise
    # mask (causal, sliding window) applies exactly as in the unsharded
    # op — the inner closure carries it
    a2a = lambda x: lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)
    inv = lambda x: lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)
    out = inner(a2a(q), a2a(k), a2a(v), causal)
    return inv(out)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    axis: str = "sp",
    mesh: Optional[Mesh] = None,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    bias_fn=None,
) -> jnp.ndarray:
    """DeepSpeed-Ulysses-style sequence parallelism: two all-to-alls around
    an ordinary full-sequence attention on a head subset. Heads (q and kv)
    must be divisible by the ``axis`` size. ``window`` = sliding-window
    band (each chip sees the full sequence post-re-shard, so the band
    applies exactly). ``bias_fn`` is REFUSED here: the fn returns
    GLOBAL heads, so each chip would materialize [Hq_global, S, S]
    before slicing its subset — a tp*sp-factor memory overshoot in
    exactly the long-S regime SP exists for; ring evaluates the bias
    per block at [Hq_local, S/sp, S/sp] instead. Use ``impl="ring"``
    for relative-bias models."""
    if bias_fn is not None:
        raise NotImplementedError(
            "bias_fn under ulysses would materialize the full "
            "global-head [S, S] bias on every chip before head-slicing "
            "— use sequence_parallel(impl='ring'), which evaluates the "
            "bias per block from global positions"
        )
    mesh = mesh or current_mesh()
    sp = mesh.shape[axis]
    tp = mesh.shape.get("tp", 1)
    # heads are already split over tp by the spec; sp divides what remains
    Hq, Hkv = q.shape[2] // tp, k.shape[2] // tp
    if sp > 1 and (Hq == 0 or Hkv == 0 or Hq % sp or Hkv % sp):
        raise ValueError(
            f"ulysses needs per-tp-shard heads divisible by sp={sp}; got "
            f"q={Hq}, kv={Hkv} after tp={tp} "
            f"(use ring_attention for head-indivisible configs)"
        )

    def inner(q, k, v, causal):
        # The post-all-to-all local attention (full sequence, head subset)
        # picks the flash kernel when selected. NOT the attention()
        # dispatcher: sequence-parallel mode is still active here, and
        # re-entering it would recurse into ulysses with the local
        # (already head-sharded) shapes.
        from pytorch_distributed_tpu.ops.attention import (
            dot_product_attention,
            get_attention_impl,
        )

        if (
            window is None and scale is None
            and get_attention_impl() == "flash"
        ):
            from pytorch_distributed_tpu.ops.flash_attention import (
                flash_attention,
            )

            return flash_attention(q, k, v, causal=causal)
        return dot_product_attention(
            q, k, v, causal=causal, window=window, scale=scale
        )

    spec = P(data_axes(), axis, "tp", None)
    fn = shard_map(
        functools.partial(
            _ulysses_local, axis_name=axis, causal=causal, inner=inner
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# --------------------------------------------------------------------------
# model-transparent activation: ops.attention.attention() consults this
# --------------------------------------------------------------------------

_SEQ_MODE: Tuple[Optional[str], str] = (None, "ring")  # (axis or None, impl)


def enable_sequence_parallel(axis: str = "sp", impl: str = "ring") -> None:
    """Route all model attention through sequence-parallel attention.

    With this set, transformer models need no code changes: activations
    stay sequence-sharded end-to-end (XLA propagates the ``sp`` sharding
    through the pointwise/matmul ops) and the attention dispatcher wraps
    the only cross-sequence op in ring/ulysses shard_map.
    """
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence-parallel impl {impl!r}")
    global _SEQ_MODE
    if _SEQ_MODE != (axis, impl):
        _SEQ_MODE = (axis, impl)
        # jit caches don't key on this mode; retrace compiled steps
        jax.clear_caches()


def disable_sequence_parallel() -> None:
    global _SEQ_MODE
    if _SEQ_MODE[0] is not None:
        _SEQ_MODE = (None, "ring")
        jax.clear_caches()


@contextlib.contextmanager
def sequence_parallel(axis: str = "sp", impl: str = "ring"):
    """Context manager form of enable/disable_sequence_parallel."""
    prev = _SEQ_MODE
    enable_sequence_parallel(axis, impl)
    try:
        yield
    finally:
        if prev[0] is None:
            disable_sequence_parallel()
        else:
            enable_sequence_parallel(*prev)


def sequence_parallel_mode() -> Tuple[Optional[str], str]:
    return _SEQ_MODE


def sequence_parallel_attention(
    q, k, v, *, causal: bool, window=None, scale=None, bias_fn=None
) -> jnp.ndarray:
    axis, impl = _SEQ_MODE
    assert axis is not None
    if impl == "ring":
        return ring_attention(q, k, v, causal=causal, axis=axis,
                              window=window, scale=scale, bias_fn=bias_fn)
    return ulysses_attention(q, k, v, causal=causal, axis=axis,
                             window=window, scale=scale, bias_fn=bias_fn)
