"""Gradient synchronisation for the true multi-process (hostring) path.

The reference's DDP wraps the model and averages gradients across ranks
with bucketed NCCL/gloo allreduce during backward (BASELINE.json:5,
SURVEY.md §2/§3). Under single-controller SPMD that role is played by
sharding propagation — gradients of replicated params come out of jit
already psum-med, so there is nothing to do.

Under the *multi-process* hostring backend (one OS process per rank, the
reference's gloo smoke path) gradients really are per-rank and must be
averaged explicitly. ``sync_grads`` is that averaging: a single host
callback per step that ring-allreduces every gradient leaf through the
native shm backend. It is inserted by ``build_train_step`` between the
gradient computation and ``apply_gradients`` — the same position as the
reference's backward-hook allreduce, minus the bucketing (one callback
already moves all leaves; shm "bandwidth" is a memcpy).

Lockstep safety: every rank traces the same step function, so the flat
leaf order — and therefore the allreduce order inside the callback — is
identical across ranks.
"""

from __future__ import annotations

import contextlib
import os

import jax
import numpy as np
from jax import tree_util


def is_multiprocess() -> bool:
    """True when the current process group is per-rank OS processes."""
    from pytorch_distributed_tpu.runtime import distributed as dist

    ring = dist.multiprocess_ring()
    return ring is not None and ring.world_size > 1


@contextlib.contextmanager
def no_sync():
    """torch DDP's ``model.no_sync()`` — a documented no-op here.

    torch needs it because DDP's backward hooks allreduce EVERY backward;
    accumulation must suppress them on non-boundary microbatches. In this
    framework gradient accumulation runs inside the jitted step
    (``build_train_step(accum_steps=...)``) and ``sync_grads`` is invoked
    exactly once per optimizer step, after accumulation — there is no
    per-microbatch sync to suppress. Provided so ported scripts keep
    their shape.
    """
    yield


_COMPRESS_DTYPES = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                    "fp16": "float16", "float16": "float16"}

#: float leaves below this element count coalesce into one flat
#: allreduce per wire dtype (also the q8 path's exact-f32 threshold —
#: one number, one meaning; the canonical constant now lives in
#: parallel/overlap.py, THE one place the ship grouping is computed)
from pytorch_distributed_tpu.parallel.overlap import (  # noqa: E402
    COALESCE_MAX_ELEMS as _COALESCE_MAX_ELEMS,
)


def _overlap_default() -> bool:
    """The bucketed pipeline is the default sync engine; set
    ``PTD_GRAD_SYNC=legacy`` for the pre-r14 single-callback path (the
    bench's synchronous A/B baseline)."""
    return os.environ.get("PTD_GRAD_SYNC", "overlap") != "legacy"


def sync_grads(grads, compress: str | None = None, *,
               overlap: bool | None = None):
    """Average gradient pytree across ranks (no-op unless multi-process).

    Safe to call inside jit: the collective runs as ONE ordered io_callback
    through the native hostring backend. ``io_callback(ordered=True)`` is
    mandatory — a collective is a side-effecting, peer-synchronised call,
    and ``pure_callback`` is documented as freely elidable/duplicable,
    either of which would desync the ring and hang the other ranks.

    ``compress`` ("bf16"/"fp16"/"int8"): gradient compression for the
    wire. Halves: f32/f64 leaves are cast to the half dtype before the
    collective and back after, halving (quartering for f64) the
    shm/network bytes; the ring ships halves natively and still
    accumulates each element in f32, dividing before the single rounding
    (native/hostring.cpp) — the NCCL fp16/bf16 contract. "int8":
    EQuARX-style block quantization in the ring itself (~4x fewer bytes,
    one f32 scale per 256 elements, f32 accumulation); leaves too small
    to amortize the scales (< 4096 elems) go exact-f32.

    Sub-4096-element float leaves are COALESCED — grouped by their
    on-the-wire dtype (after any ``compress`` cast, so bf16-compressed
    runs coalesce too) into one flat allreduce per dtype: a
    transformer's dozens of tiny bias/norm leaves each paid the ring's
    full barrier cadence; one collective now moves them all (the
    ``comm.all_reduce`` span counts prove the drop). Per-element
    reduction semantics are unchanged — the ring reduces element-wise
    (halves still accumulate in f32 and round once) — but an element's
    position picks which rank's segment accumulates it, so the
    summation ORDER can rotate: bit-identical to per-leaf at world 2
    (two-operand fp addition commutes), last-ulp differences possible
    at world > 2. Cross-rank bit-identity (the DDP invariant) holds
    regardless. The whole callback runs under a ``comm.sync_grads``
    span recording leaf count and pre-/post-compression wire bytes
    when tracing is armed.

    ``overlap`` (default on; ``PTD_GRAD_SYNC=legacy`` or
    ``overlap=False`` restores the pre-r14 path): the callback routes
    through the bucketed pipeline (``parallel/overlap.py``) — leaves
    pack into reusable staging and reduce IN PLACE on a dedicated comm
    thread, pack(b+1) ∥ ring-reduce(b), with ``comm.sync_drain`` /
    ``comm.sync.exposed_s`` recording how much comm the main thread
    actually blocked on. Per-item ring calls, element layout, and
    grouping are IDENTICAL to the legacy path (shared plan code), so
    the result is bit-identical to it; with ``compress="int8"`` the
    pipeline additionally keeps per-leaf error-feedback residuals
    (ROADMAP item 1) — each sync ships ``g + e`` and carries the local
    quantization error into the next step. The legacy path stays
    residual-free (it IS the pre-r14 behavior).
    """
    import jax.numpy as jnp
    from jax.experimental import io_callback

    from pytorch_distributed_tpu.parallel.overlap import (
        ShipPlan,
        get_engine,
    )
    from pytorch_distributed_tpu.runtime import distributed as dist
    from pytorch_distributed_tpu.runtime import tracing
    from pytorch_distributed_tpu.runtime.hostring import (
        algo_wire_bytes,
        q8_wire_payload,
    )

    ring = dist.multiprocess_ring()
    if ring is None or ring.world_size == 1:
        return grads
    if overlap is None:
        overlap = _overlap_default()
    leaves, treedef = tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    n_leaves = len(leaves)
    pre_bytes = sum(
        int(np.prod(np.shape(l), dtype=np.int64))
        * jnp.dtype(l.dtype).itemsize
        for l in leaves
    )
    orig_dtypes = None
    quantize = False
    if compress == "int8":
        quantize = True  # in-ring block quantization; dtypes unchanged
    elif compress is not None:
        if compress not in _COMPRESS_DTYPES:
            raise ValueError(
                f"unknown grad compression {compress!r}; "
                f"one of {sorted(set(_COMPRESS_DTYPES)) + ['int8']}"
            )
        cdt = jnp.dtype(_COMPRESS_DTYPES[compress])
        orig_dtypes = tuple(l.dtype for l in leaves)
        leaves = [
            l.astype(cdt) if l.dtype in (jnp.float32, jnp.float64) else l
            for l in leaves
        ]

    # ONE source of grouping truth: the ship plan (parallel/overlap.py)
    # computes the coalesce groups and q8 flags for both engines, so the
    # pipelined path can never drift from the legacy grouping
    # grouping only (coalesce + q8 flags): leaves ship WHOLE through the
    # callback — the engine applies its slot-aligned chunking host-side
    plan = ShipPlan(
        [(np.shape(l), np.dtype(l.dtype)) for l in leaves],
        quantize=quantize, chunk_bytes=1 << 62,
    )
    sizes = plan.sizes
    ship = []
    for item in plan.items:
        if item.kind == "flat":
            ship.append(jnp.concatenate(
                [leaves[i].reshape(-1) for i in item.leaf_ids]
            ))
        else:
            ship.append(leaves[item.leaf_ids[0]])
    q_flags = tuple(item.q8 for item in plan.items)
    ship_shapes = tuple(
        jax.ShapeDtypeStruct(np.shape(l), l.dtype) for l in ship
    )
    wire_bytes = sum(
        algo_wire_bytes(
            "all_reduce_q8" if qf else "all_reduce",
            q8_wire_payload(int(np.prod(s.shape, dtype=np.int64)))
            if qf else int(np.prod(s.shape, dtype=np.int64))
            * np.dtype(s.dtype).itemsize,
            ring.world_size,
        )
        for s, qf in zip(ship_shapes, q_flags)
    )
    span_args = {
        "leaves": n_leaves,
        "collectives": len(ship),
        "coalesced_leaves": len(plan.coalesced),
        "pre_bytes": int(pre_bytes),
        "wire_bytes": int(wire_bytes),
        "world": ring.world_size,
        "overlap": bool(overlap),
    }

    def _allreduce_all(*arrs):
        tr = tracing._tracer
        span = (
            tracing._NULL_SPAN if tr is None
            else tracing._Span(tr, "comm.sync_grads", span_args)
        )
        with span:
            if overlap:
                out, _stats = get_engine(ring).reduce_shipped(
                    arrs, q_flags
                )
                return tuple(out)
            out = []
            for a, qf in zip(arrs, q_flags):
                a = np.asarray(a)
                if qf:
                    out.append(ring.all_reduce_q8(a, op="avg"))
                else:
                    out.append(ring.all_reduce(a, op="avg"))
            return tuple(out)

    shipped = io_callback(
        _allreduce_all, ship_shapes, *ship, ordered=True
    )
    synced = [None] * n_leaves
    for item, arr in zip(plan.items, shipped):
        if item.kind == "flat":
            off = 0
            for i in item.leaf_ids:
                synced[i] = arr[off:off + sizes[i]].reshape(
                    np.shape(leaves[i])
                )
                off += sizes[i]
        else:
            synced[item.leaf_ids[0]] = arr
    synced = tuple(synced)
    if orig_dtypes is not None:
        synced = tuple(
            s.astype(d) if s.dtype != d else s
            for s, d in zip(synced, orig_dtypes)
        )
    return tree_util.tree_unflatten(treedef, synced)


def reset_error_feedback() -> None:
    """Drop the q8 error-feedback residuals (a fresh training run on
    the same process — stale residuals would leak the old run's last
    gradient into the new run's first sync)."""
    from pytorch_distributed_tpu.parallel import overlap as _ov

    if _ov._ENGINE is not None:
        _ov._ENGINE.reset_residuals()
