"""Gradient synchronisation for the true multi-process (hostring) path.

The reference's DDP wraps the model and averages gradients across ranks
with bucketed NCCL/gloo allreduce during backward (BASELINE.json:5,
SURVEY.md §2/§3). Under single-controller SPMD that role is played by
sharding propagation — gradients of replicated params come out of jit
already psum-med, so there is nothing to do.

Under the *multi-process* hostring backend (one OS process per rank, the
reference's gloo smoke path) gradients really are per-rank and must be
averaged explicitly. ``sync_grads`` is that averaging: a single host
callback per step that ring-allreduces every gradient leaf through the
native shm backend. It is inserted by ``build_train_step`` between the
gradient computation and ``apply_gradients`` — the same position as the
reference's backward-hook allreduce, minus the bucketing (one callback
already moves all leaves; shm "bandwidth" is a memcpy).

Lockstep safety: every rank traces the same step function, so the flat
leaf order — and therefore the allreduce order inside the callback — is
identical across ranks.
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np
from jax import tree_util


def is_multiprocess() -> bool:
    """True when the current process group is per-rank OS processes."""
    from pytorch_distributed_tpu.runtime import distributed as dist

    ring = dist.multiprocess_ring()
    return ring is not None and ring.world_size > 1


@contextlib.contextmanager
def no_sync():
    """torch DDP's ``model.no_sync()`` — a documented no-op here.

    torch needs it because DDP's backward hooks allreduce EVERY backward;
    accumulation must suppress them on non-boundary microbatches. In this
    framework gradient accumulation runs inside the jitted step
    (``build_train_step(accum_steps=...)``) and ``sync_grads`` is invoked
    exactly once per optimizer step, after accumulation — there is no
    per-microbatch sync to suppress. Provided so ported scripts keep
    their shape.
    """
    yield


_COMPRESS_DTYPES = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                    "fp16": "float16", "float16": "float16"}


def sync_grads(grads, compress: str | None = None):
    """Average gradient pytree across ranks (no-op unless multi-process).

    Safe to call inside jit: the collective runs as ONE ordered io_callback
    through the native hostring backend. ``io_callback(ordered=True)`` is
    mandatory — a collective is a side-effecting, peer-synchronised call,
    and ``pure_callback`` is documented as freely elidable/duplicable,
    either of which would desync the ring and hang the other ranks.

    ``compress`` ("bf16"/"fp16"/"int8"): gradient compression for the
    wire. Halves: f32/f64 leaves are cast to the half dtype before the
    collective and back after, halving (quartering for f64) the
    shm/network bytes; the ring ships halves natively and still
    accumulates each element in f32, dividing before the single rounding
    (native/hostring.cpp) — the NCCL fp16/bf16 contract. "int8":
    EQuARX-style block quantization in the ring itself (~4x fewer bytes,
    one f32 scale per 256 elements, f32 accumulation); leaves too small
    to amortize the scales (< 4096 elems) go exact-f32.
    """
    import jax.numpy as jnp
    from jax.experimental import io_callback

    from pytorch_distributed_tpu.runtime import distributed as dist

    ring = dist.multiprocess_ring()
    if ring is None or ring.world_size == 1:
        return grads
    leaves, treedef = tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    orig_dtypes = None
    quantize = False
    if compress == "int8":
        quantize = True  # in-ring block quantization; dtypes unchanged
    elif compress is not None:
        if compress not in _COMPRESS_DTYPES:
            raise ValueError(
                f"unknown grad compression {compress!r}; "
                f"one of {sorted(set(_COMPRESS_DTYPES)) + ['int8']}"
            )
        cdt = jnp.dtype(_COMPRESS_DTYPES[compress])
        orig_dtypes = tuple(l.dtype for l in leaves)
        leaves = [
            l.astype(cdt) if l.dtype in (jnp.float32, jnp.float64) else l
            for l in leaves
        ]
    shapes = tuple(
        jax.ShapeDtypeStruct(np.shape(l), l.dtype) for l in leaves
    )

    def _allreduce_all(*arrs):
        out = []
        for a in arrs:
            a = np.asarray(a)
            if quantize and a.dtype == np.float32 and a.size >= 4096:
                out.append(ring.all_reduce_q8(a, op="avg"))
            else:
                out.append(ring.all_reduce(a, op="avg"))
        return tuple(out)

    synced = io_callback(_allreduce_all, shapes, *leaves, ordered=True)
    if orig_dtypes is not None:
        synced = tuple(
            s.astype(d) if s.dtype != d else s
            for s, d in zip(synced, orig_dtypes)
        )
    return tree_util.tree_unflatten(treedef, synced)
