"""Bucketed, pipelined gradient synchronisation for the hostring path.

The reference DDP's signature performance mechanic is bucketed allreduce
that runs *during* backward. Through round 13 this repo reproduced it
"minus the bucketing": ``ddp.sync_grads`` was one synchronous host
callback that stalled the step while every leaf rode the ring, paying a
full functional copy (a cold allocation + memcpy of the whole payload)
before the first shm byte moved. BENCH_r04/r05 drove the ring itself to
its touched-bytes memcpy bound, so the remaining levers are exactly the
two this module implements:

* **touch fewer bytes** — leaves are packed once into *reusable* staging
  buffers and reduced IN PLACE (``hr_allreduce`` writes the result where
  the contribution already sits). The legacy path's per-call
  ``a.copy()`` — measured at roughly the cost of the ring itself on this
  box, because a cold 6 MB allocation faults every page — is gone.
* **hide the rest** — a dedicated comm thread drains a deterministic
  bucket queue while the main thread keeps packing (and, in the
  ``overlap_accum`` trainer mode, keeps fetching/accumulating microbatch
  gradients and the caller keeps staging its next batch). The 3-stage
  shape is the issue's D2H(b+1) ∥ ring-reduce(b) ∥ H2D(b−1) pipeline.

Determinism and lockstep safety are BY CONSTRUCTION: every rank builds
the same :class:`ShipPlan` from the same leaf specs (the jit trace is
identical across ranks), enqueues the same buckets in the same fixed
order, and the comm thread drains the queue FIFO — so the sequence of
ring collectives is identical on every rank regardless of per-rank
timing, which is what ``trace_merge``'s k-th-occurrence alignment and
the PTD001 lint rule continue to verify. Per-item reduction is the SAME
``hr_allreduce`` call on the same element layout as the legacy path, so
results are bit-identical to it (and the coalescing grouping is shared
code, not a reimplementation).

Honest limits (DESIGN.md §19): on a 1-core box the comm thread cannot
create wall time — compute and memcpy serialize on the one core, and the
measured win comes from the touched-byte reduction above. What the
pipeline buys here is *structure*: the exposed/hidden accounting below
measures how much of the comm wall ran while other work was in flight,
which is the quantity that turns into real hiding the moment transfer,
reduction, and compute stop sharing a core.

Error feedback (ROADMAP item 1's missing half): the q8 path keeps a
per-item residual — each sync quantizes ``g + e`` and stores
``e' = (g + e) − Q(g + e)`` with ``Q`` a numpy replication of the native
block quantizer (``native/hostring.cpp``: 256-elem blocks, scale
``amax/127``, round-half-away) — so the quantization error is carried
into the next step instead of being dropped (EQuARX, arxiv 2506.17615).
The second-stage requantization of the *reduced* segment is not
compensated (its error is only visible to the segment owner); the
loss-curve parity test bounds the total.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pytorch_distributed_tpu.runtime import faults, tracing
from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: float leaves below this element count coalesce into one flat wire
#: buffer per dtype (also the q8 exact-f32 threshold) — ONE number with
#: one meaning, shared with parallel/ddp.py which re-exports it
COALESCE_MAX_ELEMS = 4096

#: default pipeline bucket target — matches the ring's slot size, so one
#: bucket is roughly one slot-chunk of ring work (override with
#: PTD_GRAD_BUCKET_BYTES or the ``bucket_bytes=`` argument)
DEFAULT_BUCKET_BYTES = 4 << 20

_COALESCE_DTYPES = [np.dtype(np.float32), np.dtype(np.float64),
                    np.dtype(np.float16)]
try:
    import ml_dtypes as _ml_dtypes

    _COALESCE_DTYPES.append(np.dtype(_ml_dtypes.bfloat16))
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    pass

_Q8_BLOCK = 256  # must match kQBlock in native/hostring.cpp


# --------------------------------------------------------------------------
# The ship plan: the ONE place the coalescing/bucketing structure lives.
# --------------------------------------------------------------------------
class ShipItem:
    """One on-the-wire unit — exactly one ring collective.

    ``kind == "flat"``: a coalesced group of sub-:data:`COALESCE_MAX_ELEMS`
    float leaves sharing a wire dtype (the legacy coalescing, unchanged —
    the issue's "degenerate first bucket"). ``kind == "solo"``: one whole
    leaf. ``kind == "chunk"``: a slot-aligned slice of an oversized leaf
    — ``hr_allreduce`` processes payloads in slot-sized chunks with
    segment ownership computed PER CHUNK, so splitting at exactly the
    ring's slot boundaries issues the identical per-element reduce the
    unsplit call would have run (bit-identical by construction), while
    giving the pipeline slot-granular stagger.

    Every item addresses a slice ``[start, start+elems)`` of one parent
    staging buffer (``parent`` indexes ``ShipPlan.buffers``); chunks of
    one leaf share a parent, so the reduced leaf is contiguous with no
    reassembly copy.
    """

    __slots__ = ("kind", "leaf_ids", "dtype", "elems", "nbytes",
                 "q8", "offsets", "parent", "start")

    def __init__(self, kind: str, leaf_ids: Tuple[int, ...],
                 dtype, elems: int, q8: bool, parent: int,
                 start: int = 0, offsets: Tuple[int, ...] = ()):
        self.kind = kind
        self.leaf_ids = leaf_ids
        self.dtype = np.dtype(dtype)
        self.elems = int(elems)
        self.nbytes = self.elems * self.dtype.itemsize
        self.q8 = bool(q8)
        self.parent = parent
        self.start = int(start)
        self.offsets = offsets  # flat: per-leaf start offsets (elements)


def _bucketize(items: Sequence[ShipItem], bucket_bytes: int
               ) -> List[List[int]]:
    """Size-targeted buckets over CONSECUTIVE items (fixed order): close
    a bucket when the next item would cross the target; an oversized
    item rides alone."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for j, it in enumerate(items):
        if cur and cur_bytes + it.nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(j)
        cur_bytes += it.nbytes
    if cur:
        buckets.append(cur)
    return buckets


def _chunk_items(kind_leaf: int, dtype: np.dtype, elems: int, q8: bool,
                 parent: int, chunk_bytes: int) -> List[ShipItem]:
    """Split one leaf/array into slot-aligned chunk items (or one solo
    item when it fits). q8 items never split: the native q8 path chunks
    at its own scale-adjusted stride, so a python-side split would
    change the block scales — the f32 path's slot chunking is the only
    one this mirrors exactly."""
    chunk_elems = max(chunk_bytes // dtype.itemsize, 1)
    if q8 or elems <= chunk_elems:
        return [ShipItem("solo", (kind_leaf,), dtype, elems, q8, parent)]
    out = []
    for start in range(0, elems, chunk_elems):
        n = min(chunk_elems, elems - start)
        out.append(ShipItem("chunk", (kind_leaf,), dtype, n, False,
                            parent, start=start))
    return out


class ShipPlan:
    """Deterministic partition of a leaf list into ship items + buckets.

    Built from abstract specs only (shapes/dtypes), so every rank —
    tracing the same step function — derives the identical plan, which
    is what makes the bucket queue's collective order lockstep-safe.
    ``chunk_bytes`` MUST equal the ring's ``slot_bytes`` for the
    bit-identity argument above (the engine passes it).
    """

    def __init__(self, specs: Sequence[Tuple[Tuple[int, ...], np.dtype]],
                 *, quantize: bool = False,
                 bucket_bytes: Optional[int] = None,
                 chunk_bytes: Optional[int] = None):
        if bucket_bytes is None:
            bucket_bytes = int(os.environ.get(
                "PTD_GRAD_BUCKET_BYTES", DEFAULT_BUCKET_BYTES
            ))
        self.bucket_bytes = max(int(bucket_bytes), 1)
        self.chunk_bytes = int(chunk_bytes or DEFAULT_BUCKET_BYTES)
        self.specs = [(tuple(s), np.dtype(d)) for s, d in specs]
        sizes = [int(np.prod(s, dtype=np.int64)) for s, _ in self.specs]
        self.sizes = sizes
        # the legacy coalescing, verbatim: group small float leaves by
        # their ON-THE-WIRE dtype; a group needs >= 2 members
        by_dtype: Dict[str, List[int]] = {}
        for i, (_, dt) in enumerate(self.specs):
            if sizes[i] < COALESCE_MAX_ELEMS and any(
                dt == d for d in _COALESCE_DTYPES
            ):
                by_dtype.setdefault(dt.name, []).append(i)
        groups = [idxs for _, idxs in sorted(by_dtype.items())
                  if len(idxs) >= 2]
        self.coalesced = {i for g in groups for i in g}
        solo = [i for i in range(len(self.specs)) if i not in self.coalesced]
        items: List[ShipItem] = []
        buffers: List[Tuple[int, np.dtype]] = []  # (elems, dtype)
        # flats FIRST: the degenerate first bucket(s)
        for g in groups:
            offs, total = [], 0
            dt = self.specs[g[0]][1]
            for i in g:
                offs.append(total)
                total += sizes[i]
            items.append(ShipItem("flat", tuple(g), dt, total, False,
                                  len(buffers), offsets=tuple(offs)))
            buffers.append((total, dt))
        for i in solo:
            _, dt = self.specs[i]
            q8 = (quantize and dt == np.dtype(np.float32)
                  and sizes[i] >= COALESCE_MAX_ELEMS)
            items.extend(_chunk_items(i, dt, sizes[i], q8,
                                      len(buffers), self.chunk_bytes))
            buffers.append((sizes[i], dt))
        self.items = items
        self.buffers = buffers
        self.buckets = _bucketize(items, self.bucket_bytes)

    def signature(self) -> tuple:
        return (tuple(self.specs),
                tuple(it.q8 for it in self.items), self.bucket_bytes,
                self.chunk_bytes)

    @classmethod
    def pre_shipped(cls, specs, q_flags: Sequence[bool],
                    bucket_bytes: Optional[int] = None,
                    chunk_bytes: Optional[int] = None) -> "ShipPlan":
        """A plan over ALREADY-packed wire items (ddp.sync_grads ships
        its coalesced flats + solos through io_callback): no
        re-coalescing — only the slot-aligned chunking of oversized
        arrays and the size-targeted bucketing."""
        plan = cls.__new__(cls)
        if bucket_bytes is None:
            bucket_bytes = int(os.environ.get(
                "PTD_GRAD_BUCKET_BYTES", DEFAULT_BUCKET_BYTES
            ))
        plan.bucket_bytes = max(int(bucket_bytes), 1)
        plan.chunk_bytes = int(chunk_bytes or DEFAULT_BUCKET_BYTES)
        plan.specs = [(tuple(s), np.dtype(d)) for s, d in specs]
        plan.sizes = [int(np.prod(s, dtype=np.int64))
                      for s, _ in plan.specs]
        plan.coalesced = set()
        items: List[ShipItem] = []
        buffers: List[Tuple[int, np.dtype]] = []
        for i, ((_, dt), qf) in enumerate(zip(plan.specs, q_flags)):
            items.extend(_chunk_items(i, dt, plan.sizes[i], bool(qf),
                                      len(buffers), plan.chunk_bytes))
            buffers.append((plan.sizes[i], dt))
        plan.items = items
        plan.buffers = buffers
        plan.buckets = _bucketize(items, plan.bucket_bytes)
        return plan


def ship_plan_for_leaves(leaves, *, quantize: bool = False,
                         bucket_bytes: Optional[int] = None) -> ShipPlan:
    """Plan from concrete arrays / ShapeDtypeStructs (shape+dtype duck)."""
    return ShipPlan(
        [(np.shape(x), np.dtype(x.dtype)) for x in leaves],
        quantize=quantize, bucket_bytes=bucket_bytes,
    )


# --------------------------------------------------------------------------
# numpy replication of the native block quantizer (error feedback).
# --------------------------------------------------------------------------
def q8_local_roundtrip(x: np.ndarray) -> np.ndarray:
    """``dequant(quant(x))`` per 256-element block, replicating
    ``native/hostring.cpp``'s ``quantize_block`` (scale = amax/127,
    ``x * (1/scale)`` in f32, clamp ±127, round half away from zero).
    Non-finite blocks dequantize to NaN, like the native side."""
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    n = x.size
    pad = (-n) % _Q8_BLOCK
    xp = np.pad(x, (0, pad)).reshape(-1, _Q8_BLOCK)
    amax = np.max(np.abs(xp), axis=1)
    bad = ~(amax <= np.float32(3.4e38))  # False for NaN/inf, like the C
    s = (amax / np.float32(127.0)).astype(np.float32)
    safe = np.where(s > 0, s, np.float32(1.0))
    inv = (np.float32(1.0) / safe).astype(np.float32)
    v = xp * inv[:, None]
    v = np.clip(v, np.float32(-127.0), np.float32(127.0))
    q = np.trunc(v + np.copysign(np.float32(0.5), v))
    out = (q * s[:, None]).astype(np.float32)
    out[s == 0] = 0.0
    out[bad] = np.nan
    return out.reshape(-1)[:n]


# --------------------------------------------------------------------------
# The engine: one comm thread, a FIFO bucket queue, reusable staging.
# --------------------------------------------------------------------------
_STOP = object()


class _Pending:
    """One in-flight sync: per-bucket completion + timing + error."""

    __slots__ = ("total_buckets", "done", "comm_s", "error", "_cv")

    def __init__(self, total_buckets: int):
        self.total_buckets = total_buckets
        self.done = 0
        self.comm_s = 0.0
        self.error: Optional[BaseException] = None
        self._cv = threading.Condition()

    def _bucket_done(self, seconds: float,
                     error: Optional[BaseException]) -> None:
        with self._cv:
            self.done += 1
            self.comm_s += seconds
            if error is not None and self.error is None:
                self.error = error
            self._cv.notify_all()

    def wait(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self.done < self.total_buckets:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        "grad-sync pipeline drain timed out "
                        f"({self.done}/{self.total_buckets} buckets)"
                    )
                self._cv.wait(left)


class GradSyncEngine:
    """Process-level pipelined reducer bound to ONE HostRingGroup.

    All collectives issue from the single comm thread in FIFO bucket
    order (deterministic — see the module docstring); the main thread
    packs, drains and unpacks. A ring failure (peer death, deadline)
    poisons the engine: the error surfaces on ``drain`` and every later
    call refuses loudly until :func:`reset_engine` — the elastic path
    re-meshes onto a fresh ring and a fresh engine (the chaos drill in
    tests/test_overlap.py proves the recovery).
    """

    def __init__(self, ring, *, bucket_bytes: Optional[int] = None):
        self.ring = ring
        self.bucket_bytes = bucket_bytes
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._dead: Optional[BaseException] = None
        self._plans: Dict[tuple, ShipPlan] = {}
        # staging double-buffers per plan signature: generation g's
        # output arrays may still be aliased by a jit consumer while
        # generation g^1 is being packed; g is only rewritten two syncs
        # later, after its consumer provably completed (DESIGN.md §19)
        self._staging: Dict[tuple, list] = {}
        self._residuals: Dict[tuple, Dict[int, np.ndarray]] = {}
        self._gen = 0
        self._named_tracer = None
        # cumulative stats (the bench's exposed/hidden account)
        self.syncs = 0
        self.comm_s_total = 0.0
        self.exposed_s_total = 0.0
        self.hidden_s_total = 0.0

    # -- lifecycle ---------------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._comm_loop, name="grad-sync-comm", daemon=True
            )
            self._thread.start()

    def close(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._q.put(_STOP)
            self._thread.join(timeout=5)
        self._thread = None

    def _check_alive(self) -> None:
        if self._dead is not None:
            raise RuntimeError(
                "grad-sync pipeline is poisoned by an earlier ring "
                f"failure ({self._dead}) — re-mesh the world and call "
                "parallel.overlap.reset_engine() for a fresh pipeline"
            )

    # -- the comm thread ---------------------------------------------------
    def _comm_loop(self) -> None:
        while True:
            task = self._q.get()
            if task is _STOP:
                return
            bucket, pending = task
            tr = tracing._tracer
            if tr is not None and self._named_tracer is not tr:
                # the comm.* spans below land on this thread's tid; name
                # the track once per tracer so Perfetto shows "grad-sync-
                # comm" instead of a bare thread id
                self._named_tracer = tr
                tracing.name_thread("grad-sync-comm")
            err: Optional[BaseException] = None
            t0 = time.perf_counter()
            try:
                if pending.error is None and self._dead is None:
                    # a failed bucket poisons the WHOLE sync: issuing
                    # later buckets on an aborted ring would desync peers
                    faults.check("comm.overlap_stall")
                    for work in bucket:
                        work()
            except BaseException as e:  # noqa: BLE001 - surfaced on drain
                err = e
                self._dead = e
            pending._bucket_done(time.perf_counter() - t0, err)

    # -- plan/staging ------------------------------------------------------
    def _plan(self, specs, quantize: bool) -> ShipPlan:
        key = (tuple((tuple(s), np.dtype(d).name) for s, d in specs),
               bool(quantize))
        plan = self._plans.get(key)
        if plan is None:
            plan = ShipPlan(
                specs, quantize=quantize,
                bucket_bytes=self.bucket_bytes,
                # chunking MUST follow the ring's own slot stride — any
                # other boundary changes hr_allreduce's per-chunk segment
                # ownership and breaks bit-identity vs the unsplit call
                chunk_bytes=getattr(self.ring, "slot_bytes", None),
            )
            self._plans[key] = plan
        return plan

    def _buffers(self, plan: ShipPlan, gen: int) -> List[np.ndarray]:
        key = (plan.signature(), gen)
        bufs = self._staging.get(key)
        if bufs is None:
            bufs = [np.empty(elems, dt) for elems, dt in plan.buffers]
            self._staging[key] = bufs
        return bufs

    @staticmethod
    def _view(plan: ShipPlan, bufs: List[np.ndarray],
              item: ShipItem) -> np.ndarray:
        return bufs[item.parent][item.start:item.start + item.elems]

    def _residual(self, plan: ShipPlan, item_idx: int,
                  elems: int) -> np.ndarray:
        per_plan = self._residuals.setdefault(plan.signature(), {})
        r = per_plan.get(item_idx)
        if r is None:
            r = per_plan[item_idx] = np.zeros(elems, np.float32)
        return r

    def reset_residuals(self) -> None:
        """Drop all error-feedback state (a fresh training run)."""
        self._residuals.clear()

    # -- reduction work ----------------------------------------------------
    def _reduce_item(self, plan: ShipPlan, item_idx: int,
                     view: np.ndarray) -> None:
        """Ring-reduce one packed ship item IN PLACE (comm thread)."""
        item = plan.items[item_idx]
        if item.q8:
            res = self._residual(plan, item_idx, item.elems)
            # error feedback: ship g + e, keep e' = (g+e) - Q(g+e)
            np.add(view, res, out=view)
            rt = q8_local_roundtrip(view)
            np.subtract(view, rt, out=res)
            # a non-finite block round-trips to NaN (deliberately loud
            # on the wire); the residual must not carry that poison
            # into every later step once training recovers
            np.copyto(res, 0.0, where=~np.isfinite(res))
            self.ring.all_reduce_q8(view, op="avg", inplace=True)
        else:
            self.ring.all_reduce(view, op="avg", inplace=True)

    # -- public: io_callback path (sync_grads) -----------------------------
    def reduce_shipped(self, arrs: Sequence, q_flags: Sequence[bool]
                       ) -> Tuple[List[np.ndarray], dict]:
        """Average pre-packed ship arrays across ranks.

        ``arrs`` are the jit-side ship items (coalesced flats + solo
        leaves, already cast to their wire dtype) in plan order — the
        engine re-derives the same plan from their specs and asserts the
        q8 flags agree, packs each into reusable staging, and pipelines
        pack(b+1) ∥ ring-reduce(b). Returns (reduced arrays in ship
        order, stats).
        """
        self._check_alive()
        self._ensure_thread()
        specs = [(np.shape(a), np.dtype(a.dtype)) for a in arrs]
        key = (tuple((tuple(s), np.dtype(d).name) for s, d in specs),
               ("shipped",) + tuple(bool(f) for f in q_flags))
        plan = self._plans.get(key)
        if plan is None:
            plan = ShipPlan.pre_shipped(
                specs, q_flags, bucket_bytes=self.bucket_bytes,
                chunk_bytes=getattr(self.ring, "slot_bytes", None),
            )
            self._plans[key] = plan
        gen = self._gen
        self._gen ^= 1
        bufs = self._buffers(plan, gen)
        pending = _Pending(len(plan.buckets))
        t_start = time.perf_counter()
        flat_srcs: Dict[int, np.ndarray] = {}
        for bucket in plan.buckets:
            work = []
            for j in bucket:
                item = plan.items[j]
                src = flat_srcs.get(item.parent)
                if src is None:
                    src = flat_srcs[item.parent] = np.asarray(
                        arrs[item.parent]
                    ).reshape(-1)
                view = self._view(plan, bufs, item)
                np.copyto(view, src[item.start:item.start + item.elems])
                work.append(self._make_work(plan, j, view))
            self._q.put((work, pending))
        stats = self._drain(pending, t_start)
        out = [
            bufs[p].reshape(plan.specs[p][0])
            for p in range(len(plan.buffers))
        ]
        return out, stats

    def _make_work(self, plan: ShipPlan, j: int, view: np.ndarray):
        return lambda: self._reduce_item(plan, j, view)

    # -- public: host-loop accumulation path (overlap_accum) ---------------
    def begin_accum(self, specs, *, quantize: bool = False) -> "AccumSession":
        self._check_alive()
        self._ensure_thread()
        return AccumSession(self, self._plan(specs, quantize))

    # -- drain/stats -------------------------------------------------------
    def _drain(self, pending: _Pending, t_start: float) -> dict:
        t0 = time.perf_counter()
        tr = tracing._tracer
        # the drain wait IS the exposed comm: everything the main thread
        # still blocks on after its concurrent work ran out — its span
        # duration is the per-sync comm_exposed the rollups report
        span = (
            tracing._NULL_SPAN if tr is None
            else tracing._Span(tr, "comm.sync_drain", None)
        )
        with span:
            pending.wait(timeout_s=self.ring.timeout_s * (
                pending.total_buckets + 2
            ))
        exposed = time.perf_counter() - t0
        if pending.error is not None:
            raise RuntimeError(
                "grad-sync pipeline failed mid-drain (a peer died or "
                "the ring deadline passed) — survivors should re-mesh "
                f"and reset_engine(): {pending.error}"
            ) from pending.error
        comm = pending.comm_s
        hidden = max(comm - exposed, 0.0)
        self.syncs += 1
        self.comm_s_total += comm
        self.exposed_s_total += exposed
        self.hidden_s_total += hidden
        tr = tracing._tracer
        if tr is not None:
            tr.counter("comm.sync.exposed_s",
                       round(self.exposed_s_total, 6))
            tr.counter("comm.sync.hidden_s",
                       round(self.hidden_s_total, 6))
        return {
            "comm_s": comm,
            "exposed_s": min(exposed, comm),
            "hidden_s": hidden,
            "wall_s": time.perf_counter() - t_start,
            "buckets": pending.total_buckets,
        }

    def stats(self) -> dict:
        total = self.comm_s_total
        return {
            "syncs": self.syncs,
            "comm_s": total,
            "exposed_s": min(self.exposed_s_total, total),
            "hidden_s": self.hidden_s_total,
            "exposed_ratio": (
                min(self.exposed_s_total, total) / total if total > 0
                else 0.0
            ),
        }


class AccumSession:
    """Microbatch accumulation straight into the wire staging buffers.

    ``add`` folds one microbatch's per-leaf gradients into the staging
    (first add copies, later adds accumulate — the exact left-fold
    association ``lax.scan`` uses, so the local sums are bit-identical
    to the scanned path's). ``finish`` applies the 1/accum scale and
    enqueues buckets STAGGERED: bucket b's ring reduce starts while the
    main thread is still scaling/finalizing bucket b+1 (and, at the
    caller's level, staging its next batch). ``drain`` blocks, unpacks,
    and reports the exposed/hidden split.
    """

    def __init__(self, engine: GradSyncEngine, plan: ShipPlan):
        self.engine = engine
        self.plan = plan
        gen = engine._gen
        engine._gen ^= 1
        self.bufs = engine._buffers(plan, gen)
        self.adds = 0
        self._pending: Optional[_Pending] = None
        self._t_start = time.perf_counter()

    def _pieces(self, item: ShipItem, flat_leaves):
        """(dst staging view, src leaf slice) pairs for one item."""
        view = self.engine._view(self.plan, self.bufs, item)
        if item.kind == "flat":
            for leaf, loff in zip(item.leaf_ids, item.offsets):
                n = self.plan.sizes[leaf]
                yield view[loff:loff + n], flat_leaves[leaf]
        else:
            leaf = item.leaf_ids[0]
            yield view, flat_leaves[leaf][
                item.start:item.start + item.elems
            ]

    @staticmethod
    def _flat(leaves: Sequence) -> List[np.ndarray]:
        return [np.asarray(x).reshape(-1) for x in leaves]

    def _fold(self, item: ShipItem, flat_leaves, first: bool) -> None:
        for dst, src in self._pieces(item, flat_leaves):
            if first:
                np.copyto(dst, src, casting="unsafe")
            else:
                np.add(dst, src, out=dst, casting="unsafe")

    def add(self, leaves: Sequence[np.ndarray]) -> None:
        first = self.adds == 0
        flat_leaves = self._flat(leaves)
        for item in self.plan.items:
            self._fold(item, flat_leaves, first)
        self.adds += 1

    def finish(self, last_leaves: Sequence[np.ndarray],
               scale: float) -> None:
        """Fold the LAST microbatch in bucket-by-bucket, scaling and
        enqueueing each bucket as it completes — the pipeline's comm
        starts before the host finishes accumulating later buckets."""
        first = self.adds == 0
        self.adds += 1
        flat_leaves = self._flat(last_leaves)
        pending = _Pending(len(self.plan.buckets))
        self._pending = pending
        for bucket in self.plan.buckets:
            work = []
            for j in bucket:
                item = self.plan.items[j]
                self._fold(item, flat_leaves, first)
                view = self.engine._view(self.plan, self.bufs, item)
                if scale != 1.0:
                    np.multiply(
                        view, np.float32(scale).astype(view.dtype),
                        out=view,
                    )
                work.append(self.engine._make_work(self.plan, j, view))
            self.engine._q.put((work, pending))

    def drain(self) -> Tuple[List[np.ndarray], dict]:
        """Wait for the ring, return (per-LEAF reduced arrays, stats)."""
        if self._pending is None:
            raise RuntimeError("drain() before finish()")
        stats = self.engine._drain(self._pending, self._t_start)
        out: List[Optional[np.ndarray]] = [None] * len(self.plan.specs)
        for item in self.plan.items:
            view = self.engine._view(self.plan, self.bufs, item)
            if item.kind == "flat":
                for leaf, loff in zip(item.leaf_ids, item.offsets):
                    n = self.plan.sizes[leaf]
                    out[leaf] = view[loff:loff + n].reshape(
                        self.plan.specs[leaf][0]
                    )
            elif item.kind == "solo":
                leaf = item.leaf_ids[0]
                out[leaf] = view.reshape(self.plan.specs[leaf][0])
            else:  # chunk: the parent buffer IS the contiguous leaf
                leaf = item.leaf_ids[0]
                out[leaf] = self.bufs[item.parent].reshape(
                    self.plan.specs[leaf][0]
                )
        return out, stats  # type: ignore[return-value]


# --------------------------------------------------------------------------
# The process-level engine registry (one engine per live ring).
# --------------------------------------------------------------------------
_ENGINE: Optional[GradSyncEngine] = None
_ENGINE_KEY = None


def _ring_key(ring) -> tuple:
    return (id(ring), getattr(ring, "name", None), ring.rank,
            ring.world_size)


def get_engine(ring, *, bucket_bytes: Optional[int] = None
               ) -> GradSyncEngine:
    """The engine bound to ``ring`` — rebuilt whenever the ring changes
    (an elastic re-mesh swaps rings; the old engine's queue and staging
    must not survive onto the new membership)."""
    global _ENGINE, _ENGINE_KEY
    key = _ring_key(ring)
    if _ENGINE is None or _ENGINE_KEY != key:
        if _ENGINE is not None:
            _ENGINE.close()
        _ENGINE = GradSyncEngine(ring, bucket_bytes=bucket_bytes)
        _ENGINE_KEY = key
    return _ENGINE


def reset_engine() -> None:
    """Drop the process engine (staging, residuals, comm thread).

    The elastic recovery path: after a peer death poisons the pipeline,
    survivors re-mesh onto a fresh ring and the next ``get_engine``
    builds a clean pipeline for it.
    """
    global _ENGINE, _ENGINE_KEY
    if _ENGINE is not None:
        _ENGINE.close()
    _ENGINE = None
    _ENGINE_KEY = None
