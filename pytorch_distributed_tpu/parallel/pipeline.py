"""Pipeline parallelism: a GPipe schedule expressed the SPMD way.

The reference repo has no pipeline parallelism (SURVEY.md §2: DDP, ZeRO-1
and FSDP only) — this is a capability extension that falls out almost for
free on TPU: under single-controller SPMD a pipeline is just (a) the
stacked layer dimension of the params sharded over the ``pp`` mesh axis
and (b) a ``lax.scan`` over schedule ticks whose stage-to-stage handoff is
a ``ppermute`` riding the ICI torus. Backprop needs no hand-written
schedule: the transpose of ``ppermute`` is the reverse ``ppermute``, so
differentiating the scan yields the reverse (1F1B-shaped) pipeline
automatically.

Schedule shape (classic GPipe): with S stages and M microbatches the loop
runs ``M + S - 1`` ticks; stage s is busy on ticks ``s .. s+M-1``; the
bubble fraction is ``(S-1)/(M+S-1)`` — keep M >= 4*S for >80%% utilisation.

Layout contract: stage-stacked parameters have leading dim S (one slice
per stage), sharded ``P("pp")``; microbatched inputs/outputs have leading
dim M, replicated over ``pp``.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from pytorch_distributed_tpu.runtime.compat import axis_size, shard_map

from pytorch_distributed_tpu.runtime.mesh import current_mesh


def _pipeline_local(stage_params, xs, *, stage_fn, axis: str):
    """Runs per-shard inside shard_map: the GPipe tick loop for my stage.

    stage_params: this stage's slice of the stacked params (leading stage
    dim of size 1, kept so tree structure matches the global view).
    xs: [M, ...] all microbatches (replicated).
    """
    stage = lax.axis_index(axis)
    n_stages = axis_size(axis)
    M = xs.shape[0]
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    shift = [(i, i + 1) for i in range(n_stages - 1)]  # stage i -> i+1

    def tick(carry, t):
        cur, outs = carry
        # stage 0 ingests microbatch t while they last; other stages (and
        # drain ticks) consume the activation handed over last tick
        mb = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        inp = jnp.where(stage == 0, mb, cur)
        y = stage_fn(params, inp)
        # last stage: y at tick t completes microbatch t - (S-1)
        m = t - (n_stages - 1)
        is_ready = jnp.logical_and(stage == n_stages - 1, m >= 0)
        outs = lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(is_ready, y, lax.dynamic_index_in_dim(
                outs, jnp.clip(m, 0, M - 1), axis=0, keepdims=False)),
            jnp.clip(m, 0, M - 1),
            axis=0,
        )
        nxt = lax.ppermute(y, axis, shift)  # stage 0 receives zeros: unused
        return (nxt, outs), None

    y0 = jax.eval_shape(stage_fn, params, xs[0])
    cur0 = jnp.zeros(y0.shape, y0.dtype)
    outs0 = jnp.zeros((M,) + y0.shape, y0.dtype)
    (_, outs), _ = lax.scan(
        tick, (cur0, outs0), jnp.arange(M + n_stages - 1)
    )
    # outputs are only real on the last stage; psum of the masked buffer
    # replicates them to every stage
    outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis)


def pipeline_forward(
    stage_fn: Callable,
    stacked_params,
    microbatches,
    *,
    axis: str = "pp",
    mesh: Mesh | None = None,
):
    """Run stage-stacked params over microbatches with a GPipe schedule.

    ``stage_fn(params_one_stage, x) -> y`` applies ONE stage's layers; x
    and y must have identical shape/dtype (the activation handed between
    stages). ``stacked_params``: pytree whose leaves have leading dim =
    number of stages (= mesh ``axis`` size). ``microbatches``: [M, ...],
    M >= 1. Returns [M, ...] outputs, replicated over ``axis``.

    Differentiable end-to-end; grads of the stacked params come back with
    the same leading stage dim, still sharded over ``axis``.
    """
    mesh = mesh or current_mesh()
    n_stages = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    for leaf in leaves:
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked param leading dim {leaf.shape[0]} != pipeline "
                f"stages {n_stages} (mesh axis {axis!r})"
            )
    fn = shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn, axis=axis),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stacked_params, microbatches)


def stage_sharding(mesh: Mesh | None = None, axis: str = "pp"):
    """NamedSharding for stage-stacked params (leading dim over ``axis``)."""
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, P(axis))


def split_microbatches(batch, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...] for every leaf of a batch pytree."""

    def split(x):
        B = x.shape[0]
        if B % num_microbatches != 0:
            raise ValueError(
                f"batch dim {B} not divisible by {num_microbatches} "
                "microbatches"
            )
        return x.reshape((num_microbatches, B // num_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(split, batch)


def merge_microbatches(batch):
    """Inverse of :func:`split_microbatches`."""

    def merge(x):
        return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])

    return jax.tree_util.tree_map(merge, batch)
