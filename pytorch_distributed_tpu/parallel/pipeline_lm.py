"""Pipeline-parallel decoder LMs: GPipe stages over the scanned block stack.

Bridges the generic schedule (``parallel/pipeline.py``) to a *real*
transformer: the scanned models (``models/scan.py``) already keep their
block params stacked ``[L, ...]``, which is exactly the pipeline's stage
layout once grouped to ``[pp, L/pp, ...]``. Embedding / final-norm / head
run replicated on every stage (they are <1% of the FLOPs; SPMD dedups the
memory via sharding propagation), the block stack runs through the
``ppermute`` tick loop, and autodiff of the scan yields the reverse
schedule — so the SAME ``build_train_step``/Trainer machinery trains a
pipelined model with no bespoke training loop.

The reference has no pipeline parallelism (SURVEY.md §2) — capability
extension. Blocks run with dropout disabled inside the pipeline (per-layer
rng plumbing through the tick loop isn't worth the complexity for a
regularizer; GPT-2 convergence is unaffected at recipe scale).
"""

from __future__ import annotations

import re
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_forward,
    split_microbatches,
)
from pytorch_distributed_tpu.parallel.sharding import PartitionRules
from pytorch_distributed_tpu.parallel.strategies import Strategy


def _block_stage_fn(block_module) -> Callable:
    """stage_fn for pipeline_forward: scan this stage's layers of a block.

    ``stage_params`` leaves are [L/pp, ...]; the scan consumes the leading
    per-stage layer dim. Blocks run deterministic (see module docstring).
    """

    def stage_fn(stage_params, x):
        def body(c, p):
            # (x, segment_ids=None, kv_mask=None, write_pos=None,
            #  deterministic=True)
            return (
                block_module.apply({"params": p}, c, None, None, None, True),
                None,
            )

        y, _ = lax.scan(body, x, stage_params)
        return y

    return stage_fn


def gpt2_pipeline_logits(
    cfg,
    params,
    input_ids,
    *,
    num_microbatches: int,
    axis: str = "pp",
):
    """[B, S] ids -> [B, S, vocab] logits, block stack pipelined over
    ``axis``. ``params`` is the scanned GPT2LMHead tree (scan_layers=True;
    blocks/block/* stacked [L, ...]).

    The embed/ln_f/tied-head tails here mirror ``GPT2LMHead.__call__``
    (models/gpt2.py) — keep the two in lockstep when changing either;
    ``test_gpt2_pipeline_logits_match_plain_forward`` pins the pairing.
    (Embedding dropout is omitted: blocks run deterministic in the
    pipeline, see module docstring.)"""
    import flax.linen as nn

    from pytorch_distributed_tpu.models.gpt2 import GPT2Block
    from pytorch_distributed_tpu.runtime.mesh import current_mesh
    from pytorch_distributed_tpu.runtime.precision import current_policy

    policy = current_policy()
    mesh = current_mesh()
    pp = mesh.shape[axis]
    B, S = input_ids.shape

    wte = params["wte"]["embedding"]
    wpe = params["wpe"]["embedding"]
    x = wte[input_ids] + wpe[jnp.arange(S)][None, :]
    x = x.astype(policy.compute_dtype)

    blocks = params["blocks"]["block"]
    L = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    if L % pp:
        raise ValueError(f"{L} layers not divisible by {pp} pipeline stages")
    staged = jax.tree_util.tree_map(
        lambda p: p.reshape((pp, L // pp) + p.shape[1:]), blocks
    )
    mbs = split_microbatches(x, num_microbatches)
    y = pipeline_forward(
        _block_stage_fn(GPT2Block(cfg)), staged, mbs, axis=axis, mesh=mesh
    )
    x = merge_microbatches(y)

    x = nn.LayerNorm(
        epsilon=cfg.layer_norm_eps, dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    ).apply({"params": params["ln_f"]}, x)
    logits = jnp.einsum(
        "bsd,vd->bsv",
        x,
        wte.astype(policy.compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return logits.astype(policy.output_dtype)


def pipelined_causal_lm_loss_fn(
    cfg,
    *,
    num_microbatches: int,
    axis: str = "pp",
    ids_key: str = "input_ids",
) -> Callable:
    """Trainer-contract loss: next-token CE through the pipelined forward.

    Drop-in for ``causal_lm_loss_fn`` — same (params, batch_stats, batch,
    rng) signature, so ``build_train_step``/Trainer/recipes work unchanged.
    """

    def loss_fn(params, batch_stats, batch, rng):
        if "segment_ids" in batch:
            raise NotImplementedError(
                "packed batches (segment_ids) are not supported through "
                "the pipelined loss yet — silently ignoring them would "
                "attend across document boundaries"
            )
        ids = batch[ids_key]
        logits = gpt2_pipeline_logits(
            cfg, params, ids, num_microbatches=num_microbatches, axis=axis
        )
        shift_logits = logits[:, :-1].astype(jnp.float32)
        shift_labels = ids[:, 1:]
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                shift_logits, shift_labels
            )
        )
        return loss, {"metrics": {"loss": loss}, "batch_stats": batch_stats}

    return loss_fn


# -- host-dispatched stages (r20) -------------------------------------------
# The 1F1B executor's GPT-2 bridge: slice the scanned param tree into
# per-rank stage trees, and build the per-stage programs
# ``parallel/pipeline_schedule.HostPipelineStep`` compiles once each.
# The embed/ln_f/tied-head math mirrors ``gpt2_pipeline_logits`` above
# (which mirrors ``GPT2LMHead.__call__``) — keep the three in lockstep.


def host_stage_depths(num_layers, num_stages, rank_rates=None):
    """Layers per stage — even split, or rate-apportioned (a slow rank
    gets a shallower stage; ``pipeline_schedule.stage_depths``)."""
    from pytorch_distributed_tpu.parallel.pipeline_schedule import (
        stage_depths,
    )

    return stage_depths(num_layers, num_stages, rank_rates)


def host_stage_params(params, *, stage, num_stages, depths=None):
    """Slice a scanned GPT2LMHead tree into stage ``stage``'s param tree
    plus its non-optimized buffers.

    Stage 0 owns wte/wpe (and the tied wte's optimizer state); the last
    stage owns ln_f and carries ``buffers["head_wte"]`` — a REPLICA of
    stage 0's wte for the tied head projection, refreshed after every
    apply by the executor's ``exchange_params`` hook (S == 1 ties
    directly, exactly like the plain model). Returns
    ``(stage_params, buffers)``.
    """
    import numpy as np

    blocks = params["blocks"]["block"]
    num_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    if depths is None:
        depths = host_stage_depths(num_layers, num_stages)
    if sum(depths) != num_layers:
        raise ValueError(f"depths {depths} do not cover {num_layers} layers")
    start = sum(depths[:stage])
    stop = start + depths[stage]
    sp = {
        "blocks": jax.tree_util.tree_map(
            lambda p: p[start:stop], blocks
        )
    }
    first = stage == 0
    last = stage == num_stages - 1
    if first:
        sp["wte"] = params["wte"]
        sp["wpe"] = params["wpe"]
    if last:
        sp["ln_f"] = params["ln_f"]
    buffers = {}
    if last and not first:
        buffers["head_wte"] = jnp.asarray(
            np.asarray(params["wte"]["embedding"])
        )
    return sp, buffers


def host_merge_stage_params(stage_trees, depths):
    """Inverse of :func:`host_stage_params`: reassemble the full scanned
    tree from every stage's final params (the parity check's gather)."""
    num_stages = len(stage_trees)
    if num_stages != len(depths):
        raise ValueError(f"{num_stages} trees vs {len(depths)} depths")
    blocks = jax.tree_util.tree_map(
        lambda *leaves: jnp.concatenate(leaves, axis=0),
        *[t["blocks"] for t in stage_trees],
    )
    return {
        "wte": stage_trees[0]["wte"],
        "wpe": stage_trees[0]["wpe"],
        "blocks": {"block": blocks},
        "ln_f": stage_trees[-1]["ln_f"],
    }


def host_act_template(cfg, microbatch_size, seq_len, dtype=None):
    """Recv-buffer prototype for the stage-boundary activations/grads:
    ``[mb, seq, hidden]`` in the compute dtype."""
    import numpy as np

    from pytorch_distributed_tpu.runtime.precision import current_policy

    if dtype is None:
        dtype = np.dtype(jnp.dtype(current_policy().compute_dtype))
    return np.zeros(
        (microbatch_size, seq_len, cfg.hidden_size), dtype
    )


class GPT2HostStagePrograms:
    """Per-stage forward/backward programs for ``HostPipelineStep``.

    One jitted forward and one jitted backward per stage (the backward
    re-derives the forward via ``jax.vjp`` so only the stage INPUT is
    stashed per live microbatch); the last stage fuses loss + backward in
    one ``value_and_grad`` program. Blocks run deterministic (module
    docstring); the CE loss mirrors ``pipelined_causal_lm_loss_fn``.

    The tied head: the last stage projects with its ``head_wte`` replica
    and its gradient contribution travels to stage 0 over a tagged P2P
    pair (``exchange_grads``) where it joins the embedding gradient —
    the two tied contributions dp sums inside one backward are here
    regrouped across stages, the documented last-ulp tolerance class —
    and stage 0's freshly-applied wte travels back (``exchange_params``).
    """

    def __init__(self, cfg, *, stage, num_stages):
        import flax.linen as nn

        from pytorch_distributed_tpu.models.gpt2 import GPT2Block
        from pytorch_distributed_tpu.runtime.precision import current_policy

        self.cfg = cfg
        policy = current_policy()
        first = stage == 0
        last = stage == num_stages - 1
        blocks_fn = _block_stage_fn(GPT2Block(cfg))
        ln = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=policy.compute_dtype,
            param_dtype=policy.param_dtype,
        )

        def embed(sp, ids):
            wte = sp["wte"]["embedding"]
            wpe = sp["wpe"]["embedding"]
            x = wte[ids] + wpe[jnp.arange(ids.shape[1])][None, :]
            return x.astype(policy.compute_dtype)

        def body(sp, xin):
            x = (
                embed(sp, xin) if first
                else xin.astype(policy.compute_dtype)
            )
            return blocks_fn(sp["blocks"], x)

        def head_loss(sp, x, ids, head_wte):
            h = ln.apply({"params": sp["ln_f"]}, x)
            logits = jnp.einsum(
                "bsd,vd->bsv", h,
                head_wte.astype(policy.compute_dtype),
                preferred_element_type=jnp.float32,
            )
            shift_logits = logits[:, :-1].astype(jnp.float32)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    shift_logits, ids[:, 1:]
                )
            )

        if num_stages == 1:

            def loss_grad_solo(sp, ids):
                def f(p):
                    return head_loss(
                        p, body(p, ids), ids, p["wte"]["embedding"]
                    )

                return jax.value_and_grad(f)(sp)

            self.loss_grad_solo = loss_grad_solo
        elif last:

            def loss_grad(sp, head_wte, x, ids):
                def f(p, hw, xi):
                    return head_loss(p, body(p, xi), ids, hw)

                loss, (gp, ghw, dx) = jax.value_and_grad(
                    f, argnums=(0, 1, 2)
                )(sp, head_wte, x)
                return loss, gp, ghw, dx

            self.loss_grad = loss_grad
        elif first:

            def fwd(sp, ids):
                return body(sp, ids)

            def bwd(sp, ids, dy):
                y, vjp_fn = jax.vjp(lambda p: body(p, ids), sp)
                (gp,) = vjp_fn(dy.astype(y.dtype))
                return gp

            self.fwd, self.bwd = fwd, bwd
        else:

            def fwd(sp, x):
                return body(sp, x)

            def bwd(sp, x, dy):
                y, vjp_fn = jax.vjp(body, sp, x)
                gp, dx = vjp_fn(dy.astype(y.dtype))
                return gp, dx

            self.fwd, self.bwd = fwd, bwd

    # -- tied-wte pairing (first <-> last stage, tagged P2P) ----------------
    def exchange_grads(self, group, stage, num_stages, grads, aux_grad):
        import numpy as np

        if num_stages == 1:
            return grads
        last = num_stages - 1
        if stage == last:
            group.send(np.asarray(aux_grad), 0, tag="tied.wte.grad")
        elif stage == 0:
            emb = np.asarray(grads["wte"]["embedding"])
            proto = np.empty_like(emb)
            got = group.recv(proto, last, tag="tied.wte.grad")
            np.add(emb, got, out=emb)
        return grads

    def exchange_params(self, group, stage, num_stages, params, buffers):
        import numpy as np

        if num_stages == 1:
            return
        last = num_stages - 1
        if stage == 0:
            group.send(
                np.asarray(params["wte"]["embedding"]), last,
                tag="tied.wte.param",
            )
        elif stage == last:
            proto = np.empty_like(np.asarray(buffers["head_wte"]))
            got = group.recv(proto, 0, tag="tied.wte.param")
            buffers["head_wte"] = jnp.asarray(np.array(got))


class _PipelineRules(PartitionRules):
    """TP rules composed with the pp stage sharding, not racing it.

    Plain first-match-wins rules can't express "apply the TP spec AND
    shard the layer dim over pp" — a TP rule matching a block param would
    win and silently drop the stage sharding. This subclass resolves the
    TP/fallback spec first, then forces the leading (layer) dim of every
    block-stack param onto ``axis``.
    """

    def __init__(self, rules, block_pat: str, axis: str):
        super().__init__(rules)
        self._block = re.compile(block_pat)
        self._axis = axis

    def spec_for(self, path, shape, mesh=None):
        spec = super().spec_for(path, shape, mesh)
        if not self._block.search(path):
            return spec
        from pytorch_distributed_tpu.runtime.mesh import current_mesh

        size = (mesh or current_mesh()).shape[self._axis]
        entries = list(spec) if spec is not None else []
        entries += [None] * (len(shape) - len(entries))
        if entries and entries[0] is None and shape[0] % size == 0 and shape[0] >= size:
            entries[0] = self._axis
        return P(*entries)


class PipelineParallel(Strategy):
    """Stacked block params sharded over ``pp`` on the layer dim; embed /
    norms / head replicated; batch over the data axes (composes with dp).

    The [L, ...] layer dim sharded P("pp") IS the stage assignment:
    reshaping to [pp, L/pp, ...] inside the step lands each stage's layers
    exactly on its own shard — no data movement at the pipeline boundary.
    TP ``extra_rules`` compose: block params keep their TP axes *and* get
    the leading layer dim on ``pp`` (see _PipelineRules).
    """

    def __init__(self, mesh=None, *, axis: str = "pp",
                 block_pat: str = r"(blocks|layers)/block/", **kw):
        super().__init__(mesh, **kw)
        self.axis = axis
        self.block_pat = block_pat

    def param_rules(self) -> PartitionRules:
        tp = [
            (pat, self._wrap_tp(spec, self._transform_tp_param_spec))
            for pat, spec in self.extra_rules
        ]
        return _PipelineRules(
            tp + [(".*", None)], self.block_pat, self.axis
        )

    opt_rules = param_rules  # moments mirror the param layout
