"""Pipeline-parallel decoder LMs: GPipe stages over the scanned block stack.

Bridges the generic schedule (``parallel/pipeline.py``) to a *real*
transformer: the scanned models (``models/scan.py``) already keep their
block params stacked ``[L, ...]``, which is exactly the pipeline's stage
layout once grouped to ``[pp, L/pp, ...]``. Embedding / final-norm / head
run replicated on every stage (they are <1% of the FLOPs; SPMD dedups the
memory via sharding propagation), the block stack runs through the
``ppermute`` tick loop, and autodiff of the scan yields the reverse
schedule — so the SAME ``build_train_step``/Trainer machinery trains a
pipelined model with no bespoke training loop.

The reference has no pipeline parallelism (SURVEY.md §2) — capability
extension. Blocks run with dropout disabled inside the pipeline (per-layer
rng plumbing through the tick loop isn't worth the complexity for a
regularizer; GPT-2 convergence is unaffected at recipe scale).
"""

from __future__ import annotations

import re
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_forward,
    split_microbatches,
)
from pytorch_distributed_tpu.parallel.sharding import PartitionRules
from pytorch_distributed_tpu.parallel.strategies import Strategy


def _block_stage_fn(block_module) -> Callable:
    """stage_fn for pipeline_forward: scan this stage's layers of a block.

    ``stage_params`` leaves are [L/pp, ...]; the scan consumes the leading
    per-stage layer dim. Blocks run deterministic (see module docstring).
    """

    def stage_fn(stage_params, x):
        def body(c, p):
            # (x, segment_ids=None, kv_mask=None, write_pos=None,
            #  deterministic=True)
            return (
                block_module.apply({"params": p}, c, None, None, None, True),
                None,
            )

        y, _ = lax.scan(body, x, stage_params)
        return y

    return stage_fn


def gpt2_pipeline_logits(
    cfg,
    params,
    input_ids,
    *,
    num_microbatches: int,
    axis: str = "pp",
):
    """[B, S] ids -> [B, S, vocab] logits, block stack pipelined over
    ``axis``. ``params`` is the scanned GPT2LMHead tree (scan_layers=True;
    blocks/block/* stacked [L, ...]).

    The embed/ln_f/tied-head tails here mirror ``GPT2LMHead.__call__``
    (models/gpt2.py) — keep the two in lockstep when changing either;
    ``test_gpt2_pipeline_logits_match_plain_forward`` pins the pairing.
    (Embedding dropout is omitted: blocks run deterministic in the
    pipeline, see module docstring.)"""
    import flax.linen as nn

    from pytorch_distributed_tpu.models.gpt2 import GPT2Block
    from pytorch_distributed_tpu.runtime.mesh import current_mesh
    from pytorch_distributed_tpu.runtime.precision import current_policy

    policy = current_policy()
    mesh = current_mesh()
    pp = mesh.shape[axis]
    B, S = input_ids.shape

    wte = params["wte"]["embedding"]
    wpe = params["wpe"]["embedding"]
    x = wte[input_ids] + wpe[jnp.arange(S)][None, :]
    x = x.astype(policy.compute_dtype)

    blocks = params["blocks"]["block"]
    L = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    if L % pp:
        raise ValueError(f"{L} layers not divisible by {pp} pipeline stages")
    staged = jax.tree_util.tree_map(
        lambda p: p.reshape((pp, L // pp) + p.shape[1:]), blocks
    )
    mbs = split_microbatches(x, num_microbatches)
    y = pipeline_forward(
        _block_stage_fn(GPT2Block(cfg)), staged, mbs, axis=axis, mesh=mesh
    )
    x = merge_microbatches(y)

    x = nn.LayerNorm(
        epsilon=cfg.layer_norm_eps, dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    ).apply({"params": params["ln_f"]}, x)
    logits = jnp.einsum(
        "bsd,vd->bsv",
        x,
        wte.astype(policy.compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return logits.astype(policy.output_dtype)


def pipelined_causal_lm_loss_fn(
    cfg,
    *,
    num_microbatches: int,
    axis: str = "pp",
    ids_key: str = "input_ids",
) -> Callable:
    """Trainer-contract loss: next-token CE through the pipelined forward.

    Drop-in for ``causal_lm_loss_fn`` — same (params, batch_stats, batch,
    rng) signature, so ``build_train_step``/Trainer/recipes work unchanged.
    """

    def loss_fn(params, batch_stats, batch, rng):
        if "segment_ids" in batch:
            raise NotImplementedError(
                "packed batches (segment_ids) are not supported through "
                "the pipelined loss yet — silently ignoring them would "
                "attend across document boundaries"
            )
        ids = batch[ids_key]
        logits = gpt2_pipeline_logits(
            cfg, params, ids, num_microbatches=num_microbatches, axis=axis
        )
        shift_logits = logits[:, :-1].astype(jnp.float32)
        shift_labels = ids[:, 1:]
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                shift_logits, shift_labels
            )
        )
        return loss, {"metrics": {"loss": loss}, "batch_stats": batch_stats}

    return loss_fn


class _PipelineRules(PartitionRules):
    """TP rules composed with the pp stage sharding, not racing it.

    Plain first-match-wins rules can't express "apply the TP spec AND
    shard the layer dim over pp" — a TP rule matching a block param would
    win and silently drop the stage sharding. This subclass resolves the
    TP/fallback spec first, then forces the leading (layer) dim of every
    block-stack param onto ``axis``.
    """

    def __init__(self, rules, block_pat: str, axis: str):
        super().__init__(rules)
        self._block = re.compile(block_pat)
        self._axis = axis

    def spec_for(self, path, shape, mesh=None):
        spec = super().spec_for(path, shape, mesh)
        if not self._block.search(path):
            return spec
        from pytorch_distributed_tpu.runtime.mesh import current_mesh

        size = (mesh or current_mesh()).shape[self._axis]
        entries = list(spec) if spec is not None else []
        entries += [None] * (len(shape) - len(entries))
        if entries and entries[0] is None and shape[0] % size == 0 and shape[0] >= size:
            entries[0] = self._axis
        return P(*entries)


class PipelineParallel(Strategy):
    """Stacked block params sharded over ``pp`` on the layer dim; embed /
    norms / head replicated; batch over the data axes (composes with dp).

    The [L, ...] layer dim sharded P("pp") IS the stage assignment:
    reshaping to [pp, L/pp, ...] inside the step lands each stage's layers
    exactly on its own shard — no data movement at the pipeline boundary.
    TP ``extra_rules`` compose: block params keep their TP axes *and* get
    the leading layer dim on ``pp`` (see _PipelineRules).
    """

    def __init__(self, mesh=None, *, axis: str = "pp",
                 block_pat: str = r"(blocks|layers)/block/", **kw):
        super().__init__(mesh, **kw)
        self.axis = axis
        self.block_pat = block_pat

    def param_rules(self) -> PartitionRules:
        tp = [
            (pat, self._wrap_tp(spec, self._transform_tp_param_spec))
            for pat, spec in self.extra_rules
        ]
        return _PipelineRules(
            tp + [(".*", None)], self.block_pat, self.axis
        )

    opt_rules = param_rules  # moments mirror the param layout
