"""Parallelism strategies.

The reference reaches its parallelism through three distinct torch wrappers —
``DistributedDataParallel`` (bucketed gradient allreduce),
``ZeroRedundancyOptimizer`` (ZeRO-1 optimizer-state sharding) and ``FSDP``
(full param sharding) — each a separate runtime mechanism with its own hooks
(BASELINE.json:5,10,11). Under XLA SPMD all three are *the same mechanism*:
a choice of NamedSharding for (params, optimizer state, batch) on the mesh,
with the compiler inserting the collectives the torch wrappers hand-roll
(gradient allreduce, per-shard weight update + allgather, per-layer
allgather/reduce-scatter). This package expresses them exactly that way,
plus tensor parallelism (free under SPMD) and sequence/context parallelism
for long-context training.
"""

from pytorch_distributed_tpu.parallel.sharding import (
    PartitionRules,
    infer_sharding,
    infer_tree_shardings,
    shard_along,
    with_sharding_constraint,
)
from pytorch_distributed_tpu.parallel.strategies import (
    Strategy,
    DataParallel,
    ZeRO1,
    FSDP,
)
from pytorch_distributed_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
    enable_sequence_parallel,
    sequence_parallel,
    disable_sequence_parallel,
    sequence_parallel_mode,
)
from pytorch_distributed_tpu.parallel.pipeline import (
    pipeline_forward,
    stage_sharding,
    split_microbatches,
    merge_microbatches,
)
from pytorch_distributed_tpu.parallel.ddp import (
    is_multiprocess,
    no_sync,
    sync_grads,
)

__all__ = [
    "PartitionRules",
    "infer_sharding",
    "infer_tree_shardings",
    "shard_along",
    "with_sharding_constraint",
    "Strategy",
    "DataParallel",
    "ZeRO1",
    "FSDP",
    "ring_attention",
    "ulysses_attention",
    "enable_sequence_parallel",
    "sequence_parallel",
    "disable_sequence_parallel",
    "sequence_parallel_mode",
    "pipeline_forward",
    "stage_sharding",
    "split_microbatches",
    "merge_microbatches",
    "is_multiprocess",
    "no_sync",
    "sync_grads",
]
