"""DDP / ZeRO-1 / FSDP as sharding configurations of one SPMD mechanism.

Equivalence map to the reference's wrappers (BASELINE.json:5,10,11):

=================  ==========================  =============================
reference          torch mechanism             here: sharding of
=================  ==========================  =============================
DDP                grad hooks + bucketed       params/opt replicated, batch
                   NCCL allreduce              sharded over dp -> XLA emits
                                               one fused grad allreduce
ZeRO-1             ZeroRedundancyOptimizer     + optimizer state sharded
                   (per-rank shard + param     over dp -> XLA emits
                   broadcast after step)       reduce-scatter(grads) +
                                               allgather(updated params)
                                               ("cross-replica weight
                                               update sharding",
                                               PAPERS.md:5)
FSDP               flat-param shards,          + params sharded over fsdp ->
                   per-layer allgather /       XLA emits per-use allgather
                   reduce-scatter hooks        and grad reduce-scatter
=================  ==========================  =============================

Tensor-parallel rules (model-provided, path-based) compose with any of the
three: TP-matched tensors keep their TP axes, and FSDP augments them with
an ``fsdp`` axis on the largest still-unsharded divisible dim.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.parallel.sharding import (
    PartitionRules,
    infer_opt_tree_shardings,
    infer_tree_shardings,
    place_global_batch,
    shard_along,
)
from pytorch_distributed_tpu.runtime.mesh import current_mesh, data_axes


def _augment_spec_with_axis(spec: P, axis: str, shape, mesh: Mesh) -> P:
    """Add ``axis`` to the largest unsharded, divisible dim of ``spec``."""
    size = mesh.shape[axis]
    if size == 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for e in entries:
        for a in (e if isinstance(e, tuple) else (e,)):
            if a is not None:
                used.add(a)
    if axis in used:
        return spec
    candidates = [
        i for i, (e, d) in enumerate(zip(entries, shape))
        if e is None and d % size == 0 and d >= size
    ]
    if not candidates:
        return spec
    best = max(candidates, key=lambda i: shape[i])
    entries[best] = axis
    return P(*entries)


class Strategy:
    """Base: replicate everything (single-device semantics on any mesh).

    ``extra_rules`` are model-provided tensor-parallel rules; they apply to
    params (and are mirrored onto same-shaped optimizer-state leaves by
    shape-matching fallback in subclasses).
    """

    #: global batch is split over these mesh axes
    batch_axes: Tuple[str, ...] = data_axes()

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        *,
        extra_rules: Sequence[Tuple[str, object]] = (),
    ):
        self.mesh = mesh or current_mesh()
        self.extra_rules = tuple(extra_rules)

    # -- override points ----------------------------------------------------
    def _fallback_param_spec(self):
        return None  # replicate

    def _fallback_opt_spec(self):
        return None

    def _transform_tp_param_spec(self, spec: P, shape) -> P:
        return spec

    def _transform_tp_opt_spec(self, spec: P, shape) -> P:
        return spec

    # -- rule assembly ------------------------------------------------------
    def param_rules(self) -> PartitionRules:
        tp = [
            (pat, self._wrap_tp(spec, self._transform_tp_param_spec))
            for pat, spec in self.extra_rules
        ]
        return PartitionRules(tp + [(".*", self._fallback_param_spec())])

    def opt_rules(self) -> PartitionRules:
        # Optimizer moments mirror param shapes, and optax state pytrees
        # embed the param tree, so path-based TP rules still match (paths
        # end with the param path). Scalars (count, ...) match nothing
        # divisible and replicate.
        tp = [
            (pat, self._wrap_tp(spec, self._transform_tp_opt_spec))
            for pat, spec in self.extra_rules
        ]
        return PartitionRules(tp + [(".*", self._fallback_opt_spec())])

    def _wrap_tp(self, spec, transform):
        def wrapped(shape, mesh):
            s = spec(shape, mesh) if callable(spec) else spec
            if s is None:
                s = P()
            return transform(s, shape)

        return wrapped

    # -- placement ----------------------------------------------------------
    def state_shardings(self, state):
        """TrainState-of-NamedShardings matching ``state``'s structure."""
        repl = NamedSharding(self.mesh, P())
        params = infer_tree_shardings(state.params, self.param_rules(), self.mesh)
        opt = infer_opt_tree_shardings(
            state.opt_state, state.params, self.opt_rules(), self.mesh,
            # shape-mismatched states (factored stats) skip the TP path
            # rules and take the shape-generic fallback, safe on any rank
            mismatch_rules=PartitionRules(
                [(".*", self._fallback_opt_spec())]
            ),
        )
        aux = jax.tree_util.tree_map(lambda _: repl, state.batch_stats)
        scaler = jax.tree_util.tree_map(lambda _: repl, state.scaler_state)
        # EMA shadow params: identical tree and rules — reuse the params
        # shardings so "the shadow shards exactly like params" holds by
        # construction (FSDP memory would double otherwise)
        ema = params if state.ema_params is not None else None
        return state.replace(
            step=repl, params=params, opt_state=opt,
            batch_stats=aux, scaler_state=scaler, ema_params=ema,
        )

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.batch_axes))

    def place(self, state):
        """device_put the state according to this strategy's shardings."""
        return jax.device_put(state, self.state_shardings(state))

    def create_sharded(self, make_state_fn, *args):
        """Build a state directly onto its shards — no replicated copy ever
        exists. This is how pod-scale models (Llama-3-8B FSDP,
        BASELINE.json:11) must initialize: ``make_state_fn`` (e.g.
        ``lambda key: TrainState.create(... model.init(key, x) ...)``) is
        traced abstractly, its shardings inferred, then jitted with
        out_shardings so every device materializes only its own shard."""
        abstract = jax.eval_shape(make_state_fn, *args)
        shardings = self.state_shardings(abstract)
        return jax.jit(make_state_fn, out_shardings=shardings)(*args)

    def shard_batch(self, batch):
        """Place a host batch on the mesh, dim 0 split over the data axes.

        Single host: a plain sharded device_put. Multi-host (pod): each
        controller passes its PROCESS-LOCAL slice of the global batch
        (the DistributedSampler contract) and the global array is
        assembled without any cross-host transfer —
        ``jax.make_array_from_process_local_data`` validates that local
        shapes tile the global shape.
        """
        return place_global_batch(self.batch_sharding(), batch, local=True)

    def compile(self, step_fn, state, *, donate: bool = True,
                donate_batch: bool = False):
        """jit ``step_fn(state, batch) -> (state, metrics)`` with this
        strategy's shardings pinned on state in/out (donating the input
        state buffers, like an in-place optimizer step).

        ``donate_batch`` additionally donates the BATCH buffers — right
        for the loader-fed hot loop, where every batch is consumed
        exactly once: the uint8 ingest buffer is released the moment the
        fused on-device normalize reads it, instead of pinning HBM until
        the step retires. Leave False when a caller re-feeds the same
        placed batch (the synthetic-batch benches)."""
        st_sh = self.state_shardings(state)
        donate_argnums = (0,) if donate else ()
        if donate_batch:
            donate_argnums = donate_argnums + (1,)
        return jax.jit(
            step_fn,
            in_shardings=(st_sh, self.batch_sharding()),
            out_shardings=(st_sh, None),
            donate_argnums=donate_argnums,
        )

    def describe(self) -> str:
        return (
            f"{type(self).__name__}(mesh={dict(self.mesh.shape)}, "
            f"batch_axes={self.batch_axes})"
        )


class DataParallel(Strategy):
    """DDP equivalent: replicated params/opt, dp-sharded batch.

    The backward's gradient sum over the batch axis becomes a single XLA
    allreduce over ``dp`` — the compiler-scheduled analogue of DDP's
    bucketed overlap (BASELINE.json:5); bucketing/overlap is XLA's job.
    """


class ZeRO1(DataParallel):
    """ZeRO-1: DataParallel + optimizer state sharded over ``dp``.

    The weight update runs on 1/dp-th of the elements per device, then the
    updated params are (compiler-)allgathered — per-tensor cross-replica
    weight-update sharding (PAPERS.md:5; reference:
    ZeroRedundancyOptimizer, BASELINE.json:10).
    """

    def __init__(self, mesh=None, *, axis="dp", **kw):
        super().__init__(mesh, **kw)
        self.axis = axis

    def _fallback_opt_spec(self):
        return shard_along(self.axis)

    def _transform_tp_opt_spec(self, spec, shape):
        # TP-sharded moments additionally split over dp where possible;
        # params stay replicated (that's what makes this ZeRO-1, not FSDP).
        return _augment_spec_with_axis(spec, self.axis, shape, self.mesh)


class FSDP(Strategy):
    """Fully-sharded: params AND optimizer state sharded over ``fsdp``
    (+ batch over the data axes). XLA inserts per-use allgather of params
    and reduce-scatter of grads — the hook-free analogue of torch FSDP's
    FlatParameter machinery (BASELINE.json:11)."""

    def __init__(self, mesh=None, *, axis="fsdp", **kw):
        super().__init__(mesh, **kw)
        self.axis = axis

    def _fallback_param_spec(self):
        return shard_along(self.axis)

    def _fallback_opt_spec(self):
        return shard_along(self.axis)

    def _transform_tp_param_spec(self, spec, shape):
        return _augment_spec_with_axis(spec, self.axis, shape, self.mesh)

    _transform_tp_opt_spec = _transform_tp_param_spec
