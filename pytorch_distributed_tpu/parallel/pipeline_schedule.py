"""Host-dispatched pipeline-parallel schedules: 1F1B over the hostring (r20).

The SPMD GPipe sketch (``parallel/pipeline.py``) runs every stage inside ONE
jitted program: each of the ``M + S - 1`` ppermute ticks makes *every* stage
compute, so the warm-up/cool-down bubble — an ``(S-1)/(M+S-1)`` fraction of
the ticks — is paid in real FLOPs on garbage microbatches. This module is the
host-dispatched alternative in the ``HostLoopStep`` discipline: each rank owns
ONE stage, compiles its forward and backward once each, and a host loop issues
the ops of a precomputed schedule, linking neighbor stages with
``hostring.send/recv`` activation/grad handoffs tagged by
``(microbatch, stage, direction)`` through the DETAIL fingerprint handshake.

Two schedule shapes, both pure functions of ``(stage, S, M)``:

* ``schedule_gpipe`` — all ``M`` forwards, then all ``M`` backwards. Simple,
  but every stage must hold all ``M`` in-flight microbatch inputs at the
  fwd/bwd boundary (``peak_live_microbatches == M``).
* ``schedule_1f1b`` — ``min(S-1-stage, M)`` warm-up forwards, then the 1F1B
  steady state (one forward, one backward, alternating), then the cool-down
  backwards. At most ``min(S - stage, M)`` microbatches are ever live per
  stage — bounded by ``S`` regardless of ``M``: the memory win over GPipe.
  Wall-clock is the same ``(M + S - 1)`` tick critical path as an honest
  host GPipe; the bubble fraction both pay is the analytic
  ``(S-1)/(M+S-1)`` (``bubble_fraction``), which ``autoplan/pricing.py``
  charges when ranking pp candidates.

Because the issue order is a pure function of ``(stage, S, M)``, lockstep is
by construction: there is no rank-conditional branch around a send/recv for
ptdlint's PTD001 to distrust — the executor walks the op list and dispatches
on ``op.kind`` (see ``tests/lint_fixtures/ptd001_pipeline_good.py``).

Interleaved virtual stages (``schedule_interleaved``) shrink the bubble to
``(S-1)/(V*M + S-1)`` by giving each rank ``V`` non-contiguous layer chunks;
the schedule/mapping math ships tested here, the executor runs ``V == 1``
(honest limits in docs/DESIGN.md §25).

Deadlock discipline: the shm transport's P2P mailboxes buffer ONE in-flight
message per ordered rank pair (native/hostring.cpp), and activations
(``s -> s+1``) and grads (``s+1 -> s``) ride *different* ordered pairs.
``simulate_links`` replays any schedule set against exactly that channel
model; the (S, M) grid test pins that both shapes drain without deadlock and
without tag reordering.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# -- op kinds (strings, not an enum: they appear in fault paths and traces) --
RECV_ACT = "recv_act"
FWD = "fwd"
SEND_ACT = "send_act"
RECV_GRAD = "recv_grad"
BWD = "bwd"
SEND_GRAD = "send_grad"

COMPUTE_KINDS = (FWD, BWD)
COMM_KINDS = (RECV_ACT, SEND_ACT, RECV_GRAD, SEND_GRAD)


@dataclass(frozen=True)
class StageOp:
    """One schedule slot: ``kind`` over microbatch ``mb`` (chunk = the
    virtual-stage index on this rank; 0 unless interleaved)."""

    kind: str
    mb: int
    chunk: int = 0


def _check_args(stage: int, num_stages: int, num_microbatches: int) -> None:
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if not 0 <= stage < num_stages:
        raise ValueError(f"stage {stage} outside [0, {num_stages})")
    if num_microbatches < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}"
        )


def _attach_comms(
    skeleton: Sequence[Tuple[str, int]], stage: int, num_stages: int
) -> Tuple[StageOp, ...]:
    """Wrap a (kind, mb) compute skeleton with the neighbor handoffs: a
    non-first stage receives its input activation just-in-time before each
    forward; a non-last stage sends the activation right after, receives the
    output grad just-in-time before each backward; a non-first stage sends
    the input grad right after."""
    first = stage == 0
    last = stage == num_stages - 1
    ops: List[StageOp] = []
    for kind, mb in skeleton:
        if kind == FWD:
            if not first:
                ops.append(StageOp(RECV_ACT, mb))
            ops.append(StageOp(FWD, mb))
            if not last:
                ops.append(StageOp(SEND_ACT, mb))
        else:
            if not last:
                ops.append(StageOp(RECV_GRAD, mb))
            ops.append(StageOp(BWD, mb))
            if not first:
                ops.append(StageOp(SEND_GRAD, mb))
    return tuple(ops)


def schedule_1f1b(
    stage: int, num_stages: int, num_microbatches: int
) -> Tuple[StageOp, ...]:
    """The 1F1B op list for ``stage``: warm-up ``min(S-1-stage, M)``
    forwards, steady-state (fwd, bwd) pairs, cool-down backwards.

    Pure function of ``(stage, num_stages, num_microbatches)`` — the
    lockstep-by-construction property every caller leans on. Backwards
    complete in increasing microbatch order, so a left fold over them is
    the same association ``lax.scan``'s accumulation uses.
    """
    _check_args(stage, num_stages, num_microbatches)
    warmup = min(num_stages - 1 - stage, num_microbatches)
    skeleton: List[Tuple[str, int]] = []
    f = b = 0
    for _ in range(warmup):
        skeleton.append((FWD, f))
        f += 1
    for _ in range(num_microbatches - warmup):
        skeleton.append((FWD, f))
        f += 1
        skeleton.append((BWD, b))
        b += 1
    for _ in range(warmup):
        skeleton.append((BWD, b))
        b += 1
    return _attach_comms(skeleton, stage, num_stages)


def schedule_gpipe(
    stage: int, num_stages: int, num_microbatches: int
) -> Tuple[StageOp, ...]:
    """The host GPipe op list: all forwards, then all backwards. Same
    ``(M + S - 1)``-tick critical path as 1F1B, but the stage must hold all
    ``M`` microbatch inputs at the fwd/bwd boundary — the memory cost
    ``schedule_1f1b`` exists to avoid."""
    _check_args(stage, num_stages, num_microbatches)
    skeleton = [(FWD, i) for i in range(num_microbatches)]
    skeleton += [(BWD, i) for i in range(num_microbatches)]
    return _attach_comms(skeleton, stage, num_stages)


def virtual_stage(rank: int, chunk: int, world: int) -> int:
    """Global stage id of ``chunk`` on ``rank`` under interleaving: chunk
    ``v`` of rank ``r`` runs global stage ``v * world + r`` — consecutive
    global stages land on consecutive ranks, so every chunk boundary is a
    one-hop neighbor handoff."""
    return chunk * world + rank


def schedule_interleaved(
    rank: int, world: int, num_chunks: int, num_microbatches: int
) -> Tuple[StageOp, ...]:
    """Interleaved-virtual-stage 1F1B (Megatron-style): each rank runs
    ``num_chunks`` layer chunks, microbatches advance in groups of
    ``world``, and the warm-up is deep enough to keep every chunk fed.

    Compute ops only (``chunk`` = local chunk index; the global stage is
    ``virtual_stage(rank, chunk, world)``) — this is the schedule/mapping
    math the planner prices and the tests pin; the executor runs V == 1.
    Requires ``num_microbatches % world == 0`` (the grouping invariant).
    """
    if world < 1 or not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside [0, {world})")
    if num_chunks < 2:
        raise ValueError(
            "interleaving needs num_chunks >= 2 — V == 1 is plain 1F1B "
            "(schedule_1f1b)"
        )
    if num_microbatches % world:
        raise ValueError(
            f"interleaved schedule needs num_microbatches divisible by "
            f"world, got M={num_microbatches} world={world}"
        )
    total = num_microbatches * num_chunks
    warmup = min((world - rank - 1) * 2 + (num_chunks - 1) * world, total)

    def fwd_op(k: int) -> StageOp:
        chunk = (k // world) % num_chunks
        mb = (k // (world * num_chunks)) * world + k % world
        return StageOp(FWD, mb, chunk)

    def bwd_op(k: int) -> StageOp:
        chunk = num_chunks - 1 - (k // world) % num_chunks
        mb = (k // (world * num_chunks)) * world + k % world
        return StageOp(BWD, mb, chunk)

    ops = [fwd_op(k) for k in range(warmup)]
    for k in range(warmup, total):
        ops.append(fwd_op(k))
        ops.append(bwd_op(k - warmup))
    for k in range(total - warmup, total):
        ops.append(bwd_op(k))
    return tuple(ops)


def bubble_fraction(
    num_stages: int, num_microbatches: int, num_chunks: int = 1
) -> float:
    """The analytic pipeline bubble: the fraction of the steady-state
    critical path spent waiting for the pipe to fill and drain —
    ``(S-1) / (V*M + S-1)``. This is the price ``autoplan/pricing.py``
    multiplies into a pp candidate's compute seconds."""
    if num_stages < 1 or num_microbatches < 1 or num_chunks < 1:
        raise ValueError(
            f"need S, M, V >= 1, got ({num_stages}, {num_microbatches}, "
            f"{num_chunks})"
        )
    return (num_stages - 1) / (
        num_chunks * num_microbatches + num_stages - 1
    )


def peak_live_microbatches(program: Sequence[StageOp]) -> int:
    """Max concurrently-live microbatches implied by a schedule: a forward
    stashes its input until the matching backward retires it. For 1F1B
    stage ``s`` this is ``min(S - s, M)`` (<= S everywhere); for GPipe it
    is ``M`` at every stage — the accounting behind the memory claim."""
    live = peak = 0
    for op in program:
        if op.kind == FWD:
            live += 1
            peak = max(peak, live)
        elif op.kind == BWD:
            live -= 1
    return peak


def stage_depths(
    num_layers: int,
    num_stages: int,
    rank_rates: Optional[Sequence[float]] = None,
) -> Tuple[int, ...]:
    """Layers per stage. Even split when ``rank_rates`` is None (requires
    divisibility — refusing beats silently unbalancing a homogeneous
    fleet); with per-rank rates, the ``train/balance.py`` apportionment
    gives a slow rank a proportionally shallower stage (floor 1 layer)."""
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if num_layers < num_stages:
        raise ValueError(
            f"{num_layers} layers cannot fill {num_stages} stages"
        )
    if rank_rates is None:
        if num_layers % num_stages:
            raise ValueError(
                f"{num_layers} layers not divisible by {num_stages} "
                "stages — pass rank_rates to apportion unevenly"
            )
        return (num_layers // num_stages,) * num_stages
    if len(rank_rates) != num_stages:
        raise ValueError(
            f"{len(rank_rates)} rates for {num_stages} stages"
        )
    from pytorch_distributed_tpu.train.balance import (
        apportion,
        quantize_rates,
    )

    return tuple(apportion(num_layers, quantize_rates(rank_rates), floor=1))


def stage_layer_slices(
    depths: Sequence[int],
) -> Tuple[Tuple[int, int], ...]:
    """(start, stop) layer ranges per stage for a depth list."""
    out, start = [], 0
    for d in depths:
        out.append((start, start + d))
        start += d
    return tuple(out)


class ScheduleDeadlock(RuntimeError):
    """Raised by :func:`simulate_links` when no stage can advance."""


def simulate_links(
    programs: Sequence[Sequence[StageOp]], capacity: int = 1
) -> int:
    """Replay per-stage op lists against the shm transport's channel model
    (one mailbox per ordered rank pair, ``capacity`` buffered messages —
    native/hostring.cpp buffers exactly one) and return the number of
    round-robin passes to drain. Raises :class:`ScheduleDeadlock` if every
    stage blocks, and ValueError if a receive would consume a message out
    of tag order — the static form of the DETAIL fingerprint mismatch."""
    num_stages = len(programs)
    pcs = [0] * num_stages
    chans: Dict[Tuple[int, int], List[Tuple[str, int]]] = {}
    passes = 0
    while any(pc < len(programs[s]) for s, pc in enumerate(pcs)):
        progressed = False
        passes += 1
        for s in range(num_stages):
            if pcs[s] >= len(programs[s]):
                continue
            op = programs[s][pcs[s]]
            if op.kind in COMPUTE_KINDS:
                pcs[s] += 1
                progressed = True
                continue
            direction = "act" if op.kind in (RECV_ACT, SEND_ACT) else "grad"
            if op.kind == SEND_ACT:
                pair = (s, s + 1)
            elif op.kind == SEND_GRAD:
                pair = (s, s - 1)
            elif op.kind == RECV_ACT:
                pair = (s - 1, s)
            else:
                pair = (s + 1, s)
            chan = chans.setdefault(pair, [])
            if op.kind in (SEND_ACT, SEND_GRAD):
                if len(chan) < capacity:
                    chan.append((direction, op.mb))
                    pcs[s] += 1
                    progressed = True
            else:
                if chan:
                    if chan[0] != (direction, op.mb):
                        raise ValueError(
                            f"stage {s} expects {direction}.m{op.mb} but "
                            f"channel {pair} holds {chan[0]} — schedule "
                            "would trip the fingerprint handshake"
                        )
                    chan.pop(0)
                    pcs[s] += 1
                    progressed = True
        if not progressed:
            stuck = {
                s: str(programs[s][pc])
                for s, pc in enumerate(pcs) if pc < len(programs[s])
            }
            raise ScheduleDeadlock(
                f"no stage can advance after {passes} passes: {stuck}"
            )
    return passes


def pipeline_trace_stats(
    events: Sequence[dict],
) -> Dict[int, Dict[str, float]]:
    """Per-rank busy/bubble/link accounting from merged chrome-trace
    events (``scripts/trace_merge.py`` output: ``pid`` = rank, us).

    For each rank with ``pipeline.fwd``/``pipeline.bwd`` spans: ``busy_s``
    is their summed duration, ``window_s`` the first-start to last-end
    extent, ``bubble`` the idle fraction ``1 - busy/window``, and
    ``link_s`` the summed ``comm.send``/``comm.recv`` span time inside the
    window — all exposed on the serial host loop, so ``link_s/window_s``
    IS the exposed-link ratio the bench pins."""
    by_rank: Dict[int, Dict[str, float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        if name in ("pipeline.fwd", "pipeline.bwd"):
            key = "busy"
        elif name in ("comm.send", "comm.recv"):
            key = "link"
        else:
            continue
        rank = int(ev.get("pid", 0))
        rec = by_rank.setdefault(
            rank, {"busy": 0.0, "link": 0.0, "t0": float("inf"), "t1": 0.0}
        )
        rec[key] += float(ev.get("dur", 0.0))
        if key == "busy":
            rec["t0"] = min(rec["t0"], float(ev["ts"]))
            rec["t1"] = max(rec["t1"], float(ev["ts"]) + float(ev["dur"]))
    out: Dict[int, Dict[str, float]] = {}
    for rank, rec in sorted(by_rank.items()):
        window = max(rec["t1"] - rec["t0"], 1e-9)
        out[rank] = {
            "busy_s": rec["busy"] / 1e6,
            "link_s": rec["link"] / 1e6,
            "window_s": window / 1e6,
            "bubble": max(0.0, 1.0 - rec["busy"] / window),
        }
    return out


class HostPipelineStep:
    """Host-dispatched pipeline stage executor: one rank, one stage, one
    fwd and one bwd program compiled once each (the ``HostLoopStep``
    prep/grad/apply idiom applied to a stage), activations and grads
    linked over ``hostring.send/recv`` with ``(microbatch, stage,
    direction)`` tags through the DETAIL fingerprint handshake.

    ``programs`` supplies the per-stage math (``parallel/pipeline_lm.py``
    builds the GPT-2 bridge):

    * non-last stages: ``fwd(params, xin) -> y`` and
      ``bwd(params, xin, dy) -> (grads, dx)`` (first stage:
      ``bwd(params, ids_mb, dy) -> grads`` — integer inputs have no dx);
      the backward re-derives the forward via ``jax.vjp`` inside the jit,
      so only the stage INPUT is stashed per live microbatch — the
      ``peak_live_microbatches`` accounting is exactly the executor's
      stash size.
    * last stage (S > 1): ``loss_grad(params, head_wte, x, ids_mb) ->
      (loss, grads, head_grad, dx)``; S == 1:
      ``loss_grad_solo(params, ids_mb) -> (loss, grads)``.
    * optional ``exchange_grads(group, stage, num_stages, grads,
      aux_grad)`` / ``exchange_params(group, stage, num_stages, params,
      buffers)`` hooks for tied weights (the GPT-2 bridge pairs the
      first/last wte replicas over tagged P2P).

    Grads are left-folded in microbatch order (1F1B backwards complete in
    increasing mb order, so this is ``lax.scan``'s association) and scaled
    by ``1/M`` inside the jitted ``apply`` — the exact-multiply step.
    Cross-stage reductions inside the optimizer (global-norm clipping) are
    out of scope: ``tx`` must be elementwise per stage (DESIGN.md §25).

    ``delay_s`` sleeps that long before each compute op, OUTSIDE the math
    (the r18 ``prefill_delay_s`` idiom): a 1-core box then behaves like an
    S-deep pipeline because sleeps overlap across processes — the bench's
    bubble-measurement shaping, with bit-identity to the delay-free run
    enforced by CRC.
    """

    def __init__(
        self,
        programs,
        *,
        stage: int,
        num_stages: int,
        num_microbatches: int,
        tx,
        group=None,
        schedule: str = "1f1b",
        act_template: Optional[np.ndarray] = None,
        delay_s: float = 0.0,
        ids_key: str = "input_ids",
    ):
        import jax

        if schedule == "1f1b":
            self.program = schedule_1f1b(stage, num_stages, num_microbatches)
        elif schedule == "gpipe":
            self.program = schedule_gpipe(
                stage, num_stages, num_microbatches
            )
        else:
            raise ValueError(
                f"schedule must be '1f1b' or 'gpipe', got {schedule!r}"
            )
        if num_stages > 1 and group is None:
            raise ValueError("num_stages > 1 needs a hostring group")
        if num_stages > 1 and act_template is None:
            raise ValueError("num_stages > 1 needs an act_template buffer")
        self.stage = stage
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.group = group
        self.delay_s = float(delay_s)
        self.ids_key = ids_key
        self.programs = programs
        self._first = stage == 0
        self._last = stage == num_stages - 1
        self._act_buf = (
            None if act_template is None
            else np.ascontiguousarray(act_template)
        )
        # fault paths precomputed so the armed-site poll stays a Name arg
        self._paths = tuple(
            f"s{stage}.{op.kind}.m{op.mb}" for op in self.program
        )
        self._tx = tx
        inv = 1.0 / num_microbatches

        def apply_fn(params, opt_state, grads):
            g = jax.tree_util.tree_map(lambda a: a * inv, grads)
            updates, new_opt = tx.update(g, opt_state, params)
            import optax

            return optax.apply_updates(params, updates), new_opt

        self._jits: Dict[str, object] = {"apply": jax.jit(apply_fn)}
        if num_stages == 1:
            self._jits["loss_grad"] = jax.jit(programs.loss_grad_solo)
        elif self._last:
            self._jits["loss_grad"] = jax.jit(programs.loss_grad)
        else:
            self._jits["fwd"] = jax.jit(programs.fwd)
            self._jits["bwd"] = jax.jit(programs.bwd)

    def compile_counts(self) -> Dict[str, Optional[int]]:
        """Jit-cache sizes per program — the pin is 1 per program per
        distinct microbatch shape (the compile-count correctness bar)."""
        from pytorch_distributed_tpu.runtime.compat import jit_cache_size

        return {k: jit_cache_size(v) for k, v in sorted(self._jits.items())}

    def init_opt_state(self, params):
        return self._tx.init(params)

    # -- internals ----------------------------------------------------------
    def _pause(self, path):
        from pytorch_distributed_tpu.runtime import faults

        faults.check("pipeline.stage_stall", path)
        act = faults.hang_action("pipeline.stage_stall", path)
        if act is not None and act[0] == "stall":
            time.sleep(act[1])
        if self.delay_s > 0.0:
            time.sleep(self.delay_s)

    def _recv(self, src, tag):
        got = self.group.recv(self._act_buf, src, tag=tag)
        return np.array(got)  # the proto buffer is reused between recvs

    @staticmethod
    def _split(batch, num_microbatches: int) -> List[dict]:
        out = []
        for i in range(num_microbatches):
            mb = {}
            for k, v in batch.items():
                n = v.shape[0]
                if n % num_microbatches:
                    raise ValueError(
                        f"batch dim {n} not divisible by "
                        f"{num_microbatches} microbatches"
                    )
                size = n // num_microbatches
                mb[k] = np.asarray(v[i * size:(i + 1) * size])
            out.append(mb)
        return out

    @staticmethod
    def _fold(acc, tree):
        """Left fold in numpy — IEEE f32 adds in the same fixed order as
        ``lax.scan``'s accumulation, so the sum is the scan association."""
        leaves = _tree_leaves(tree)
        if acc is None:
            # own the accumulator: views of jax buffers are read-only
            return [np.array(x) for x in leaves]
        for a, b in zip(acc, leaves):
            np.add(a, np.asarray(b), out=a)
        return acc

    def step(self, params, opt_state, batch, buffers=None):
        """One optimizer step: returns ``(params, opt_state, metrics)``.
        ``buffers`` carries non-optimized replicas (the tied head wte on
        the last stage); updated in place via ``exchange_params``."""
        from pytorch_distributed_tpu.runtime import tracing

        mbs = self._split(batch, self.num_microbatches)
        stash: Dict[int, object] = {}
        dys: Dict[int, object] = {}
        dxs: Dict[int, np.ndarray] = {}
        grads_acc = None
        aux_acc = None
        grads_struct = None
        losses: List[float] = []
        st = self.stage
        for op, path in zip(self.program, self._paths):
            mb = op.mb
            if op.kind == RECV_ACT:
                stash[mb] = self._recv(st - 1, tag=f"act.m{mb}.s{st}")
            elif op.kind == SEND_ACT:
                self.group.send(
                    stash.pop((SEND_ACT, mb)), st + 1,
                    tag=f"act.m{mb}.s{st + 1}",
                )
            elif op.kind == RECV_GRAD:
                dys[mb] = self._recv(st + 1, tag=f"grad.m{mb}.s{st}")
            elif op.kind == SEND_GRAD:
                self.group.send(
                    dxs.pop(mb), st - 1, tag=f"grad.m{mb}.s{st - 1}"
                )
            elif op.kind == FWD:
                with tracing.span("pipeline.fwd", mb=mb, stage=st):
                    self._pause(path)
                    if self._last:
                        # forward runs inside the last stage's loss_grad
                        # program (value_and_grad); this slot only admits
                        # the microbatch into the pipe
                        if self._first:
                            stash[mb] = mbs[mb][self.ids_key]
                        continue
                    xin = (
                        mbs[mb][self.ids_key] if self._first
                        else stash.pop(mb)
                    )
                    stash[mb] = xin  # retired by the matching BWD
                    y = self._jits["fwd"](params, xin)
                    y.block_until_ready()
                    stash[(SEND_ACT, mb)] = np.asarray(y)
            else:  # BWD
                with tracing.span("pipeline.bwd", mb=mb, stage=st):
                    self._pause(path)
                    if self.num_stages == 1:
                        loss, grads = self._jits["loss_grad"](
                            params, stash.pop(mb)
                        )
                        _block_tree(grads)
                    elif self._last:
                        loss, grads, head_grad, dx = self._jits[
                            "loss_grad"
                        ](
                            params, buffers["head_wte"], stash.pop(mb),
                            mbs[mb][self.ids_key],
                        )
                        _block_tree(grads)
                        aux_acc = self._fold(aux_acc, head_grad)
                        dxs[mb] = np.asarray(dx)
                    elif self._first:
                        grads = self._jits["bwd"](
                            params, stash.pop(mb), dys.pop(mb)
                        )
                        _block_tree(grads)
                    else:
                        grads, dx = self._jits["bwd"](
                            params, stash.pop(mb), dys.pop(mb)
                        )
                        _block_tree(grads)
                        dxs[mb] = np.asarray(dx)
                    if grads_struct is None:
                        grads_struct = _tree_structure(grads)
                    grads_acc = self._fold(grads_acc, grads)
                    if self._last:
                        losses.append(float(loss))
        assert not stash and not dys and not dxs, (
            f"stage {st} retired the schedule with live state: "
            f"{list(stash)} {list(dys)} {list(dxs)}"
        )
        grads = _tree_unflatten(grads_struct, grads_acc)
        if self.group is not None and hasattr(
            self.programs, "exchange_grads"
        ):
            grads = self.programs.exchange_grads(
                self.group, self.stage, self.num_stages, grads,
                aux_acc[0] if aux_acc else None,
            )
        params, opt_state = self._jits["apply"](params, opt_state, grads)
        _block_tree(params)
        if self.group is not None and hasattr(
            self.programs, "exchange_params"
        ):
            self.programs.exchange_params(
                self.group, self.stage, self.num_stages, params, buffers
            )
        metrics = {}
        if losses:
            metrics["loss"] = float(np.mean(losses))
        return params, opt_state, metrics


def _tree_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def _tree_structure(tree):
    import jax

    return jax.tree_util.tree_structure(tree)


def _tree_unflatten(struct, leaves):
    import jax

    return jax.tree_util.tree_unflatten(struct, leaves)


def _block_tree(tree) -> None:
    for leaf in _tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
