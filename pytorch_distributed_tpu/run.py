"""``python -m pytorch_distributed_tpu.run`` — the torchrun equivalent.

torchrun-shaped flags over the ElasticAgent supervisor (launch.py):

    python -m pytorch_distributed_tpu.run --nproc-per-node 4 \
        recipes/resnet18_cifar10.py --synthetic --steps-per-epoch 5

Workers get RANK/WORLD_SIZE/LOCAL_RANK/... env; ``init_process_group``
inside the script joins the native hostring backend (multi-process CPU,
the reference's gloo path) or, with ``--platform tpu`` on a pod, each
worker drives its own slice after ``init_multihost()``.
"""

from __future__ import annotations

import argparse
import sys

from pytorch_distributed_tpu.launch import ElasticAgent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="pytorch_distributed_tpu.run",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--nproc-per-node", type=int, default=1)
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--node-rank", type=int, default=0)
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument(
        "--platform", default="cpu", choices=("cpu", "tpu"),
        help="worker JAX platform; cpu = hostring smoke path",
    )
    parser.add_argument(
        "--standalone", action="store_true",
        help="single-node shorthand (accepted for torchrun parity; implied)",
    )
    parser.add_argument("--master-addr", default=None)
    parser.add_argument("--master-port", default=None)
    parser.add_argument(
        "-m", "--module", action="store_true",
        help="treat script as a python module name (python -m style)",
    )
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    cmd = [sys.executable]
    if args.module:
        cmd += ["-m"]
    cmd += [args.script] + args.script_args
    extra_env = {}
    if args.master_addr:
        extra_env["MASTER_ADDR"] = args.master_addr
    if args.master_port:
        extra_env["MASTER_PORT"] = args.master_port
    agent = ElasticAgent(
        cmd=cmd,
        nproc_per_node=args.nproc_per_node,
        nnodes=args.nnodes,
        node_rank=args.node_rank,
        max_restarts=args.max_restarts,
        platform=args.platform,
        extra_env=extra_env,
    )
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
