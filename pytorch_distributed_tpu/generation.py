"""Autoregressive generation — KV-cache decode, TPU-first.

The reference is a training-recipe repo; inference is table stakes for a
complete framework, and on TPU it has one idiomatic shape:

* **Static everything.** The KV cache is a fixed [B, max_len, H, D] buffer
  per layer (``ops.attention.decode_cache``), written with
  ``dynamic_update_slice``; the token loop is a ``lax.scan`` of a
  fixed-shape single-token step. One compile serves the whole generation,
  regardless of prompt length or tokens produced.
* **Prefill + decode.** The prompt runs through the model ONCE at full
  width (MXU-efficient), filling the cache; then the scan emits one token
  per tick. This is the standard split CUDA inference engines arrive at —
  XLA gets it from tracing two calls of the same model.
* Works with any model that takes ``decode=True`` and maintains flax
  ``cache`` collection state (GPT2LMHead, LlamaForCausalLM).

Sampling: greedy (``temperature=0``), temperature, top-k, and top-p
(nucleus) — enough to smoke-test every recipe's model family offline.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _validate_filters(top_k, top_p) -> None:
    """One home for the sampler-filter argument checks, shared by
    sample_logits (which must also raise on the greedy early-return
    path) and filter_logits (so direct consumers like speculative
    decoding are guarded without routing through sample_logits)."""
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")


def filter_logits(
    logits: jnp.ndarray,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jnp.ndarray:
    """Temperature-scaled, k/p-filtered f32 logits ([..., vocab]).

    The exact distribution ``sample_logits`` draws from, exposed so
    rejection-sampling consumers (speculative decoding) can compute the
    same probabilities the sampler uses. ``temperature`` must be > 0
    (greedy has no distribution to filter).
    """
    if temperature <= 0.0:
        raise ValueError(
            f"filter_logits needs temperature > 0, got {temperature}"
        )
    _validate_filters(top_k, top_p)
    if top_k is not None:
        # HF clamps k to the vocab size; without this, k >= vocab fails
        # with an opaque out-of-bounds index at trace time
        top_k = min(top_k, logits.shape[-1])
    neg_inf = jnp.finfo(jnp.float32).min
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None or top_p is not None:
        # one descending sort serves both filters
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    if top_k is not None:
        kth = sorted_desc[..., top_k - 1][..., None]
        logits = jnp.where(logits < kth, neg_inf, logits)
        sorted_desc = jnp.where(
            jnp.arange(sorted_desc.shape[-1]) < top_k, sorted_desc, neg_inf
        )
    if top_p is not None:
        # a token survives if the cumulative probability BEFORE it is
        # still < top_p (so the top token always survives)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum_before = jnp.cumsum(probs, axis=-1) - probs
        keep = cum_before < top_p
        # threshold = smallest surviving logit per row
        thresh = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < thresh, neg_inf, logits)
    return logits


def sample_logits(
    logits: jnp.ndarray,
    rng: Optional[jax.Array],
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jnp.ndarray:
    """[B, vocab] logits -> [B] token ids.

    ``top_k`` and ``top_p`` (nucleus) filters compose like the HF
    sampler: k-filter first, then keep the smallest prefix of the
    probability-sorted vocab whose mass reaches ``top_p``.
    """
    # validate before the greedy early-return so a bad config is loud
    # even while smoke-testing with temperature=0
    _validate_filters(top_k, top_p)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        raise ValueError("sampling with temperature > 0 needs an rng key")
    logits = filter_logits(
        logits, temperature=temperature, top_k=top_k, top_p=top_p
    )
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def model_max_len(model):
    """The model's position/cache capacity, or None when untyped —
    one extraction point shared by generate/generate_beam/
    generate_speculative so a new model family's limit attribute only
    needs teaching here."""
    cfg = getattr(model, "config", None)
    return getattr(cfg, "n_positions", None) or getattr(
        cfg, "max_seq_len", None
    )


def ragged_prompt_state(prompt_mask, B: int, P: int, cache_len: int):
    """Validated per-row state for a LEFT-padded (HF-style) prompt batch.

    Returns ``(prompt_mask, positions, prompt_lens, kv_mask)`` — the one
    construction of the ragged-prompt contract, shared by ``generate``
    and ``generate_speculative`` so the two can never diverge. Eager
    (non-traced) masks are refused upfront when RIGHT-padded or when a
    row has no real token at all: both would silently sample from a
    pad-slot query attending to nothing (NaN softmax / garbage tokens).
    """
    if prompt_mask.shape != (B, P):
        raise ValueError(
            f"prompt_mask must be {(B, P)}, got {prompt_mask.shape}"
        )
    prompt_mask = prompt_mask.astype(jnp.bool_)
    if not isinstance(prompt_mask, jax.core.Tracer):
        m = np.asarray(prompt_mask).astype(np.int8)
        if not (np.diff(m, axis=1) >= 0).all():
            raise ValueError(
                "prompt_mask must be LEFT-padded: each row one "
                "contiguous run of real tokens ending at the last "
                "slot (HF left-padding for decoder-only generation)"
            )
        if not m[:, -1].all():
            # left-padded + nonempty <=> last slot real; an all-pad row
            # would clamp to prompt_lens=1 and decode from a fully
            # masked attention row
            raise ValueError(
                "prompt_mask has a row with no real tokens — every row "
                "must contain at least one real (last-slot) token"
            )
    # positions count real tokens only: pads share position 0 (their
    # K/V are masked out of attention, so their rope/wpe is inert)
    positions = jnp.maximum(
        jnp.cumsum(prompt_mask.astype(jnp.int32), axis=1) - 1, 0
    )
    prompt_lens = positions[:, -1] + 1  # real tokens per row
    # cache-slot validity for the WHOLE generation: prompt slots follow
    # the mask; future decode slots are valid (the causal q_offset
    # masking hides the not-yet-written tail)
    kv_mask = jnp.concatenate(
        [prompt_mask, jnp.ones((B, cache_len - P), jnp.bool_)], axis=1
    )
    return prompt_mask, positions, prompt_lens, kv_mask


def cache_batch_axis(path, leaf) -> Optional[int]:
    """Batch axis of a decode-cache leaf, or None for shared counters.

    KV payload buffers are ``[..., B, T, H, D]`` (a leading ``[L]`` when
    layers are scanned), so the batch axis is ``ndim - 4``; the int8
    cache's per-token scale buffers carry the SAME layout and must move
    in lockstep with their payloads. Index/position counters have no
    batch dim and return None. Shared by ``generate_beam`` (beam
    replicate/reorder) and the serving engine's slot pool (per-slot
    insert/extract) so the two can never disagree about which leaves
    are per-sequence state.
    """
    name = getattr(path[-1], "key", None) or str(path[-1])
    if name in (
        "cached_key", "cached_value",
        "cached_key_scale", "cached_value_scale",
    ):
        return leaf.ndim - 4
    return None


def decode_step_body(
    model,
    params,
    cache,
    tok: jnp.ndarray,
    *,
    cache_len: int,
    positions: Optional[jnp.ndarray] = None,
    kv_mask: Optional[jnp.ndarray] = None,
    write_pos: Optional[jnp.ndarray] = None,
):
    """One KV-cache decode tick: ``[B]`` tokens -> ``([B, V] logits, cache)``.

    The single implementation of the per-token decode body, shared by
    the offline batch path (``generate``'s scan step, ``generate_beam``)
    and the serving engine's continuous-batching tick
    (``serve/engine.py``) — the two must stay one code path so engine
    output can be pinned bit-identical to offline ``generate``.
    ``write_pos`` is the slot-pool contract (per-row KV writes at each
    row's own length, ``ops.attention.decode_cache``); the lockstep
    paths leave it None and let the model's scalar cache_index advance.
    """
    extra = {}
    if positions is not None:
        extra["positions"] = positions
    if kv_mask is not None:
        extra["kv_mask"] = kv_mask
    if write_pos is not None:
        extra["write_pos"] = write_pos
    logits, state = model.apply(
        {"params": params, "cache": cache},
        tok[:, None],
        decode=True,
        cache_len=cache_len,
        mutable=["cache"],
        **extra,
    )
    return logits[:, -1], state["cache"]


def _generation_limits(model, P, max_new_tokens):
    """Shared validation for generate/generate_beam: positive token count
    and prompt+new within the model's position/cache capacity. Returns
    the cache length."""
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    limit = model_max_len(model)
    if limit is not None and P + max_new_tokens > limit:
        # past the cache/position table the dynamic_update_slice clamps
        # and gathers clamp — silent garbage, so refuse up front
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model's maximum sequence length {limit}"
        )
    return P + max_new_tokens


def generate(
    model,
    params,
    prompt_ids: jnp.ndarray,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    prompt_mask: Optional[jnp.ndarray] = None,
    repetition_penalty: float = 1.0,
    no_repeat_ngram_size: int = 0,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations of ``prompt_ids`` [B, P].

    Returns [B, P + max_new_tokens]; sequences that hit ``eos_id`` are
    padded with ``pad_id`` after it. Jit-compatible end to end — wrap in
    ``jax.jit(..., static_argnums=...)`` or call inside a jitted fn; the
    decode loop is a single ``lax.scan`` either way.

    ``no_repeat_ngram_size`` matches HF's ``NoRepeatNGramLogitsProcessor``
    token-for-token for unpadded prompts (n=1 bans every seen token;
    n larger than the sequence is a no-op, like HF). Static shapes: the
    token history lives in a fixed [B, P + max_new_tokens] buffer and
    each step scans its sliding n-gram windows. With ``prompt_mask``,
    PAD slots are excluded from grams (HF scans raw input_ids, pads
    included) — the same deliberate divergence as repetition_penalty,
    keeping ragged batches equal to unpadded per-prompt runs.

    ``repetition_penalty`` (> 1.0 discourages) matches HF's
    ``RepetitionPenaltyLogitsProcessor``: logits of every token already in
    the row (prompt + generated so far) are divided by the penalty when
    positive and multiplied when negative, before sampling. One deliberate
    divergence: with ``prompt_mask``, PAD slots are not counted as seen —
    HF penalizes them because they sit in input_ids; padding is not
    content, and this keeps ragged-batch outputs equal to the unpadded
    per-prompt runs.

    ``prompt_mask`` [B, P] (True = real token) enables RAGGED batches via
    LEFT padding — the HF ``generate(attention_mask=...)`` idiom: pads
    occupy the leading slots, every row's last real token sits at slot
    P-1, positions count real tokens only, and cache slots holding pads
    are masked out of every attention step. Continuations match the
    unpadded per-prompt results.
    """
    B, P = prompt_ids.shape
    # the cache is sized to exactly what this generation needs — NOT the
    # model's max positions (at 8B scale that difference is gigabytes of
    # HBM and a proportionally wider attention every step)
    cache_len = _generation_limits(model, P, max_new_tokens)
    if rng is None:
        rng = jax.random.key(0)

    extra = {}
    prompt_lens = None
    if prompt_mask is not None:
        prompt_mask, positions, prompt_lens, kv_mask = ragged_prompt_state(
            prompt_mask, B, P, cache_len
        )
        extra = {"positions": positions, "kv_mask": kv_mask}

    if repetition_penalty <= 0.0:
        raise ValueError(
            f"repetition_penalty must be > 0, got {repetition_penalty}"
        )
    if no_repeat_ngram_size < 0:
        raise ValueError(
            f"no_repeat_ngram_size must be >= 0, got {no_repeat_ngram_size}"
        )

    # prefill: one full-width pass fills every layer's cache
    logits, state = model.apply(
        {"params": params}, prompt_ids, decode=True, cache_len=cache_len,
        mutable=["cache"], **extra,
    )
    cache = state["cache"]

    presence = None
    if repetition_penalty != 1.0:
        # [B, V] token-presence mask (prompt tokens; pads excluded when a
        # prompt_mask is given), updated as tokens are emitted
        V = logits.shape[-1]
        presence = jnp.zeros((B, V), jnp.bool_)
        rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, P))
        if prompt_mask is not None:
            # masked slots contribute a False update — a no-op under .max
            safe_ids = jnp.where(prompt_mask, prompt_ids, 0)
            presence = presence.at[rows, safe_ids].max(prompt_mask)
        else:
            presence = presence.at[rows, prompt_ids].set(True)

    def _penalize(logits, presence):
        if presence is None:
            return logits
        l32 = logits.astype(jnp.float32)
        pen = jnp.where(
            l32 > 0, l32 / repetition_penalty, l32 * repetition_penalty
        )
        return jnp.where(presence, pen, l32)

    n = no_repeat_ngram_size
    if n > cache_len:
        n = 0  # no n-gram can ever complete — a no-op, like HF
    history = None
    if n > 0:
        # fixed-size token history; slots >= cur_len are not yet written
        history = jnp.zeros((B, cache_len), jnp.int32)
        history = history.at[:, :P].set(prompt_ids.astype(jnp.int32))
        # slot validity: with a prompt_mask, PAD slots never participate
        # in grams (unlike HF's raw-input_ids scan) so ragged batches
        # keep matching the unpadded per-prompt runs — the same
        # deliberate divergence repetition_penalty documents
        if prompt_mask is not None:
            hist_valid = jnp.concatenate(
                [prompt_mask,
                 jnp.ones((B, cache_len - P), jnp.bool_)], axis=1,
            )
        else:
            hist_valid = jnp.ones((B, cache_len), jnp.bool_)
        if n >= 2:
            # sliding (n-1)-gram window start indices, built once
            win = (
                jnp.arange(cache_len - n + 1)[:, None] + jnp.arange(n - 1)
            )  # [W, n-1]

    def _ban_ngrams(logits, history, cur_len):
        """-inf on tokens that would complete a seen n-gram (HF
        semantics; n=1 bans every seen token). ``cur_len`` = tokens
        written so far; candidates extend history[cur_len-(n-1):cur_len]."""
        if history is None:
            return logits
        l32 = logits.astype(jnp.float32)
        V = l32.shape[-1]
        rows_full = jnp.arange(B)[:, None]
        if n == 1:  # every already-seen (valid) token is banned
            seen = (
                jnp.arange(cache_len)[None, :] < cur_len
            ) & hist_valid
            banned = jnp.where(seen, history, V)
            return l32.at[
                jnp.broadcast_to(rows_full, banned.shape), banned
            ].set(-jnp.inf, mode="drop")
        grams = history[:, win]  # [B, W, n-1]
        suffix = lax.dynamic_slice_in_dim(
            history, cur_len - (n - 1), n - 1, axis=1
        )  # [B, n-1]
        match = jnp.all(grams == suffix[:, None, :], axis=-1)  # [B, W]
        # a window is a real, completed n-gram iff it ends before cur_len
        ends = jnp.arange(cache_len - n + 1) + n  # window's full-gram end
        match = match & (ends[None, :] <= cur_len)
        # every slot of the gram AND its follower must be a real token
        follower_idx = jnp.arange(cache_len - n + 1) + (n - 1)
        gram_valid = jnp.all(hist_valid[:, win], axis=-1) & hist_valid[
            :, follower_idx
        ]
        match = match & gram_valid
        follower = history[:, follower_idx]
        banned = jnp.where(match, follower, V)  # V = dropped by scatter
        rows = jnp.broadcast_to(rows_full, banned.shape)
        return l32.at[rows, banned].set(-jnp.inf, mode="drop")

    rng, sub = jax.random.split(rng)
    first_logits = _penalize(logits[:, -1], presence)
    if history is not None:
        first_logits = _ban_ngrams(first_logits, history, P)
    tok = sample_logits(
        first_logits, sub, temperature=temperature,
        top_k=top_k, top_p=top_p,
    )
    if presence is not None:
        presence = presence.at[jnp.arange(B), tok].set(True)
    if history is not None:
        history = history.at[:, P].set(tok)
    done = (
        tok == eos_id if eos_id is not None
        else jnp.zeros((B,), jnp.bool_)
    )

    def step(carry, t):
        cache, tok, rng, done, presence, history = carry
        dec_extra = {}
        if prompt_lens is not None:
            # per-row positions continue each row's REAL length, not the
            # padded slot index
            dec_extra["positions"] = (prompt_lens + t)[:, None]
            dec_extra["kv_mask"] = extra["kv_mask"]
        last, cache = decode_step_body(
            model, params, cache, tok, cache_len=cache_len, **dec_extra
        )
        rng, sub = jax.random.split(rng)
        step_logits = _penalize(last, presence)
        if history is not None:
            # t counts from 0; the prefill token is already written, so
            # the history holds P + t + 1 tokens at this point
            step_logits = _ban_ngrams(step_logits, history, P + t + 1)
        nxt = sample_logits(
            step_logits, sub,
            temperature=temperature, top_k=top_k, top_p=top_p,
        )
        nxt = jnp.where(done, jnp.int32(pad_id), nxt)
        if eos_id is not None:
            done = done | (nxt == eos_id)
        if presence is not None:
            presence = presence.at[jnp.arange(B), nxt].set(True)
        if history is not None:  # traced column index -> scatter form;
            # this step's token is sequence index P + t + 1 (prefill
            # already wrote index P)
            history = history.at[
                jnp.arange(B), jnp.full((B,), P + t + 1)
            ].set(nxt)
        return (cache, nxt, rng, done, presence, history), nxt

    # scan step t consumes continuation token #t+1, whose position is
    # (real length) + t
    (cache, _, _, _, _, _), rest = lax.scan(
        step, (cache, tok, rng, done, presence, history),
        jnp.arange(max_new_tokens - 1), length=max_new_tokens - 1,
    )
    out = jnp.concatenate(
        [prompt_ids, tok[:, None], rest.T.astype(prompt_ids.dtype)], axis=1
    )
    return out


def generate_beam(
    model,
    params,
    prompt_ids: jnp.ndarray,
    *,
    max_new_tokens: int,
    num_beams: int,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    length_penalty: float = 1.0,
    return_scores: bool = False,
):
    """Beam search over the same static-cache decode loop as ``generate``.

    Deterministic (no sampling): keeps the ``num_beams`` highest
    log-probability continuations per row, finishing beams at ``eos_id``
    and ranking finished beams by ``sum(logp) / len**length_penalty``
    (HF's convention). Returns the best sequence [B, P + max_new_tokens]
    (finished beams padded with ``pad_id``), or ``(sequences, scores)``
    with ``return_scores``.

    TPU shape discipline: beams are a batch dimension — the cache is
    replicated to [B*num_beams, ...] once after prefill, and every scan
    step reorders it with one gather; all shapes static, one compile.
    """
    B, P = prompt_ids.shape
    K = num_beams
    if K < 2:
        raise ValueError("num_beams must be >= 2 (use generate for greedy)")
    cache_len = _generation_limits(model, P, max_new_tokens)
    NEG = jnp.float32(-1e30)

    # prefill once at [B, P]; expand to beams afterwards
    logits, state = model.apply(
        {"params": params}, prompt_ids, decode=True, cache_len=cache_len,
        mutable=["cache"],
    )
    logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))  # [B, V]
    V = logp0.shape[-1]
    scores, tok = lax.top_k(logp0, K)  # [B, K] initial beams
    # replicate every layer's cache K times along its BATCH axis
    # (``cache_batch_axis``: KV payloads AND their int8 scale buffers
    # move together; counters stay shared)
    def _rep(path, x):
        ax = cache_batch_axis(path, x)
        return x if ax is None else jnp.repeat(x, K, axis=ax)

    cache = jax.tree_util.tree_map_with_path(_rep, state["cache"])
    tokens = jnp.full((B, K, max_new_tokens), pad_id, jnp.int32)
    tokens = tokens.at[:, :, 0].set(tok)
    finished = (
        tok == eos_id if eos_id is not None
        else jnp.zeros((B, K), jnp.bool_)
    )

    def step(carry, t):
        cache, tokens, scores, finished, prev = carry
        last, cache = decode_step_body(
            model, params, cache, prev.reshape(B * K),
            cache_len=cache_len,
        )
        logp = jax.nn.log_softmax(
            last.astype(jnp.float32)
        ).reshape(B, K, V)
        # finished beams may only extend with pad, at unchanged score
        pad_only = jnp.full((V,), NEG).at[pad_id].set(0.0)
        logp = jnp.where(finished[:, :, None], pad_only[None, None, :], logp)
        total = scores[:, :, None] + logp  # [B, K, V]
        flat = total.reshape(B, K * V)
        scores, idx = lax.top_k(flat, K)  # [B, K]
        beam_idx = idx // V  # which parent beam
        tok = (idx % V).astype(jnp.int32)
        # reorder histories and caches to the surviving parents
        tokens = jnp.take_along_axis(
            tokens, beam_idx[:, :, None], axis=1
        )
        tokens = tokens.at[:, :, t].set(tok)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        if eos_id is not None:
            finished = finished | (tok == eos_id)
        gather = (
            jnp.arange(B)[:, None] * K + beam_idx
        ).reshape(B * K)  # global cache rows

        def _take(path, x):
            ax = cache_batch_axis(path, x)
            return x if ax is None else jnp.take(x, gather, axis=ax)

        cache = jax.tree_util.tree_map_with_path(_take, cache)
        return (cache, tokens, scores, finished, tok), None

    (cache, tokens, scores, finished, _), _ = lax.scan(
        step,
        (cache, tokens, scores, finished, tok),
        jnp.arange(1, max_new_tokens),
        length=max_new_tokens - 1,
    )

    # rank by length-penalized score: finished beams use tokens-to-eos,
    # unfinished use the full length
    if eos_id is not None:
        is_eos = tokens == eos_id
        eos_pos = jnp.argmax(is_eos, axis=-1)  # first eos (0 if none)
        has_eos = jnp.any(is_eos, axis=-1)
        lengths = jnp.where(has_eos, eos_pos + 1, max_new_tokens)
    else:
        lengths = jnp.full((B, K), max_new_tokens)
    final = scores / (lengths.astype(jnp.float32) ** length_penalty)
    best = jnp.argmax(final, axis=1)  # [B]
    seq = jnp.take_along_axis(
        tokens, best[:, None, None], axis=1
    )[:, 0]  # [B, max_new_tokens]
    out = jnp.concatenate(
        [prompt_ids, seq.astype(prompt_ids.dtype)], axis=1
    )
    if return_scores:
        return out, jnp.take_along_axis(final, best[:, None], axis=1)[:, 0]
    return out
