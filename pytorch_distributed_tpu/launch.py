"""Launchers: the torchrun / ``mp.spawn`` equivalents.

The reference launches one process per GPU via ``torchrun`` (PyTorch's
elastic agent) or ``torch.multiprocessing.spawn`` (BASELINE.json:5,
SURVEY.md §2). The TPU-native execution model is single-controller SPMD —
ONE process drives every local chip — so the launcher's three jobs map to:

* ``spawn(fn, nprocs)``         — mp.spawn texture for the multi-process
  CPU path (workers join the native hostring backend; the gloo recipe).
* ``ElasticAgent`` / CLI        — torchrun texture: supervise worker
  processes, tear the group down on any failure, re-rendezvous and retry
  up to ``max_restarts`` (failure detection + elastic recovery, SURVEY §5).
* ``init_multihost()``          — the pod story: on a TPU pod slice each
  *host* runs one controller process; ``jax.distributed.initialize`` is
  the rendezvous (the NCCL TCP-store equivalent). Accepts both JAX-style
  and torchrun-style (MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE) env.
* ``ElasticWorldLauncher``      — supervisor for the IN-PROCESS elastic
  path (``train/elastic_world.py``): starts a genesis world and can add
  joiners mid-run; unlike ``ElasticAgent`` it never restarts anything —
  membership changes are handled by the workers re-meshing in place.

CLI: ``python -m pytorch_distributed_tpu.run --nproc-per-node 4 script.py``.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import signal
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence


def _worker_env(
    rank: int,
    world_size: int,
    group_name: str,
    *,
    node_rank: int = 0,
    nproc_per_node: Optional[int] = None,
    platform: str = "cpu",
    base: Optional[dict] = None,
) -> dict:
    """Env block for one worker, torchrun-shaped."""
    nproc = nproc_per_node or world_size
    env = dict(base if base is not None else os.environ)
    env.update(
        RANK=str(rank),
        WORLD_SIZE=str(world_size),
        LOCAL_RANK=str(rank % nproc),
        LOCAL_WORLD_SIZE=str(nproc),
        GROUP_RANK=str(node_rank),
        MASTER_ADDR=env.get("MASTER_ADDR", "127.0.0.1"),
        MASTER_PORT=env.get("MASTER_PORT", "29500"),
        PTD_GROUP_NAME=group_name,
        # Workers must not fight over the (single) local TPU; the chip
        # belongs to the single-controller path. Opt in via platform="tpu"
        # only when each worker has its own slice (multi-host).
        JAX_PLATFORMS=platform,
    )
    if platform == "cpu":
        # stop the axon TPU plugin registration in workers
        env["PALLAS_AXON_POOL_IPS"] = ""
    if platform == "tpu":
        # tpu workers are one-controller-per-HOST: init_process_group must
        # rendezvous via jax.distributed (RANK = host index), never join
        # the host-local shm ring with the global world size.
        env["PTD_MULTIHOST"] = "1"
    return env


def _spawn_target(fn, rank, world_size, group_name, platform, args):
    # The child inherited the parent env at interpreter start; overlay the
    # per-rank identity before user code runs.
    os.environ.update(
        _worker_env(rank, world_size, group_name, platform=platform, base={})
    )
    fn(rank, *args)


def spawn(
    fn: Callable,
    args: Sequence = (),
    nprocs: int = 1,
    *,
    join: bool = True,
    platform: str = "cpu",
    timeout_s: float = 600.0,
):
    """``torch.multiprocessing.spawn`` equivalent.

    Runs ``fn(rank, *args)`` in ``nprocs`` fresh processes with
    torchrun-shaped env (RANK/WORLD_SIZE/...) so ``init_process_group``
    inside ``fn`` joins the multi-process hostring backend. ``fn`` must be
    picklable (module-level). Returns the list of processes if
    ``join=False``.
    """
    ctx = mp.get_context("spawn")
    group_name = f"ptd_spawn_{uuid.uuid4().hex[:8]}"
    old_env = {
        k: os.environ.get(k) for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
    }
    # spawn children inherit the parent env at interpreter start — keep the
    # TPU plugin away from them even before fn runs.
    os.environ["JAX_PLATFORMS"] = platform
    if platform == "cpu":
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
    procs = []
    try:
        procs = [
            ctx.Process(
                target=_spawn_target,
                args=(fn, r, nprocs, group_name, platform, tuple(args)),
            )
            for r in range(nprocs)
        ]
        for p in procs:
            p.start()
    except BaseException:
        # partial start: reap the workers already running — they'd block
        # in the rendezvous waiting for ranks that will never come
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)
        from pytorch_distributed_tpu.runtime.hostring import unlink_segment

        unlink_segment(group_name)
        raise
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if not join:
        return procs
    deadline = time.monotonic() + timeout_s
    try:
        for p in procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
        bad = [
            (p.pid, p.exitcode) for p in procs if p.exitcode not in (0, None)
        ]
        hung = [p.pid for p in procs if p.exitcode is None]
        if bad or hung:
            raise RuntimeError(
                f"spawn workers failed: nonzero={bad} hung={hung}"
            )
    finally:
        dirty = False
        for p in procs:
            if p.is_alive():
                p.terminate()
                dirty = True
            elif p.exitcode != 0:
                dirty = True
        if dirty:
            # killed/crashed workers never reach hr_finalize
            from pytorch_distributed_tpu.runtime.hostring import unlink_segment

            unlink_segment(group_name)
    return None


@dataclass
class ElasticAgent:
    """torchrun-equivalent supervisor for command-line workers.

    Launches ``nproc_per_node`` copies of ``cmd`` with torchrun-shaped env,
    watches them, and on any worker failure tears the whole group down and
    re-rendezvouses (fresh shm group name) up to ``max_restarts`` times —
    the reference's elastic-agent restart policy (SURVEY.md §5: failure
    detection / elastic recovery).
    """

    cmd: Sequence[str]
    nproc_per_node: int
    max_restarts: int = 3
    node_rank: int = 0
    nnodes: int = 1
    platform: str = "cpu"
    poll_s: float = 0.2
    extra_env: dict = field(default_factory=dict)

    def _launch_once(self, attempt: int) -> int:
        world = self.nproc_per_node * self.nnodes
        group_name = f"ptd_run_{uuid.uuid4().hex[:8]}_a{attempt}"
        procs = []
        for local in range(self.nproc_per_node):
            rank = self.node_rank * self.nproc_per_node + local
            env = _worker_env(
                rank, world, group_name,
                node_rank=self.node_rank,
                nproc_per_node=self.nproc_per_node,
                platform=self.platform,
            )
            env.update({k: str(v) for k, v in self.extra_env.items()})
            env["TORCHELASTIC_RESTART_COUNT"] = str(attempt)
            procs.append(subprocess.Popen(list(self.cmd), env=env))
        try:
            while True:
                codes = [p.poll() for p in procs]
                if all(c == 0 for c in codes):
                    return 0
                failed = [
                    (p.pid, c) for p, c in zip(procs, codes)
                    if c is not None and c != 0
                ]
                if failed:
                    print(
                        f"[ptd.run] worker failure {failed}; "
                        "tearing down group",
                        file=sys.stderr,
                    )
                    return failed[0][1]
                time.sleep(self.poll_s)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            t0 = time.monotonic()
            for p in procs:
                while p.poll() is None and time.monotonic() - t0 < 10:
                    time.sleep(0.1)
                if p.poll() is None:
                    p.kill()
                    p.wait()
            # killed workers never reach hr_finalize; reap their segment
            from pytorch_distributed_tpu.runtime.hostring import unlink_segment

            unlink_segment(group_name)

    def run(self) -> int:
        if self.nnodes > 1 and self.platform == "cpu":
            raise ValueError(
                "nnodes > 1 requires --platform tpu (multi-host pods "
                "rendezvous via init_multihost); the cpu/hostring backend "
                "is host-local shared memory and cannot span nodes"
            )
        for attempt in range(self.max_restarts + 1):
            code = self._launch_once(attempt)
            if code == 0:
                return 0
            if attempt < self.max_restarts:
                print(
                    f"[ptd.run] restart {attempt + 1}/{self.max_restarts}",
                    file=sys.stderr,
                )
        return code


@dataclass
class ElasticWorldLauncher:
    """Launch / supervise ``train/elastic_world.py`` worker processes.

    The torchrun-agent counterpart for the IN-PROCESS elastic path: it
    starts the genesis world and can ``add_worker`` (the grow drill) —
    but unlike :class:`ElasticAgent` it never tears the group down on a
    failure; membership changes are the workers' own business. One
    launcher = one rendezvous dir. Shared by ``scripts/chaos_drill.py
    --drill resize``, bench.py's ``elastic`` phase, and the tests.
    """

    rendezvous_dir: str
    worker_args: Sequence[str] = ()  # engine CLI flags, minus identity
    python: Optional[str] = None

    def __post_init__(self):
        os.makedirs(self.rendezvous_dir, exist_ok=True)
        self.procs: dict = {}

    def _cmd(self, worker_id: str, extra: Sequence[str]) -> list:
        return [
            self.python or sys.executable, "-m",
            "pytorch_distributed_tpu.train.elastic_world",
            "--rendezvous-dir", self.rendezvous_dir,
            "--worker-id", worker_id,
            *self.worker_args, *extra,
        ]

    def start_world(self, worker_ids: Sequence[str],
                    env_overrides: Optional[dict] = None) -> None:
        """Genesis: every worker gets ``--expected-world len(ids)``.

        ``env_overrides`` maps worker_id -> extra env (the drill arms
        one worker's ``PTD_FAULTS`` here to pick the deterministic
        victim)."""
        for wid in worker_ids:
            self.launch_worker(
                wid, extra=("--expected-world", str(len(worker_ids))),
                env=(env_overrides or {}).get(wid),
            )

    def add_worker(self, worker_id: str,
                   env: Optional[dict] = None) -> None:
        """The grow path: a fresh process joins the live world."""
        self.launch_worker(worker_id, extra=("--join",), env=env)

    def launch_worker(self, worker_id: str, *, extra: Sequence[str] = (),
                      env: Optional[dict] = None) -> None:
        worker_env = dict(os.environ)
        # workers never touch the (single, shared) TPU
        worker_env["JAX_PLATFORMS"] = "cpu"
        worker_env["PALLAS_AXON_POOL_IPS"] = ""
        worker_env.pop("XLA_FLAGS", None)
        # the -m target must resolve regardless of the caller's cwd
        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        prev = worker_env.get("PYTHONPATH")
        worker_env["PYTHONPATH"] = (
            repo_root if not prev else repo_root + os.pathsep + prev
        )
        worker_env.update(env or {})
        self.procs[worker_id] = subprocess.Popen(
            self._cmd(worker_id, extra), env=worker_env,
            stdout=sys.stderr, stderr=subprocess.STDOUT,
        )

    def wait(self, timeout_s: float = 180.0) -> dict:
        """Join every worker; returns worker_id -> exit code."""
        deadline = time.monotonic() + timeout_s
        codes = {}
        try:
            for wid, p in self.procs.items():
                left = max(0.1, deadline - time.monotonic())
                try:
                    codes[wid] = p.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    codes[wid] = None
        finally:
            for p in self.procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait()
        return codes

    def results(self) -> dict:
        """worker_id -> parsed result-<id>.json (absent workers omitted)."""
        import json

        out = {}
        for wid in self.procs:
            path = os.path.join(
                self.rendezvous_dir, f"result-{wid}.json"
            )
            try:
                with open(path) as f:
                    out[wid] = json.load(f)
            except (OSError, ValueError):
                pass
        return out


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host (pod) rendezvous: ``jax.distributed.initialize`` with
    torchrun-style env fallbacks.

    On a TPU pod each host runs ONE controller process; after this call
    ``jax.devices()`` spans the whole pod and every mesh built on top of it
    shards over ICI/DCN. Resolution order per field: explicit arg →
    JAX-style env (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID) →
    torchrun-style env (MASTER_ADDR:MASTER_PORT / WORLD_SIZE / RANK) →
    jax autodetection (GKE/Cloud TPU metadata).
    """
    import jax

    def pick(explicit, *env_keys, cast=str):
        if explicit is not None:
            return explicit
        for k in env_keys:
            if os.environ.get(k):
                return cast(os.environ[k])
        return None

    coordinator_address = pick(
        coordinator_address, "COORDINATOR_ADDRESS"
    )
    if coordinator_address is None and os.environ.get("MASTER_ADDR"):
        coordinator_address = (
            f"{os.environ['MASTER_ADDR']}:"
            f"{os.environ.get('MASTER_PORT', '29500')}"
        )
    num_processes = pick(num_processes, "NUM_PROCESSES", "WORLD_SIZE", cast=int)
    process_id = pick(process_id, "PROCESS_ID", "RANK", cast=int)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
