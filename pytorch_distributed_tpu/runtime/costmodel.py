"""Calibrated α–β cost model for collectives.

The auto-parallel planner (ROADMAP open item 4, AMP-style strategy
search) needs to PRICE a candidate sharding before running it, which
needs a transport model calibrated from this machine's own measurements
rather than folklore constants. The classic α–β model is exactly that:

    time(op, payload, world) = α  +  β · wire_bytes(op, payload, world)

with α the per-call latency floor (barriers, dispatch, rendezvous) and
β the per-byte cost of the transport. ``wire_bytes`` is the
NCCL-convention algorithmic bytes per participant
(``runtime/hostring.algo_wire_bytes``), so one β is comparable across
collectives and the fitted models compose with the ``comm.*`` span
accounting — the bytes the tracer records are the bytes the model
prices.

Calibration sources, in order of fidelity:

* ``scripts/collective_bench.py --fit`` — a live size sweep on the
  current mesh, written to ``costmodel.json``;
* past ``--metrics-path`` JSONL records (``split="comm_bench"``) via
  :func:`fit_from_metrics` — bench history becomes a model without
  re-running anything.

Fits are per (op, world_size): α genuinely varies with the participant
count (a ring pays world barrier phases), so folding worlds together
would smear it. ``predict`` at an unbenched world reuses the nearest
fitted world's β (a per-byte property of the transport) and scales its
α by the barrier-phase ratio ``(w-1)/(w_fit-1)`` — flagged as
``extrapolated`` in the result, because honesty about model reach is
the difference between a planner and a guesser.

This module is deliberately jax-free (a planner or report tool must be
able to load a costmodel.json without a runtime).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Dict, Iterable, List, Optional, Tuple

from pytorch_distributed_tpu.runtime.hostring import algo_wire_bytes

#: current costmodel.json schema version
FORMAT_VERSION = 1


def calibration_command(path: str = "costmodel.json") -> str:
    """The exact command that (re)creates a calibrated model at ``path``
    — every load/validate failure names it, because "go calibrate" is
    only actionable when the error says how."""
    return f"python scripts/collective_bench.py --fit {path}"


class CostModelUnavailable(ValueError):
    """A cost model could not be loaded/used for the requested purpose.

    Raised with a message naming the ``collective_bench --fit`` command
    to run. Subclasses ValueError so report tooling that treats an
    unreadable model as a degraded (not fatal) input keeps working;
    planners catch it explicitly to fall back to an analytic model.
    """


@dataclasses.dataclass
class OpFit:
    """One collective's fitted α–β line at one world size."""

    op: str
    world_size: int
    alpha_s: float  # per-call latency floor (seconds)
    beta_s_per_byte: float  # per-wire-byte cost (seconds/byte)
    r2: float  # goodness of fit on the calibration points
    n_samples: int
    wire_bytes_min: int  # calibrated range: predictions outside it
    wire_bytes_max: int  # are extrapolations

    @property
    def bandwidth_gb_s(self) -> float:
        """The β term as an achievable-bandwidth number (GB/s)."""
        return (
            1.0 / self.beta_s_per_byte / 1e9
            if self.beta_s_per_byte > 0 else float("inf")
        )


@dataclasses.dataclass
class Prediction:
    seconds: float
    wire_bytes: int
    fit: OpFit
    extrapolated: bool  # off the calibrated (op, world, size) range


class CostModel:
    """A set of per-(op, world) α–β fits for one transport."""

    def __init__(self, transport: str,
                 fits: Optional[Dict[Tuple[str, int], OpFit]] = None):
        self.transport = transport
        self.fits: Dict[Tuple[str, int], OpFit] = dict(fits or {})

    def ops(self) -> List[str]:
        return sorted({op for op, _ in self.fits})

    def predict(self, op: str, nbytes: int,
                world_size: int) -> Prediction:
        """Predicted seconds for ``op`` moving a ``nbytes`` payload over
        ``world_size`` participants (payload per the NCCL conventions of
        ``algo_wire_bytes``). Raises ``KeyError`` for an op the model
        was never calibrated on — a planner must know what it cannot
        price."""
        worlds = sorted(w for o, w in self.fits if o == op)
        if not worlds:
            raise KeyError(
                f"cost model ({self.transport}) has no fit for {op!r}; "
                f"calibrated ops: {self.ops()}"
            )
        wire = algo_wire_bytes(op, nbytes, world_size)
        if world_size in worlds:
            fit = self.fits[(op, world_size)]
            alpha = fit.alpha_s
            extrapolated = False
        else:  # nearest calibrated world: β carries over, α scales with
            # the number of barrier phases a ring pays (~world - 1)
            nearest = min(worlds, key=lambda w: abs(w - world_size))
            fit = self.fits[(op, nearest)]
            alpha = fit.alpha_s * max(world_size - 1, 0) / max(
                nearest - 1, 1
            )
            extrapolated = True
        if not fit.wire_bytes_min <= wire <= fit.wire_bytes_max:
            extrapolated = True
        return Prediction(
            seconds=alpha + fit.beta_s_per_byte * wire,
            wire_bytes=wire, fit=fit, extrapolated=extrapolated,
        )

    # -- persistence -------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "format_version": FORMAT_VERSION,
            "transport": self.transport,
            "fits": [dataclasses.asdict(f) for f in self.fits.values()],
        }

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)  # atomic: a killed writer leaves no torn model
        return path

    @classmethod
    def from_dict(cls, doc: dict) -> "CostModel":
        if doc.get("format_version") != FORMAT_VERSION:
            raise ValueError(
                f"costmodel format {doc.get('format_version')!r} != "
                f"{FORMAT_VERSION} — refit rather than misread"
            )
        fits = {}
        for fd in doc["fits"]:
            f = OpFit(**fd)
            fits[(f.op, f.world_size)] = f
        return cls(doc["transport"], fits)

    @classmethod
    def load(cls, path: str, *,
             expected_transport: Optional[str] = None) -> "CostModel":
        """Load ``path``, failing ACTIONABLY: a missing, unreadable or
        transport-mismatched model raises :class:`CostModelUnavailable`
        naming the exact calibration command, instead of a bare
        traceback three frames from the actual fix."""
        try:
            with open(path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            raise CostModelUnavailable(
                f"no cost model at {path!r} — calibrate this machine "
                f"first: `{calibration_command(path)}`"
            ) from None
        except (OSError, ValueError) as e:
            raise CostModelUnavailable(
                f"cost model {path!r} is unreadable ({e}) — refit: "
                f"`{calibration_command(path)}`"
            ) from e
        try:
            model = cls.from_dict(doc)
        except (ValueError, KeyError, TypeError) as e:
            raise CostModelUnavailable(
                f"cost model {path!r} does not parse ({e}) — refit: "
                f"`{calibration_command(path)}`"
            ) from e
        if (expected_transport is not None
                and model.transport != expected_transport):
            raise CostModelUnavailable(
                f"cost model {path!r} was calibrated on transport "
                f"{model.transport!r} but this run needs "
                f"{expected_transport!r} — a memcpy fit cannot price a "
                f"network; refit here: `{calibration_command(path)}`"
            )
        return model


#: transport label analytic (uncalibrated) models carry — consumers key
#: their "this is a guess" warnings off it
ANALYTIC_TRANSPORT = "analytic-guess"

#: every op the planner may need to price — send/recv are the pipeline
#: candidates' per-link activation/grad handoffs (world = the 2-rank
#: ordered pair; wire bytes = payload, algo_wire_bytes)
_ANALYTIC_OPS = ("all_reduce", "all_reduce_q8", "all_gather",
                 "reduce_scatter", "broadcast", "send", "recv")


def analytic_cost_model(
    worlds: Iterable[int],
    *,
    bandwidth_gb_s: float = 1.0,
    alpha_per_phase_s: float = 2e-5,
    ops: Iterable[str] = _ANALYTIC_OPS,
) -> CostModel:
    """A bandwidth-GUESS α–β model for when no calibration exists.

    The planner's degraded mode (never its default): α scales with the
    ring's barrier phases (``(world-1) x alpha_per_phase_s``), β is one
    flat per-wire-byte cost. Rankings under it reflect VOLUME and CALL
    COUNT only — usually the right ordering, but every consumer must
    surface the ``analytic-guess`` transport as an ``uncalibrated``
    flag, and the fix is always :func:`calibration_command`.
    """
    beta = 1.0 / (bandwidth_gb_s * 1e9)
    fits: Dict[Tuple[str, int], OpFit] = {}
    for op in ops:
        for w in sorted(set(int(w) for w in worlds)):
            if w <= 1:
                continue
            fits[(op, w)] = OpFit(
                op=op, world_size=w,
                alpha_s=alpha_per_phase_s * (w - 1),
                beta_s_per_byte=beta, r2=0.0, n_samples=0,
                wire_bytes_min=0, wire_bytes_max=1 << 62,
            )
    return CostModel(ANALYTIC_TRANSPORT, fits)


def _fit_line(xs: List[float], ys: List[float]) -> Tuple[float, float, float]:
    """Least-squares y = α + βx with both clamped non-negative (a
    transport cannot have negative latency or negative per-byte cost;
    a tiny-noise fit CAN produce either). Returns (α, β, r²) with r²
    computed on the clamped line — the honesty metric reflects the
    model actually shipped."""
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    var = sum((x - mx) ** 2 for x in xs)
    if var > 0:
        beta = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / var
    else:  # one distinct size: all bytes, no intercept information
        beta = my / mx if mx > 0 else 0.0
    beta = max(beta, 0.0)
    alpha = max(my - beta * mx, 0.0)
    ss_res = sum((y - (alpha + beta * x)) ** 2 for x, y in zip(xs, ys))
    ss_tot = sum((y - my) ** 2 for y in ys)
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else (
        1.0 if ss_res == 0 else 0.0
    )
    return alpha, beta, r2


def fit(records: Iterable[dict], transport: str) -> CostModel:
    """Fit a :class:`CostModel` from measurement records.

    Each record needs ``op``, ``payload_bytes``, ``world``, and
    ``seconds`` (one timed collective at one size — exactly what
    ``collective_bench --metrics-path`` writes and what the bench's
    in-memory sweep holds). Records with non-positive wire bytes (one
    participant, a barrier) are skipped: there is no line to fit
    through zero-byte points.
    """
    groups: Dict[Tuple[str, int], List[Tuple[float, float]]] = {}
    for r in records:
        op, world = str(r["op"]), int(r["world"])
        wire = algo_wire_bytes(op, int(r["payload_bytes"]), world)
        if wire <= 0:
            continue
        groups.setdefault((op, world), []).append(
            (float(wire), float(r["seconds"]))
        )
    fits: Dict[Tuple[str, int], OpFit] = {}
    for (op, world), pts in groups.items():
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        alpha, beta, r2 = _fit_line(xs, ys)
        fits[(op, world)] = OpFit(
            op=op, world_size=world, alpha_s=alpha,
            beta_s_per_byte=beta, r2=r2, n_samples=len(pts),
            wire_bytes_min=int(min(xs)), wire_bytes_max=int(max(xs)),
        )
    return CostModel(transport, fits)


def fit_from_metrics(records: Iterable[dict],
                     transport: Optional[str] = None) -> CostModel:
    """Fit from a MetricsWriter JSONL stream (``train.metrics
    .read_metrics`` output): consumes the ``split="comm_bench"``
    records ``collective_bench --metrics-path`` writes, so bench
    history calibrates a model without re-running the sweep."""
    rows = [
        r for r in records
        if r.get("split") == "comm_bench" and r.get("event") == "collective"
    ]
    if transport is None:
        transports = {r.get("transport") for r in rows} - {None}
        if len(transports) > 1:
            raise ValueError(
                f"records span transports {sorted(transports)}; pass "
                "transport= to pick one — mixing them would average "
                "a memcpy with a network"
            )
        transport = next(iter(transports), "unknown")
    return fit(
        [r for r in rows if r.get("transport", transport) == transport],
        transport,
    )


def validate(model: CostModel, records: Iterable[dict]) -> Dict[str, float]:
    """Max |predicted/measured| ratio-error per op over ``records``
    (same schema as :func:`fit`) — the "within 2x" acceptance number.
    Returns ``{op: max(pred/meas, meas/pred)}``."""
    worst: Dict[str, float] = {}
    for r in records:
        op, world = str(r["op"]), int(r["world"])
        if algo_wire_bytes(op, int(r["payload_bytes"]), world) <= 0:
            continue
        try:
            pred = model.predict(op, int(r["payload_bytes"]), world)
        except KeyError:
            continue
        meas = float(r["seconds"])
        if meas <= 0 or pred.seconds <= 0:
            ratio = math.inf
        else:
            ratio = max(pred.seconds / meas, meas / pred.seconds)
        worst[op] = max(worst.get(op, 0.0), ratio)
    return worst
