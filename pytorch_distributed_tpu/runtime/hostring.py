"""ctypes bindings for the native shared-memory host collectives.

The reference's CPU smoke path is true multi-process training over the gloo
process group (SURVEY.md §2: gloo -> "single-host CPU backend of the same
API (... host ring in C++)"). ``native/hostring.cpp`` is that backend: N OS
processes rendezvous on a POSIX shm segment and run collectives through
per-rank slots under a process-shared barrier.

This module is deliberately JAX-free so spawned worker processes can import
it without dragging in (or re-initialising) a TPU runtime. Semantics are
torch.distributed-shaped: each *process* passes its local tensor.
"""

from __future__ import annotations

import ctypes
import os
import time
from typing import Optional

import numpy as np

# tracing/faults/flightrec are deliberately jax-free too, so instrumenting
# the collectives keeps this module importable from spawned workers without
# a TPU runtime
from pytorch_distributed_tpu.runtime import faults, flightrec, tracing

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "hostring.cpp")
_SO = os.path.join(_NATIVE_DIR, "libhostring.so")

_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.float16): 6,  # F16: native 2-byte collectives
}
_U8 = 4  # raw-byte dtype: copy-shaped collectives on arbitrary dtypes
# "avg" is a REAL C-side op for float dtypes: the division happens in the
# f32 accumulator before the final rounding, so half averages can't
# overflow the way divide-after-rounded-sum would (f16 avg of 30000.0 x4)
_OPS = {"sum": 0, "prod": 1, "product": 1, "max": 2, "min": 3, "avg": 4}

# Half dtypes (the TPU compute dtypes): ``all_reduce`` ships them NATIVELY
# at 2-byte bandwidth — the C side accumulates each segment in f32 and
# rounds once, NCCL's half-allreduce design. The remaining reduction
# (reduce_scatter) still takes the f32 round trip below.
_HALF = {np.dtype(np.float16)}
try:  # ml_dtypes ships with jax
    import ml_dtypes

    _HALF.add(np.dtype(ml_dtypes.bfloat16))
    _DTYPES[np.dtype(ml_dtypes.bfloat16)] = 5  # BF16
except ImportError:  # pragma: no cover
    pass

_lib: Optional[ctypes.CDLL] = None


def build_library(force: bool = False) -> str:
    """Compile libhostring.so if missing/stale; returns the path."""
    from pytorch_distributed_tpu.utils.native_build import (
        build_native_library,
    )

    return build_native_library(
        _SRC, _SO, extra_flags=("-pthread", "-lrt"), force=force
    )


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_library())
        lib.hr_init.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_double, ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.hr_init.restype = ctypes.c_int
        for name, args in {
            "hr_barrier": [ctypes.c_void_p],
            "hr_allreduce": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32, ctypes.c_int32,
            ],
            "hr_allreduce_q8": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32,
            ],
            "hr_allgather": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_int32,
            ],
            "hr_reduce_scatter": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ],
            "hr_broadcast": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32,
            ],
            "hr_sendrecv": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32, ctypes.c_int32,
            ],
            "hr_finalize": [ctypes.c_void_p],
        }.items():
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = ctypes.c_int
        # the q8 fold kernel, shared with the TCP transport so both
        # transports run the identical (FMA-contracted) instruction
        # sequence — see hr_q8_dequant_add in native/hostring.cpp
        lib.hr_q8_dequant_add.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_uint64,
        ]
        lib.hr_q8_dequant_add.restype = None
        _lib = lib
    return _lib


def _check(rc: int, what: str) -> None:
    if rc != 0:
        # where this rank stopped, even without a dump dir armed: the
        # flight recorder's last completed record turns a bare deadline
        # legend into "the world died after seq N kind/op"
        where = flightrec.last_completed_desc()
        flightrec.dump(f"hostring {what} failed (rc={rc}; {where})")
        raise RuntimeError(f"hostring {what} failed (rc={rc}; "
                           f"-110=peer timeout, -22=bad args, -5=peer died; "
                           f"{where})")


def _as_contig(x, dtype_required=True) -> np.ndarray:
    a = np.ascontiguousarray(x)
    if dtype_required and a.dtype not in _DTYPES:
        raise TypeError(
            f"unsupported dtype {a.dtype}; one of {list(_DTYPES)} required"
        )
    return a


def unlink_segment(name: str) -> None:
    """Best-effort removal of a group's shm segment (launcher teardown).

    Workers killed mid-collective never reach ``hr_finalize``; the
    supervising agent calls this after reaping them so abandoned attempts
    don't accumulate in /dev/shm.
    """
    import glob

    shm = name.strip("/").replace("/", "_")
    # init_process_group suffixes a per-init generation (_gN) onto the
    # group name; reap those too so abandoned re-inits don't accumulate.
    for path in [os.path.join("/dev/shm", shm)] + glob.glob(
        os.path.join("/dev/shm", shm + "_g*")
    ):
        try:
            os.unlink(path)
        except OSError:
            pass


# --------------------------------------------------------------------------
# Wire-byte accounting (the ``comm.*`` observability spans).
#
# Conventions follow NCCL-tests so numbers are comparable to GPU rigs and
# to scripts/collective_bench.py's busbw lines. ``payload`` is the op's
# FULL data size: the local tensor for all_reduce/broadcast/send/recv, the
# gathered output for all_gather, the [world, ...] input for
# reduce_scatter. ``algo_wire_bytes`` is the per-participant bytes a
# bandwidth-optimal ring moves for that payload — what "bytes on the wire"
# means everywhere in this repo (spans, rollups, the cost model).
# --------------------------------------------------------------------------

#: elements per q8 scale block — must match kQBlock in native/hostring.cpp
Q8_BLOCK = 256


def q8_wire_payload(n_elems: int) -> int:
    """Bytes one rank's q8-quantized f32 payload occupies on the wire:
    one int8 per element plus one f32 scale per 256-element block — the
    REAL bytes `hr_allreduce_q8` ships (~0.254x the f32 payload at
    >= 4096 elements), so the ~4x reduction is a recorded fact."""
    return int(n_elems) + 4 * ((int(n_elems) + Q8_BLOCK - 1) // Q8_BLOCK)


def algo_wire_bytes(kind: str, payload_bytes: int, world: int) -> int:
    """NCCL-convention algorithmic bytes moved per participant.

    all_reduce 2(n-1)/n x payload; all_gather / reduce_scatter
    (n-1)/n x payload; broadcast / send / recv / permute: payload;
    barrier: 0. A one-rank world moves nothing.
    """
    payload_bytes, world = int(payload_bytes), int(world)
    if world <= 1:
        return 0
    if kind in ("all_reduce", "all_reduce_q8"):
        return 2 * (world - 1) * payload_bytes // world
    if kind in ("all_gather", "reduce_scatter"):
        return (world - 1) * payload_bytes // world
    if kind in ("broadcast", "send", "recv", "permute"):
        return payload_bytes
    if kind == "barrier":
        return 0
    raise ValueError(f"unknown collective kind {kind!r}")


#: cumulative per-op accounting, fed to the Chrome ``counter()`` tracks:
#: span name -> [calls, wire_bytes_moved, seconds]. Module-level so a
#: whole process's rings share one set of tracks (torch's comms logger
#: shape); armed-only — disarmed collectives never touch it — and
#: scoped to ONE tracer: a re-armed window starts from zero rather
#: than exporting the previous window's totals.
_COMM_CUM: dict = {}
_COMM_CUM_OWNER = None  # the Tracer the running totals belong to


def reset_comm_counters() -> None:
    """Zero the cumulative ``comm.<op>.*`` counter tracks — for callers
    measuring a window narrower than the tracer's lifetime (bench.py's
    comms phase: warm-up calls must not pollute the measured totals)."""
    global _COMM_CUM_OWNER
    _COMM_CUM.clear()
    _COMM_CUM_OWNER = None


class _CommSpan:
    """Armed-only span around one collective: records the ``comm.*``
    trace event (op/dtype/count/payload/wire bytes) and advances the
    cumulative per-op counter tracks on exit."""

    __slots__ = ("_t", "_name", "_args", "_t0")

    def __init__(self, t, name: str, args: dict):
        self._t = t
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = self._t._clock()
        return self

    def __exit__(self, *exc):
        global _COMM_CUM_OWNER
        t1 = self._t._clock()
        self._t.complete(self._name, self._args, self._t0, t1)
        if _COMM_CUM_OWNER is not self._t:  # fresh tracer, fresh totals
            _COMM_CUM.clear()
            _COMM_CUM_OWNER = self._t
        cum = _COMM_CUM.setdefault(self._name, [0, 0, 0.0])
        cum[0] += 1
        cum[1] += self._args["wire_bytes"]
        cum[2] += t1 - self._t0
        self._t.counter(self._name + ".calls", cum[0])
        self._t.counter(self._name + ".bytes_moved", cum[1])
        self._t.counter(self._name + ".seconds", round(cum[2], 6))
        tkind = self._args.get("transport")
        if tkind:
            # per-transport byte totals (comm.bytes.shm / comm.bytes.tcp):
            # obs_report's "Cross-host bytes" line sums the non-shm tracks
            # — the bytes that would cross a real DCN
            tname = "comm.bytes." + tkind
            tcum = _COMM_CUM.setdefault(tname, [0, 0, 0.0])
            tcum[1] += self._args["wire_bytes"]
            self._t.counter(tname, tcum[1])
        return False


def _comm_span(tracer, kind: str, op: str, count: int, dtype,
               payload_bytes: int, world: int, transport: str):
    """Build the armed comm span. Call sites gate on the module-global
    ``tracing._tracer is None`` test FIRST (the faults.py discipline), so
    the disarmed path never reaches this function — no arg evaluation,
    no dict build, nothing but the is-None test and the shared no-op."""
    return _CommSpan(tracer, "comm." + kind, {
        "op": op,
        "dtype": str(dtype),
        "count": int(count),
        "payload_bytes": int(payload_bytes),
        "wire_bytes": algo_wire_bytes(kind, payload_bytes, world),
        "world": world,
        "transport": transport,
    })


class HostRingGroup:
    """One process's membership in a collectives group.

    The byte-moving layer is pluggable (r16): by default the group
    constructs the native shared-memory ring
    (:class:`~pytorch_distributed_tpu.runtime.transport.ShmTransport` —
    the exact pre-r16 segment layout and code path), but any
    :class:`~pytorch_distributed_tpu.runtime.transport.Transport` with
    matching rank/world can be passed instead (``TcpTransport`` for
    ranks that do not share a host). Everything above the transport —
    dtype/op validation, copy-vs-inplace semantics, DETAIL fingerprint
    handshakes, integer-avg floor division, the half reduce_scatter
    round trip, ``comm.*`` spans — is transport-independent, and the
    transports share one reduction structure, so results are
    bit-identical across transports (tests/test_transport.py pins it).
    """

    def __init__(
        self,
        name: str,
        rank: int,
        world_size: int,
        *,
        slot_bytes: int = 4 << 20,
        timeout_s: float = 120.0,
        debug: Optional[bool] = None,
        clock_sync: bool = False,
        transport=None,
    ):
        if transport is None:
            from pytorch_distributed_tpu.runtime.transport import (
                ShmTransport,
            )

            transport = ShmTransport(
                name, rank, world_size, slot_bytes=slot_bytes,
                timeout_s=timeout_s,
            )
        elif (transport.rank != rank
              or transport.world_size != world_size):
            raise ValueError(
                f"transport rank/world ({transport.rank}/"
                f"{transport.world_size}) != group rank/world "
                f"({rank}/{world_size})"
            )
        self._transport = transport
        #: the group's segment name as given (pre-shm mangling): the
        #: teardown side (``unlink_segment``) and the elastic membership
        #: layer (which reaps a dead peer's never-finalized segment on
        #: re-rendezvous) key off it
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.timeout_s = float(transport.timeout_s)
        #: the per-rank slot size: allreduce processes payloads in
        #: slot-sized chunks with segment ownership computed PER CHUNK —
        #: the grad-sync pipeline (parallel/overlap.py) splits oversized
        #: leaves at exactly these boundaries, which is what makes the
        #: split bit-identical to the unsplit call. Taken from the
        #: transport: cross-transport bit-identity requires agreeing
        #: chunk boundaries.
        self.slot_bytes = int(transport.slot_bytes)
        if debug is None:
            # DETAIL turns on cross-rank call verification, the analogue
            # of TORCH_DISTRIBUTED_DEBUG=DETAIL (SURVEY.md §5: collective
            # mismatch is the SPMD-era data race)
            debug = os.environ.get(
                "PTD_DISTRIBUTED_DEBUG", ""
            ).upper() == "DETAIL"
        self.debug = debug
        #: this rank's wall-clock offset vs rank 0 (seconds); measured by
        #: the barrier handshake below when ``clock_sync=True`` (the WORLD
        #: ring — subgroups skip it, their ranks are renumbered)
        self.clock_offset_s = 0.0
        self.clock_offsets_s = [0.0] * world_size
        self._clock_synced = bool(clock_sync) and world_size > 1
        if self._clock_synced:
            self._measure_clock_offsets()

    def _measure_clock_offsets(self, rounds: int = 5) -> None:
        """Barrier-based clock handshake: every rank reads ``time.time()``
        immediately after a shared barrier release and allgathers the
        readings; rank r's offset is the per-round median of
        ``t_r - t_0``. On one host the clocks are literally the same, so
        the offsets bound the barrier-exit jitter (~us-ms here) — the
        alignment error budget ``scripts/trace_merge.py`` inherits. The
        readings ride raw transport calls so the handshake itself never
        lands on the ``comm.*`` tracks. Stamped into the trace metadata
        (:func:`tracing.set_meta`) at init AND at :meth:`close`, so a
        tracer armed between the two still exports aligned ranks.
        """
        offsets = np.empty((rounds, self.world_size), np.float64)
        t = np.empty(1, np.float64)
        out = np.empty((self.world_size, 1), np.float64)
        for i in range(rounds):
            self._transport.barrier()
            t[0] = time.time()
            self._transport.allgather(t, out)
            offsets[i] = out[:, 0] - out[0, 0]
        per_rank = np.median(offsets, axis=0)
        self.clock_offsets_s = [float(o) for o in per_rank]
        self.clock_offset_s = self.clock_offsets_s[self.rank]
        self._stamp_clock_meta()

    def _stamp_clock_meta(self) -> None:
        tracing.set_meta(
            rank=self.rank,
            world_size=self.world_size,
            clock_offset_s=self.clock_offset_s,
            clock_offsets_s=self.clock_offsets_s,
        )

    def _hang(self, kind: str) -> bool:
        """The ``comm.hang`` injection poll at the top of every collective
        (one is-None test unarmed). ``mode=stall`` sleeps here and
        proceeds; ``mode=skip`` returns True and the caller skips the
        transport call entirely, returning its LOCAL data — the desynced
        rank the flight-recorder autopsy exists to name. A skipped
        collective deliberately leaves NO flight record: the victim's
        log really does end one operation early, which is exactly the
        evidence shape the ``missing_rank``/``mismatch`` verdicts key on."""
        act = faults.hang_action("comm.hang", kind)
        if act is None:
            return False
        mode, seconds = act
        if mode == "stall":
            time.sleep(seconds)
            return False
        return True  # skip

    def _flight(self, kind: str, op: str, count: int, dtype,
                payload_bytes: int) -> int:
        """Begin this collective's always-on flight record (ENQUEUED).
        Not tracer-gated on purpose — see runtime/flightrec.py; the
        per-record cost is pinned by bench.py's ``flightrec`` phase."""
        return flightrec.RECORDER.begin(
            kind, op, dtype, int(count),
            algo_wire_bytes(kind, payload_bytes, self.world_size),
            self._transport.kind, self.name,
        )

    @property
    def bytes_sent(self) -> int:
        """Cumulative data bytes this rank's transport pushed — exact
        socket-payload bytes on tcp (``Transport.bytes_exact``), the
        NCCL-convention algorithmic estimate on shm (a memcpy has no
        wire). The bench multihost phase pins the tcp counter against
        the analytic 2*(H-1)/H formula as an integer equality."""
        return self._transport.bytes_sent if self._transport else 0

    _FP_BYTES = 96

    def _verify_uniform(self, kind: str, a: np.ndarray, op: str = "") -> None:
        """Debug mode: every rank must be issuing the SAME collective with
        the same shape/dtype — divergence otherwise corrupts data or hangs.
        The fingerprints themselves ride a raw allgather over the ring."""
        sig = f"{kind}|{a.shape}|{a.dtype}|{op}".encode()[: self._FP_BYTES]
        buf = np.zeros(self._FP_BYTES, np.uint8)
        buf[: len(sig)] = np.frombuffer(sig, np.uint8)
        out = np.empty((self.world_size, self._FP_BYTES), np.uint8)
        self._transport.allgather(buf, out)
        sigs = [bytes(row).rstrip(b"\x00").decode() for row in out]
        if len(set(sigs)) != 1:
            detail = "; ".join(f"rank{r}: {s}" for r, s in enumerate(sigs))
            raise RuntimeError(
                f"collective mismatch across ranks (PTD_DISTRIBUTED_DEBUG"
                f"=DETAIL): {detail}"
            )

    def barrier(self) -> None:
        if self._hang("barrier"):
            return
        if self.debug:
            # a rank calling barrier() while peers issue a data collective
            # used to hang until the group deadline; the fingerprint
            # allgather meets the peers' _verify_uniform allgather and
            # both sides raise naming the divergent rank instead
            self._verify_uniform("barrier", np.zeros(0, np.uint8))
        fseq = self._flight("barrier", "", 0, "", 0)
        tr = tracing._tracer
        span = tracing._NULL_SPAN if tr is None else _comm_span(
            tr, "barrier", "", 0, "", 0, self.world_size,
            self._transport.kind,
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.barrier()
        flightrec.RECORDER.complete(fseq)

    def all_reduce(self, x, op: str = "sum", *, inplace: bool = False) -> np.ndarray:
        """``inplace=True`` reduces directly into ``x`` (torch
        ``dist.all_reduce`` semantics) — skipping a full payload copy,
        which on the 1-core shm topology is a measurable share of the
        op. ``x`` must then already be a C-contiguous supported-dtype
        ndarray: anything needing conversion would silently reduce into
        a private copy while the caller's buffer kept its local values
        (torch raises here too; divergence must never be quiet)."""
        a = _as_contig(x)
        if inplace:
            if a is not x:
                raise ValueError(
                    "all_reduce(inplace=True) needs a C-contiguous "
                    f"supported-dtype ndarray; got {type(x).__name__}"
                    " needing conversion — the reduction would land in "
                    "a copy and the caller's buffer would keep its "
                    "local values"
                )
        else:
            a = a.copy()
        if self._hang("all_reduce"):
            return a  # skipped: local values, peers left at the rendezvous
        if self.debug:
            self._verify_uniform("all_reduce", a, op)
        # floats average natively (divide-then-round in the C f32
        # accumulator); integers sum natively and floor-divide here
        int_avg = op == "avg" and a.dtype.kind in "iu"
        fseq = self._flight("all_reduce", op, a.size, a.dtype, a.nbytes)
        tr = tracing._tracer
        span = tracing._NULL_SPAN if tr is None else _comm_span(
            tr, "all_reduce", op, a.size, a.dtype, a.nbytes,
            self.world_size, self._transport.kind,
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.allreduce(a, "sum" if int_avg else op)
        flightrec.RECORDER.complete(fseq)
        if int_avg:
            a //= self.world_size
        return a

    def all_reduce_q8(self, x, op: str = "sum", *,
                      inplace: bool = False) -> np.ndarray:
        """Block-quantized f32 allreduce (EQuARX-style, PAPERS.md): int8
        payload + one f32 scale per 256 elements on the wire (~4x fewer
        bytes), f32 accumulation, identical results on every rank. Lossy
        (~1% of each 256-block's max-abs); opt-in for gradient sync.
        SUM/AVG only; f32 input only.

        Measured trade-off (2026-07-30, 12.8M elems, 4 procs, 1 core):
        ~2x SLOWER than the f32 path on this shm transport — quantization
        compute outweighs byte savings when the "wire" is a memcpy. The
        4x byte reduction pays off on network-bound transports (multi-host
        DCN), which is what the op exists for.
        """
        if op not in ("sum", "avg"):
            raise ValueError(f"q8 allreduce supports sum/avg, got {op!r}")
        if np.asarray(x).dtype != np.float32:
            raise TypeError(
                f"q8 allreduce is f32-only, got {np.asarray(x).dtype}"
            )
        if inplace:
            # the grad-sync pipeline's staging buffers: same contract as
            # all_reduce(inplace=True) — a buffer needing conversion
            # would silently reduce into a private copy
            a = _as_contig(x)
            if a is not x:
                raise ValueError(
                    "all_reduce_q8(inplace=True) needs a C-contiguous "
                    f"f32 ndarray; got {type(x).__name__} needing "
                    "conversion"
                )
        else:
            a = np.ascontiguousarray(x, dtype=np.float32).copy()
        if self._hang("all_reduce_q8"):
            return a
        if self.debug:
            self._verify_uniform("all_reduce_q8", a, op)
        fseq = flightrec.RECORDER.begin(
            "all_reduce_q8", op, a.dtype, int(a.size),
            algo_wire_bytes("all_reduce_q8", q8_wire_payload(a.size),
                            self.world_size),
            self._transport.kind, self.name,
        )
        tr = tracing._tracer
        # payload = the REAL wire occupancy of the quantized form (int8 +
        # one f32 scale per 256-elem block), NOT the f32 nbytes — the
        # recorded wire_bytes prove the ~4x reduction; f32_bytes rides
        # along so the ratio is computable from one span
        span = tracing._NULL_SPAN if tr is None else _CommSpan(
            tr, "comm.all_reduce_q8", {
                "op": op, "dtype": "float32(q8)", "count": int(a.size),
                "payload_bytes": q8_wire_payload(a.size),
                "f32_bytes": int(a.nbytes),
                "wire_bytes": algo_wire_bytes(
                    "all_reduce_q8", q8_wire_payload(a.size),
                    self.world_size,
                ),
                "world": self.world_size,
                "transport": self._transport.kind,
            },
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.allreduce_q8(a, op)
        flightrec.RECORDER.complete(fseq)
        return a

    def all_gather(self, x) -> np.ndarray:
        a = _as_contig(x, dtype_required=False)
        out = np.empty((self.world_size,) + a.shape, a.dtype)
        if self._hang("all_gather"):
            out[:] = a  # skipped: every row is this rank's local data
            return out
        if self.debug:
            self._verify_uniform("all_gather", a)
        fseq = self._flight("all_gather", "", a.size, a.dtype, out.nbytes)
        tr = tracing._tracer
        span = tracing._NULL_SPAN if tr is None else _comm_span(
            tr, "all_gather", "", a.size, a.dtype, out.nbytes,
            self.world_size, self._transport.kind,
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.allgather(a, out)
        flightrec.RECORDER.complete(fseq)
        return out

    def reduce_scatter(self, x, op: str = "sum") -> np.ndarray:
        """x: [world_size, ...] — returns this rank's reduced chunk x[rank]."""
        if op == "avg":  # the C AVG op divides only in hr_allreduce
            raise ValueError("op='avg' is only supported for all_reduce")
        half = np.asarray(x).dtype if np.asarray(x).dtype in _HALF else None
        if half is not None:
            x = np.asarray(x).astype(np.float32)
        a = _as_contig(x)
        if a.shape[0] != self.world_size:
            raise ValueError(
                f"leading dim {a.shape[0]} != world_size {self.world_size}"
            )
        out = np.empty(a.shape[1:], a.dtype)
        if self._hang("reduce_scatter"):
            out[:] = a[self.rank]  # skipped: this rank's unreduced chunk
            return out.astype(half) if half is not None else out
        if self.debug:
            self._verify_uniform("reduce_scatter", a, op)
        fseq = self._flight("reduce_scatter", op, a.size, a.dtype, a.nbytes)
        tr = tracing._tracer
        span = tracing._NULL_SPAN if tr is None else _comm_span(
            tr, "reduce_scatter", op, a.size, a.dtype, a.nbytes,
            self.world_size, self._transport.kind,
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.reduce_scatter(a, out, op)
        flightrec.RECORDER.complete(fseq)
        return out.astype(half) if half is not None else out

    def broadcast(self, x, src: int = 0, *,
                  inplace: bool = False) -> np.ndarray:
        """``inplace=True`` broadcasts directly into ``x`` (same
        contract as ``all_reduce(inplace=True)``: a buffer needing
        conversion would receive the bytes in a private copy while the
        caller's array kept stale values) — the hierarchical group's
        fan-out hop uses it to skip a full payload copy per leg."""
        if inplace:
            a = _as_contig(x, dtype_required=False)
            if a is not x:
                raise ValueError(
                    "broadcast(inplace=True) needs a C-contiguous "
                    f"ndarray; got {type(x).__name__} needing conversion"
                )
        else:
            a = _as_contig(x, dtype_required=False).copy()
        if self._hang("broadcast"):
            return a  # skipped: local bytes, whatever the src holds
        if self.debug:
            self._verify_uniform("broadcast", a, str(src))
        fseq = self._flight("broadcast", str(src), a.size, a.dtype, a.nbytes)
        tr = tracing._tracer
        span = tracing._NULL_SPAN if tr is None else _comm_span(
            tr, "broadcast", str(src), a.size, a.dtype, a.nbytes,
            self.world_size, self._transport.kind,
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.broadcast(a, src)
        flightrec.RECORDER.complete(fseq)
        return a

    def all_to_all(self, x) -> np.ndarray:
        """x: this rank's [world*chunk, ...] row, chunk j destined for rank
        j — returns [world*chunk, ...] of the chunks addressed to this rank
        (torch ``all_to_all_single`` semantics). Composed from all_gather;
        the CPU smoke path favors simplicity over the 2x bandwidth."""
        a = _as_contig(x, dtype_required=False)
        w = self.world_size
        if a.shape[0] % w:
            raise ValueError(
                f"dim 0 {a.shape[0]} not divisible by world_size {w}"
            )
        g = self.all_gather(a)  # [w, w*chunk, ...]
        c = a.shape[0] // w
        r = self.rank
        return np.concatenate([g[j, r * c:(r + 1) * c] for j in range(w)])

    def scatter(self, x, src: int = 0) -> np.ndarray:
        """x: [world_size, ...] (meaningful on ``src``) — returns this
        rank's row x[rank] (torch ``scatter`` semantics)."""
        a = _as_contig(x, dtype_required=False)
        if a.shape[0] != self.world_size:
            raise ValueError(
                f"leading dim {a.shape[0]} != world_size {self.world_size}"
            )
        return self.broadcast(a, src=src)[self.rank]

    def _verify_p2p(
        self, a: np.ndarray, src: int, dst: int, tag: str = ""
    ) -> None:
        """Debug mode for the P2P pair: both endpoints describe the
        transfer (``shape|dtype|src->dst|tag``) and exchange the 96-byte
        fingerprints over the same mailbox pair BEFORE the payload — a
        shape/dtype/peer/tag mismatch raises on BOTH ranks naming both
        descriptions, instead of a silently short/corrupt copy or a
        mailbox hang. The caller-supplied ``tag`` (the r20 pipeline
        stamps ``(microbatch, stage, direction)``) extends the handshake
        to PROTOCOL mismatches: same shape, wrong message — the schedule
        desync a shape check can't see. Debug mode must be uniform across
        ranks (true for the env-var arming): a lone debug endpoint would
        ship its fingerprint into a peer expecting payload."""
        sig = f"p2p|{a.shape}|{a.dtype}|{src}->{dst}|{tag}".encode()
        mine = np.zeros(self._FP_BYTES, np.uint8)
        mine[: len(sig[: self._FP_BYTES])] = np.frombuffer(
            sig[: self._FP_BYTES], np.uint8
        )
        theirs = np.zeros(self._FP_BYTES, np.uint8)
        if self.rank == src:  # fingerprint ahead of payload, echo back
            self._transport.sendrecv(mine, src, dst)
            self._transport.sendrecv(theirs, dst, src)
        else:
            self._transport.sendrecv(theirs, src, dst)
            self._transport.sendrecv(mine, dst, src)
        if bytes(mine) != bytes(theirs):
            me = bytes(mine).rstrip(b"\x00").decode()
            peer = bytes(theirs).rstrip(b"\x00").decode()
            raise RuntimeError(
                f"P2P mismatch (PTD_DISTRIBUTED_DEBUG=DETAIL): rank"
                f"{self.rank} expects {me}; peer sees {peer}"
            )

    def send(self, x, dst: int, *, tag: str = "") -> None:
        """True point-to-point send: only this rank and ``dst`` participate
        (per-pair shm mailbox — no group barrier, bystander ranks are free
        to run other collectives or nothing at all). ``tag`` names the
        message (default "" keeps old callers byte-compatible); under
        DETAIL debug both ends must present the same tag."""
        a = _as_contig(x, dtype_required=False).copy()
        if self._hang("send"):
            return  # skipped: the peer's recv is left hanging
        if self.debug:
            self._verify_p2p(a, self.rank, dst, tag)
        fseq = self._flight("send", f"->{dst}", a.size, a.dtype, a.nbytes)
        tr = tracing._tracer
        span = tracing._NULL_SPAN if tr is None else _comm_span(
            tr, "send", f"->{dst}", a.size, a.dtype, a.nbytes,
            self.world_size, self._transport.kind,
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.sendrecv(a, self.rank, dst)
        flightrec.RECORDER.complete(fseq)

    def recv(self, x, src: int, *, tag: str = "") -> np.ndarray:
        """x supplies shape/dtype; returns the received array. True P2P —
        see :meth:`send` (and its ``tag``)."""
        a = _as_contig(x, dtype_required=False).copy()
        if self._hang("recv"):
            return a  # skipped: stale local bytes, the sender left hanging
        if self.debug:
            self._verify_p2p(a, src, self.rank, tag)
        fseq = self._flight("recv", f"<-{src}", a.size, a.dtype, a.nbytes)
        tr = tracing._tracer
        span = tracing._NULL_SPAN if tr is None else _comm_span(
            tr, "recv", f"<-{src}", a.size, a.dtype, a.nbytes,
            self.world_size, self._transport.kind,
        )
        flightrec.RECORDER.start(fseq)
        with span:
            self._transport.sendrecv(a, src, self.rank)
        flightrec.RECORDER.complete(fseq)
        return a

    def close(self) -> None:
        if self._transport is not None:
            if self._clock_synced:
                # re-stamp (no re-measure: close() isn't barrier-safe —
                # a lone closing rank must not block on absent peers): a
                # tracer armed AFTER init still exports aligned metadata
                self._stamp_clock_meta()
            self._transport.close()
            self._transport = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
