"""ctypes bindings for the native shared-memory host collectives.

The reference's CPU smoke path is true multi-process training over the gloo
process group (SURVEY.md §2: gloo -> "single-host CPU backend of the same
API (... host ring in C++)"). ``native/hostring.cpp`` is that backend: N OS
processes rendezvous on a POSIX shm segment and run collectives through
per-rank slots under a process-shared barrier.

This module is deliberately JAX-free so spawned worker processes can import
it without dragging in (or re-initialising) a TPU runtime. Semantics are
torch.distributed-shaped: each *process* passes its local tensor.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "hostring.cpp")
_SO = os.path.join(_NATIVE_DIR, "libhostring.so")

_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.float16): 6,  # F16: native 2-byte collectives
}
_U8 = 4  # raw-byte dtype: copy-shaped collectives on arbitrary dtypes
# "avg" is a REAL C-side op for float dtypes: the division happens in the
# f32 accumulator before the final rounding, so half averages can't
# overflow the way divide-after-rounded-sum would (f16 avg of 30000.0 x4)
_OPS = {"sum": 0, "prod": 1, "product": 1, "max": 2, "min": 3, "avg": 4}

# Half dtypes (the TPU compute dtypes): ``all_reduce`` ships them NATIVELY
# at 2-byte bandwidth — the C side accumulates each segment in f32 and
# rounds once, NCCL's half-allreduce design. The remaining reduction
# (reduce_scatter) still takes the f32 round trip below.
_HALF = {np.dtype(np.float16)}
try:  # ml_dtypes ships with jax
    import ml_dtypes

    _HALF.add(np.dtype(ml_dtypes.bfloat16))
    _DTYPES[np.dtype(ml_dtypes.bfloat16)] = 5  # BF16
except ImportError:  # pragma: no cover
    pass

_lib: Optional[ctypes.CDLL] = None


def build_library(force: bool = False) -> str:
    """Compile libhostring.so if missing/stale; returns the path."""
    from pytorch_distributed_tpu.utils.native_build import (
        build_native_library,
    )

    return build_native_library(
        _SRC, _SO, extra_flags=("-pthread", "-lrt"), force=force
    )


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_library())
        lib.hr_init.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_double, ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.hr_init.restype = ctypes.c_int
        for name, args in {
            "hr_barrier": [ctypes.c_void_p],
            "hr_allreduce": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32, ctypes.c_int32,
            ],
            "hr_allreduce_q8": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32,
            ],
            "hr_allgather": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_int32,
            ],
            "hr_reduce_scatter": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ],
            "hr_broadcast": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32,
            ],
            "hr_sendrecv": [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int32, ctypes.c_int32,
            ],
            "hr_finalize": [ctypes.c_void_p],
        }.items():
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = ctypes.c_int
        _lib = lib
    return _lib


def _check(rc: int, what: str) -> None:
    if rc != 0:
        raise RuntimeError(f"hostring {what} failed (rc={rc}; "
                           f"-110=peer timeout, -22=bad args, -5=peer died)")


def _as_contig(x, dtype_required=True) -> np.ndarray:
    a = np.ascontiguousarray(x)
    if dtype_required and a.dtype not in _DTYPES:
        raise TypeError(
            f"unsupported dtype {a.dtype}; one of {list(_DTYPES)} required"
        )
    return a


def unlink_segment(name: str) -> None:
    """Best-effort removal of a group's shm segment (launcher teardown).

    Workers killed mid-collective never reach ``hr_finalize``; the
    supervising agent calls this after reaping them so abandoned attempts
    don't accumulate in /dev/shm.
    """
    import glob

    shm = name.strip("/").replace("/", "_")
    # init_process_group suffixes a per-init generation (_gN) onto the
    # group name; reap those too so abandoned re-inits don't accumulate.
    for path in [os.path.join("/dev/shm", shm)] + glob.glob(
        os.path.join("/dev/shm", shm + "_g*")
    ):
        try:
            os.unlink(path)
        except OSError:
            pass


class HostRingGroup:
    """One process's membership in a shared-memory collectives group."""

    def __init__(
        self,
        name: str,
        rank: int,
        world_size: int,
        *,
        slot_bytes: int = 4 << 20,
        timeout_s: float = 120.0,
        debug: Optional[bool] = None,
    ):
        lib = _load()
        handle = ctypes.c_void_p()
        # shm names must start with '/' and contain no further slashes
        shm = "/" + name.strip("/").replace("/", "_")
        rc = lib.hr_init(
            shm.encode(), rank, world_size, slot_bytes, timeout_s,
            ctypes.byref(handle),
        )
        _check(rc, "init")
        self._h = handle
        self.rank = rank
        self.world_size = world_size
        self.timeout_s = timeout_s
        if debug is None:
            # DETAIL turns on cross-rank call verification, the analogue
            # of TORCH_DISTRIBUTED_DEBUG=DETAIL (SURVEY.md §5: collective
            # mismatch is the SPMD-era data race)
            debug = os.environ.get(
                "PTD_DISTRIBUTED_DEBUG", ""
            ).upper() == "DETAIL"
        self.debug = debug

    _FP_BYTES = 96

    def _verify_uniform(self, kind: str, a: np.ndarray, op: str = "") -> None:
        """Debug mode: every rank must be issuing the SAME collective with
        the same shape/dtype — divergence otherwise corrupts data or hangs.
        The fingerprints themselves ride a raw allgather over the ring."""
        sig = f"{kind}|{a.shape}|{a.dtype}|{op}".encode()[: self._FP_BYTES]
        buf = np.zeros(self._FP_BYTES, np.uint8)
        buf[: len(sig)] = np.frombuffer(sig, np.uint8)
        out = np.empty((self.world_size, self._FP_BYTES), np.uint8)
        rc = _load().hr_allgather(
            self._h, buf.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p), self._FP_BYTES, _U8,
        )
        _check(rc, "debug fingerprint allgather")
        sigs = [bytes(row).rstrip(b"\x00").decode() for row in out]
        if len(set(sigs)) != 1:
            detail = "; ".join(f"rank{r}: {s}" for r, s in enumerate(sigs))
            raise RuntimeError(
                f"collective mismatch across ranks (PTD_DISTRIBUTED_DEBUG"
                f"=DETAIL): {detail}"
            )

    def barrier(self) -> None:
        _check(_load().hr_barrier(self._h), "barrier")

    def all_reduce(self, x, op: str = "sum", *, inplace: bool = False) -> np.ndarray:
        """``inplace=True`` reduces directly into ``x`` (torch
        ``dist.all_reduce`` semantics) — skipping a full payload copy,
        which on the 1-core shm topology is a measurable share of the
        op. ``x`` must then already be a C-contiguous supported-dtype
        ndarray: anything needing conversion would silently reduce into
        a private copy while the caller's buffer kept its local values
        (torch raises here too; divergence must never be quiet)."""
        a = _as_contig(x)
        if inplace:
            if a is not x:
                raise ValueError(
                    "all_reduce(inplace=True) needs a C-contiguous "
                    f"supported-dtype ndarray; got {type(x).__name__}"
                    " needing conversion — the reduction would land in "
                    "a copy and the caller's buffer would keep its "
                    "local values"
                )
        else:
            a = a.copy()
        if self.debug:
            self._verify_uniform("all_reduce", a, op)
        # floats average natively (divide-then-round in the C f32
        # accumulator); integers sum natively and floor-divide here
        int_avg = op == "avg" and a.dtype.kind in "iu"
        rc = _load().hr_allreduce(
            self._h, a.ctypes.data_as(ctypes.c_void_p), a.size,
            _DTYPES[a.dtype], _OPS["sum" if int_avg else op],
        )
        _check(rc, "all_reduce")
        if int_avg:
            a //= self.world_size
        return a

    def all_reduce_q8(self, x, op: str = "sum") -> np.ndarray:
        """Block-quantized f32 allreduce (EQuARX-style, PAPERS.md): int8
        payload + one f32 scale per 256 elements on the wire (~4x fewer
        bytes), f32 accumulation, identical results on every rank. Lossy
        (~1% of each 256-block's max-abs); opt-in for gradient sync.
        SUM/AVG only; f32 input only.

        Measured trade-off (2026-07-30, 12.8M elems, 4 procs, 1 core):
        ~2x SLOWER than the f32 path on this shm transport — quantization
        compute outweighs byte savings when the "wire" is a memcpy. The
        4x byte reduction pays off on network-bound transports (multi-host
        DCN), which is what the op exists for.
        """
        if op not in ("sum", "avg"):
            raise ValueError(f"q8 allreduce supports sum/avg, got {op!r}")
        if np.asarray(x).dtype != np.float32:
            raise TypeError(
                f"q8 allreduce is f32-only, got {np.asarray(x).dtype}"
            )
        a = np.ascontiguousarray(x, dtype=np.float32).copy()
        if self.debug:
            self._verify_uniform("all_reduce_q8", a, op)
        rc = _load().hr_allreduce_q8(
            self._h, a.ctypes.data_as(ctypes.c_void_p), a.size,
            _OPS[op],
        )
        _check(rc, "all_reduce_q8")
        return a

    def all_gather(self, x) -> np.ndarray:
        a = _as_contig(x, dtype_required=False)
        if self.debug:
            self._verify_uniform("all_gather", a)
        out = np.empty((self.world_size,) + a.shape, a.dtype)
        if a.dtype in _DTYPES:
            count, dt = a.size, _DTYPES[a.dtype]
        else:  # any other dtype gathers as raw bytes
            count, dt = a.nbytes, _U8
        rc = _load().hr_allgather(
            self._h, a.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p), count, dt,
        )
        _check(rc, "all_gather")
        return out

    def reduce_scatter(self, x, op: str = "sum") -> np.ndarray:
        """x: [world_size, ...] — returns this rank's reduced chunk x[rank]."""
        if op == "avg":  # the C AVG op divides only in hr_allreduce
            raise ValueError("op='avg' is only supported for all_reduce")
        half = np.asarray(x).dtype if np.asarray(x).dtype in _HALF else None
        if half is not None:
            x = np.asarray(x).astype(np.float32)
        a = _as_contig(x)
        if a.shape[0] != self.world_size:
            raise ValueError(
                f"leading dim {a.shape[0]} != world_size {self.world_size}"
            )
        if self.debug:
            self._verify_uniform("reduce_scatter", a, op)
        out = np.empty(a.shape[1:], a.dtype)
        chunk = int(np.prod(a.shape[1:], dtype=np.int64))
        rc = _load().hr_reduce_scatter(
            self._h, a.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p), chunk, _DTYPES[a.dtype],
            _OPS[op],
        )
        _check(rc, "reduce_scatter")
        return out.astype(half) if half is not None else out

    def broadcast(self, x, src: int = 0) -> np.ndarray:
        a = _as_contig(x, dtype_required=False).copy()
        if self.debug:
            self._verify_uniform("broadcast", a, str(src))
        rc = _load().hr_broadcast(
            self._h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes, src
        )
        _check(rc, "broadcast")
        return a

    def all_to_all(self, x) -> np.ndarray:
        """x: this rank's [world*chunk, ...] row, chunk j destined for rank
        j — returns [world*chunk, ...] of the chunks addressed to this rank
        (torch ``all_to_all_single`` semantics). Composed from all_gather;
        the CPU smoke path favors simplicity over the 2x bandwidth."""
        a = _as_contig(x, dtype_required=False)
        w = self.world_size
        if a.shape[0] % w:
            raise ValueError(
                f"dim 0 {a.shape[0]} not divisible by world_size {w}"
            )
        g = self.all_gather(a)  # [w, w*chunk, ...]
        c = a.shape[0] // w
        r = self.rank
        return np.concatenate([g[j, r * c:(r + 1) * c] for j in range(w)])

    def scatter(self, x, src: int = 0) -> np.ndarray:
        """x: [world_size, ...] (meaningful on ``src``) — returns this
        rank's row x[rank] (torch ``scatter`` semantics)."""
        a = _as_contig(x, dtype_required=False)
        if a.shape[0] != self.world_size:
            raise ValueError(
                f"leading dim {a.shape[0]} != world_size {self.world_size}"
            )
        return self.broadcast(a, src=src)[self.rank]

    def send(self, x, dst: int) -> None:
        """True point-to-point send: only this rank and ``dst`` participate
        (per-pair shm mailbox — no group barrier, bystander ranks are free
        to run other collectives or nothing at all)."""
        a = _as_contig(x, dtype_required=False).copy()
        rc = _load().hr_sendrecv(
            self._h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes,
            self.rank, dst,
        )
        _check(rc, "send")

    def recv(self, x, src: int) -> np.ndarray:
        """x supplies shape/dtype; returns the received array. True P2P —
        see :meth:`send`."""
        a = _as_contig(x, dtype_required=False).copy()
        rc = _load().hr_sendrecv(
            self._h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes,
            src, self.rank,
        )
        _check(rc, "recv")
        return a

    def close(self) -> None:
        if self._h:
            _load().hr_finalize(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
