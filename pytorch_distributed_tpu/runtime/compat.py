"""Version shims for jax APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
``jax`` namespace, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` in the same window. The container pins
whatever jaxlib the accelerator toolchain ships, so both spellings must
work; every in-repo caller imports the wrapper below instead of picking
a spelling.
"""

from __future__ import annotations

try:  # jax >= 0.6: public API, kwarg is check_vma
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4/0.5: experimental API, kwarg is check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication-check flag normalized to
    the new ``check_vma`` name regardless of the installed jax."""
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis inside traced code —
    ``lax.axis_size`` where it exists (newer jax), else jax 0.4's
    ``core.axis_frame`` (which returns the int directly there)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core as core

    frame = core.axis_frame(axis_name)
    return getattr(frame, "size", frame)


def abstract_mesh(axis_sizes, axis_names):
    """``jax.sharding.AbstractMesh`` across its signature change:
    newer jax takes ``(axis_sizes, axis_names)``, jax 0.4 takes one
    ``((name, size), ...)`` shape tuple."""
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh(tuple(axis_sizes), tuple(axis_names))
    except TypeError:
        return AbstractMesh(tuple(zip(axis_names, axis_sizes)))


def jit_cache_size(fn):
    """Compiled-specialization count of a jitted callable, or None.

    The recompile sentinel (runtime/tracing.py) polls this after each
    step: a steady-state loop whose count grows is silently recompiling.
    ``_cache_size`` is private jax API present on PjitFunction across
    the 0.4–0.6 window this repo supports; any absence/failure degrades
    to None (sentinel off for that callable) rather than raising in the
    hot loop.
    """
    f = getattr(fn, "_cache_size", None)
    if not callable(f):
        return None
    try:
        return int(f())
    except Exception:  # pragma: no cover - backend/version specific
        return None


def live_buffer_bytes():
    """Live device-buffer bytes, or None when nothing can report them.

    TPU/GPU backends expose per-device ``memory_stats()['bytes_in_use']``
    — the allocator's own number, preferred. XLA:CPU reports no memory
    stats, so the fallback sums ``nbytes`` over ``jax.live_arrays()``
    (committed arrays only — it cannot see donated/internal scratch, but
    it tracks the leak shapes that matter: caches, states, stale
    references). Sampled at log cadence only; never on the step path.
    """
    import jax

    total, saw = 0, False
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
        except Exception:  # pragma: no cover - backend-dependent
            s = None
        if s and "bytes_in_use" in s:
            total += int(s["bytes_in_use"])
            saw = True
    if saw:
        return total
    live = getattr(jax, "live_arrays", None)
    if live is None:  # pragma: no cover - very old jax
        return None
    try:
        return int(sum(getattr(a, "nbytes", 0) or 0 for a in live()))
    except Exception:  # pragma: no cover - defensive: gauge must not kill
        return None
