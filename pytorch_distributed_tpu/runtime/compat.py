"""Version shims for jax APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
``jax`` namespace, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` in the same window. The container pins
whatever jaxlib the accelerator toolchain ships, so both spellings must
work; every in-repo caller imports the wrapper below instead of picking
a spelling.
"""

from __future__ import annotations

try:  # jax >= 0.6: public API, kwarg is check_vma
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4/0.5: experimental API, kwarg is check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication-check flag normalized to
    the new ``check_vma`` name regardless of the installed jax."""
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis inside traced code —
    ``lax.axis_size`` where it exists (newer jax), else jax 0.4's
    ``core.axis_frame`` (which returns the int directly there)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core as core

    frame = core.axis_frame(axis_name)
    return getattr(frame, "size", frame)


def abstract_mesh(axis_sizes, axis_names):
    """``jax.sharding.AbstractMesh`` across its signature change:
    newer jax takes ``(axis_sizes, axis_names)``, jax 0.4 takes one
    ``((name, size), ...)`` shape tuple."""
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh(tuple(axis_sizes), tuple(axis_names))
    except TypeError:
        return AbstractMesh(tuple(zip(axis_names, axis_sizes)))
