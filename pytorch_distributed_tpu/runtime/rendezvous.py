"""Pluggable rendezvous channels for elastic membership.

``WorldMembership`` (runtime/membership.py) speaks one small record
protocol: upsert my member record (worker id, pid, epoch bid), read all
live records (dead members garbage-collected), read/write the committed
``view-<epoch>`` audit records, drop my record on clean exit. Through
r15 that protocol had exactly one home — a shared directory — which is
also the reason the whole membership layer was single-host: the module
docstring promised "the multi-host version of this protocol would put
the same records on the coordinator's KV store". This module keeps that
promise: the channel is now an interface with two implementations,

* :class:`FileRendezvousChannel` — the r13 directory protocol verbatim
  (atomic tmp+rename record writes, pid-based liveness with the
  zombie-aware /proc check, any member reaps dead records), and
* :class:`TcpRendezvousChannel` — the same records over ONE persistent
  connection per member to a :class:`RendezvousServer`. Liveness is the
  connection itself: the kernel closes a SIGKILLed member's socket, and
  the server drops its record — strictly better than pid polling (pids
  are meaningless across hosts, and there is no recycled-pid aliasing
  window). Max-bid-wins, settle, and the view-commit barrier all live
  ABOVE the channel and run unchanged over either one.

``WorldMembership(rendezvous_dir="tcp://host:port", ...)`` selects the
TCP channel; anything else is a directory path. The per-view data-plane
rings are constructed by membership, not the channel — on one box they
stay shm regardless of which channel carried the rendezvous (the
channels agree on the ``key()`` string the shm prefix is derived from
only within one channel kind, which is fine: a world must anyway agree
on its rendezvous address).

jax-free, like the rest of the runtime stack.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from pytorch_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MEMBER_PREFIX = "member-"
_VIEW_PREFIX = "view-"


class RendezvousChannel:
    """The record protocol :class:`WorldMembership` settles views over."""

    def key(self) -> str:
        """Stable identity string every member of this rendezvous derives
        identically — the shm ring prefix hashes it."""
        raise NotImplementedError

    def write_member(self, rec: dict) -> None:
        """Upsert this process's member record (keyed by worker_id)."""
        raise NotImplementedError

    def read_members(self) -> List[dict]:
        """All LIVE member records; the channel garbage-collects dead
        members (dead pid / dropped connection) before returning."""
        raise NotImplementedError

    def remove_member(self, worker_id: str) -> None:
        raise NotImplementedError

    def last_committed_epoch(self) -> int:
        raise NotImplementedError

    def write_view_record(self, rec: dict) -> None:
        """Persist the committed ``view-<epoch>`` audit record."""
        raise NotImplementedError

    def close(self) -> None:
        pass


def _pid_alive(pid: int) -> bool:
    """Is ``pid`` a live (non-zombie) process?

    ``os.kill(pid, 0)`` alone is wrong here: a SIGKILLed worker stays a
    ZOMBIE until its launcher reaps it, and kill(0) reports zombies as
    alive — the survivors' candidate set would never settle. /proc's
    stat state field distinguishes them (this backend is Linux-only shm
    already); kill(0) is the fallback when /proc is unreadable.
    """
    if pid <= 0:
        return False
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        # state is the first field after the parenthesized comm (which
        # may itself contain spaces/parens — split on the LAST ')')
        state = stat.rsplit(b")", 1)[1].split()[0]
        return state not in (b"Z", b"X")
    except (OSError, IndexError):
        pass
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - someone else's pid
        return True
    return True


class FileRendezvousChannel(RendezvousChannel):
    """The shared-directory channel (single-host): one
    ``member-<id>.json`` per live member, ``view-<epoch>.json`` audit
    records, pid liveness, torn writes tolerated (the writer replaces
    them atomically)."""

    def __init__(self, rendezvous_dir: str):
        self.dir = os.path.abspath(rendezvous_dir)
        os.makedirs(self.dir, exist_ok=True)

    def key(self) -> str:
        return self.dir

    def _member_path(self, worker_id: str) -> str:
        return os.path.join(self.dir, _MEMBER_PREFIX + worker_id + ".json")

    def write_member(self, rec: dict) -> None:
        path = self._member_path(rec["worker_id"])
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)

    def read_members(self) -> List[dict]:
        out = []
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return out
        for name in names:
            if not (name.startswith(_MEMBER_PREFIX)
                    and name.endswith(".json")):
                continue
            path = os.path.join(self.dir, name)
            try:
                with open(path) as f:
                    rec = json.load(f)
                pid = int(rec["pid"])
                str(rec["worker_id"])
                int(rec["bid"])
            except (OSError, ValueError, TypeError, KeyError):
                continue  # torn write: the writer will replace it
            if not _pid_alive(pid):
                # the garbage collection of the protocol: any member may
                # reap a dead peer's record (peer loss becomes visible
                # to poll_change even before a collective deadline)
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            out.append(rec)
        return out

    def remove_member(self, worker_id: str) -> None:
        try:
            os.unlink(self._member_path(worker_id))
        except OSError:
            pass

    def last_committed_epoch(self) -> int:
        best = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        for name in names:
            if name.startswith(_VIEW_PREFIX) and name.endswith(".json"):
                try:
                    best = max(best, int(name[len(_VIEW_PREFIX):-5]))
                except ValueError:
                    continue
        return best

    def write_view_record(self, rec: dict) -> None:
        path = os.path.join(self.dir, f"{_VIEW_PREFIX}{rec['epoch']}.json")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)


# --------------------------------------------------------------------------
# The TCP channel: same records, one coordinator, connection liveness.
# --------------------------------------------------------------------------
def _send_line(sock: socket.socket, obj: dict) -> None:
    sock.sendall(json.dumps(obj).encode() + b"\n")


class _LineReader:
    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()

    def read(self) -> Optional[dict]:
        while b"\n" not in self._buf:
            b = self._sock.recv(65536)
            if not b:
                return None
            self._buf += b
            if len(self._buf) > 16 << 20:
                raise RuntimeError("oversized rendezvous frame")
        line, _, rest = bytes(self._buf).partition(b"\n")
        self._buf = bytearray(rest)
        return json.loads(line.decode())


class RendezvousServer:
    """The coordinator: member records keyed by worker_id, each owned by
    the connection that announced it (drop the connection, drop the
    record — SIGKILL becomes visible at kernel-close speed), plus the
    committed view audit records. One thread per client; state under one
    lock. Run it anywhere every member can reach — the launcher process
    on one box, a head node in a real fleet."""

    def __init__(self, addr: str = "127.0.0.1:0"):
        host, _, port = addr.rpartition(":")
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host or "127.0.0.1", int(port)))
        self._lsock.listen(64)
        self.host, self.port = self._lsock.getsockname()[:2]
        self.addr = f"{self.host}:{self.port}"
        self._lock = threading.Lock()
        self._members: Dict[str, dict] = {}
        self._owner: Dict[str, socket.socket] = {}
        self._views: Dict[int, dict] = {}
        self._conns: set = set()
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ptd-rdzv-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            if self._closing:  # the close() wake-up connection
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="ptd-rdzv-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        owned: Optional[str] = None
        reader = _LineReader(conn)
        with self._lock:
            self._conns.add(conn)
        try:
            while True:
                req = reader.read()
                if req is None:
                    return
                cmd = req.get("cmd")
                if cmd == "announce":
                    rec = dict(req["rec"])
                    wid = str(rec["worker_id"])
                    with self._lock:
                        self._members[wid] = rec
                        self._owner[wid] = conn
                    owned = wid
                    _send_line(conn, {"ok": True})
                elif cmd == "members":
                    with self._lock:
                        recs = list(self._members.values())
                    _send_line(conn, {"members": recs})
                elif cmd == "leave":
                    self._drop(str(req["worker_id"]), conn)
                    owned = None
                    _send_line(conn, {"ok": True})
                elif cmd == "view":
                    rec = dict(req["rec"])
                    with self._lock:
                        self._views[int(rec["epoch"])] = rec
                    _send_line(conn, {"ok": True})
                elif cmd == "last_epoch":
                    with self._lock:
                        epoch = max(self._views, default=0)
                    _send_line(conn, {"epoch": epoch})
                elif cmd == "views":
                    with self._lock:
                        views = list(self._views.values())
                    _send_line(conn, {"views": views})
                else:
                    _send_line(conn, {"error": f"unknown cmd {cmd!r}"})
        except (OSError, ValueError, KeyError, RuntimeError):
            pass
        finally:
            # connection gone: the member it owned is dead (the GC of
            # the protocol — the kernel closed this socket even if the
            # process was SIGKILLed mid-collective)
            if owned is not None:
                self._drop(owned, conn)
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _drop(self, worker_id: str, conn: socket.socket) -> None:
        with self._lock:
            if self._owner.get(worker_id) is conn:
                self._members.pop(worker_id, None)
                self._owner.pop(worker_id, None)

    def views(self) -> List[dict]:
        with self._lock:
            return [self._views[e] for e in sorted(self._views)]

    def close(self) -> None:
        """Stop accepting AND sever every live client connection: a
        closed coordinator must not keep serving stale membership — the
        clients' next RPC raises loudly instead."""
        self._closing = True
        # Closing the listener fd does NOT interrupt a thread already
        # parked inside accept() on it — the loop would keep serving new
        # connections on a "closed" server. Wake it with a throwaway
        # connection, join it, THEN release the port.
        try:
            w = socket.create_connection((self.host, self.port), timeout=1.0)
            w.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=5.0)
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class TcpRendezvousChannel(RendezvousChannel):
    """Client side: one persistent connection carrying JSON-line RPCs.
    The connection doubles as the liveness lease — losing it (server
    gone) makes every later call raise loudly rather than settle on a
    stale view."""

    def __init__(self, addr: str, *, timeout_s: float = 60.0):
        if addr.startswith("tcp://"):
            addr = addr[len("tcp://"):]
        self.addr = addr
        host, _, port = addr.rpartition(":")
        deadline = time.monotonic() + timeout_s
        while True:
            self._sock = socket.socket()
            self._sock.settimeout(timeout_s)
            try:
                self._sock.connect((host or "127.0.0.1", int(port)))
                # connect() alone doesn't prove the server is alive — a
                # SYN can land in a dead listener's backlog and "succeed"
                # with nobody ever serving the connection. One ping
                # round-trip at construction makes "server gone" loud at
                # the join point instead of a hang on the first real RPC.
                self._sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                reader = _LineReader(self._sock)
                _send_line(self._sock, {"cmd": "last_epoch"})
                if reader.read() is None:
                    raise OSError("server closed during handshake")
                break
            except OSError:
                self._sock.close()
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"rendezvous server at {addr} unreachable for "
                        f"{timeout_s:.0f}s"
                    ) from None
                time.sleep(0.05)
        self._reader = reader
        self._lock = threading.Lock()

    def key(self) -> str:
        return "tcp://" + self.addr

    def _rpc(self, req: dict) -> dict:
        with self._lock:
            _send_line(self._sock, req)
            reply = self._reader.read()
        if reply is None:
            raise RuntimeError(
                f"rendezvous server at {self.addr} closed the connection"
            )
        if "error" in reply:
            raise RuntimeError(f"rendezvous rpc failed: {reply['error']}")
        return reply

    def write_member(self, rec: dict) -> None:
        self._rpc({"cmd": "announce", "rec": rec})

    def read_members(self) -> List[dict]:
        return [dict(r) for r in self._rpc({"cmd": "members"})["members"]]

    def remove_member(self, worker_id: str) -> None:
        self._rpc({"cmd": "leave", "worker_id": worker_id})

    def last_committed_epoch(self) -> int:
        return int(self._rpc({"cmd": "last_epoch"})["epoch"])

    def write_view_record(self, rec: dict) -> None:
        self._rpc({"cmd": "view", "rec": rec})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def open_channel(rendezvous_dir: str, *,
                 timeout_s: float = 60.0) -> RendezvousChannel:
    """``tcp://host:port`` selects the TCP channel; anything else is a
    shared directory."""
    if rendezvous_dir.startswith("tcp://"):
        return TcpRendezvousChannel(rendezvous_dir, timeout_s=timeout_s)
    return FileRendezvousChannel(rendezvous_dir)
