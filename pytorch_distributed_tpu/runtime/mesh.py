"""Mesh construction — the TPU-native replacement for process-group "world"
setup.

Where the reference's recipes build a world of N one-GPU processes
(``torchrun`` / ``mp.spawn`` + ``init_process_group('nccl')``,
BASELINE.json:5), a TPU framework builds ONE logical device mesh and lets
XLA place collectives over ICI/DCN. All parallelism strategies in
``pytorch_distributed_tpu.parallel`` are expressed against the named axes of
this mesh:

=========  =====================================================
axis       meaning
=========  =====================================================
``dp``     data parallel (batch sharding; DDP / ZeRO-1 gradient axis)
``fsdp``   fully-sharded data parallel (params + batch sharded)
``pp``     pipeline parallel (layer stages; GPipe schedule over ppermute)
``tp``     tensor/model parallel (weight matrices sharded)
``sp``     sequence/context parallel (ring attention axis)
``ep``     expert parallel (MoE experts sharded)
=========  =====================================================

Axes of size 1 are kept in the mesh so PartitionSpecs mentioning them are
always valid; XLA elides the no-op collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order. dp outermost, tp innermost: tensor-parallel
# collectives are per-layer and latency-bound, so they should ride the
# fastest (innermost/ICI-adjacent) axis; dp allreduce happens once per step
# and tolerates the slower outer axis (DCN on multi-pod).
AXES: Tuple[str, ...] = ("dp", "fsdp", "pp", "ep", "sp", "tp")

_CURRENT_MESH: Optional[Mesh] = None


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes. ``-1`` on at most one axis means "absorb the
    remaining devices" (like a reshape wildcard)."""

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill in the -1 wildcard so the product equals ``n_devices``."""
        sizes = list(self.sizes())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one -1 axis allowed, got spec {self}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product "
                    f"{fixed} (spec {self})"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"MeshSpec {self} wants {fixed} devices, have {n_devices}"
            )
        return MeshSpec(**dict(zip(AXES, sizes)))


def make_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    set_current: bool = True,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over all (or the given) devices.

    Uses ``mesh_utils.create_device_mesh`` on real hardware so axis
    adjacency maps onto the physical ICI torus; falls back to a plain
    reshape for CPU/virtual devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolve(len(devices))
    shape = spec.sizes()
    if devices[0].platform == "tpu" and len(devices) > 1:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, NotImplementedError) as e:
            # A flat reshape still works but loses ICI adjacency — tp
            # collectives may cross slow links. Loud, not silent.
            import logging

            logging.getLogger(__name__).warning(
                "create_device_mesh failed (%s); falling back to flat reshape "
                "— mesh axes will not follow the physical ICI torus", e
            )
            dev_array = np.asarray(devices).reshape(shape)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, AXES)
    if set_current:
        set_current_mesh(mesh)
    return mesh


def remesh(
    spec: MeshSpec | None = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Rebuild the process-wide mesh in place — the re-mesh half of the
    elastic resize path (``runtime/distributed.rebuild_process_group``).

    Unlike tearing down the whole process group, this replaces ONLY the
    mesh: jitted functions, live arrays, and the rest of process state
    survive; callables compiled against the OLD mesh keep working on it
    (meshes are immutable), while new compilations pick up the new
    shape. Callers re-placing state onto the new mesh do so through the
    ordinary Strategy.place / checkpoint-restore machinery.
    """
    return make_mesh(spec, devices=devices, set_current=True)


def set_current_mesh(mesh: Optional[Mesh]) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def current_mesh() -> Mesh:
    """The process-wide mesh, creating a default (pure-dp) one on demand."""
    global _CURRENT_MESH
    if _CURRENT_MESH is None:
        _CURRENT_MESH = make_mesh(set_current=False)
    return _CURRENT_MESH


def mesh_axis_size(axis: str, mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or current_mesh()
    return mesh.shape[axis]


def data_axes() -> Tuple[str, ...]:
    """Axes over which the global batch is sharded (dp and fsdp)."""
    return ("dp", "fsdp")
