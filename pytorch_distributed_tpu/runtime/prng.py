"""Deterministic PRNG management.

The reference's recipes seed torch/numpy per process and rely on
per-rank offsets. Under single-controller SPMD there is one logical
program, so randomness is a single key tree: a base seed, folded with
stable integer tags (step number, purpose) — never Python-side RNG state
that could drift from the compiled program.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np

_SEED: int = 0


def seed_all(seed: int) -> None:
    """Set the process-wide base seed (and numpy, for host-side shuffles)."""
    global _SEED
    _SEED = int(seed)
    np.random.seed(seed % (2**32))


def base_key() -> jax.Array:
    """Fresh key from the base seed.

    Built on every call (never cached): a cached key array created while
    tracing would leak a tracer into global state; a fresh
    ``jax.random.key(int)`` is constant-folded by jit anyway.
    """
    return jax.random.key(_SEED)


def key_for(step: int, tag: int = 0) -> jax.Array:
    """Stable per-(step, tag) key: fold_in twice, no sequential state."""
    return jax.random.fold_in(jax.random.fold_in(base_key(), step), tag)


class RngSeq:
    """Stateful convenience for eager call sites (init, data shuffling).

    Inside jitted code pass explicit keys (``key_for``) instead — hidden
    state cannot cross a trace boundary.
    """

    def __init__(self, seed: int = 0):
        self._key = jax.random.key(seed)

    def next(self, n: int = 1):
        keys = jax.random.split(self._key, n + 1)
        self._key = keys[0]
        return keys[1] if n == 1 else keys[1:]

    def __iter__(self) -> Iterator[jax.Array]:
        while True:
            yield self.next()
