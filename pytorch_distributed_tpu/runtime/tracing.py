"""Unified runtime tracing + goodput accounting.

The repo could tell you *that* a step was slow, not *where* the time
went. This module is the measurement substrate: a process-wide span
recorder buffering Chrome ``trace_event`` records, three production
sentinels on the same stream, and the goodput account that turns a
chaos-drill's wall clock into productive-vs-recovery seconds.

* :func:`span` — ``with span("data.fetch"):`` around any host-side
  phase. Complete ("X") events carry microsecond ts/dur, pid/tid, so
  ``trace.json`` loads directly in Perfetto / chrome://tracing and
  spans from loader threads land on their own track.
* :func:`instant` / :func:`counter` — point events and gauges (e.g.
  ``device_bytes_in_use``) on the same timeline.
* :func:`note_compiles` — the recompile sentinel: instrumented code
  reports its jitted callable's compile count (serve's
  ``decode_compiles``/``prefill_compiles`` counters, the Trainer's
  ``jit_cache_size`` poll); the FIRST observation is the warm-up
  baseline, any later increase logs loudly — a steady-state loop that
  recompiles is the classic silent 100x regression.
* :class:`GoodputAccount` — classifies wall time into ``productive`` /
  ``stalled`` / ``recovering`` (+ ``checkpoint``) buckets; whatever is
  not attributed is ``other_s``, so the buckets always sum to wall.
* ``Tracer.write_rollups`` — per-span count/total/mean/p50/p95/p99
  through the existing MetricsWriter JSONL protocol
  (``split="trace"``), consumed by ``scripts/obs_report.py``. Spans
  whose args carry ``wire_bytes`` (the ``comm.*`` collective spans,
  runtime/hostring.py) additionally accumulate an exact byte total, so
  rollups report achieved GB/s per op.
* :func:`set_meta` — process-level trace metadata (rank, world size,
  measured clock offset). Lives at module scope, NOT on the tracer, so
  a group initialised before the tracer is armed still stamps the
  export; ``scripts/trace_merge.py`` aligns per-rank traces with it.

Overhead discipline (same as runtime/faults.py): unarmed — the
production default — every instrumentation site is a single
module-global ``is None`` test. A kwarg-free ``span()`` then returns
one shared no-op object: no allocation, no clock read. Sites that
attach args (``span("ingest.fetch", n=len(indices))``) additionally
pay Python's kwargs dict + argument evaluation before the is-None
test — keep hot-path sites kwarg-free or ~ms-grained. Pinned by
bench.py's ``observability`` phase: traced-vs-untraced < 2%.

Arming::

    tracer = tracing.configure("/tmp/run")     # or TrainerConfig.trace
    ...                                        # instrumented code runs
    tracer.export()                            # -> /tmp/run/trace.json
    tracer.write_rollups(metrics_writer)       # -> JSONL rollups
    tracing.clear()

or scoped (tests)::

    with tracing.enabled() as t:
        ...

This module deliberately imports no jax: it must stay importable (and
cheap) from the data-loader producer thread and from host-only tools.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from pytorch_distributed_tpu.utils.logging import get_logger
from pytorch_distributed_tpu.utils.timing import percentile

logger = get_logger(__name__)

#: goodput bucket names every summary reports (extra buckets are kept too).
#: ``resize`` is the in-process elastic window (train/elastic_world.py):
#: peer-loss detection -> membership re-rendezvous -> in-memory re-shard —
#: distinct from ``recovering`` (restore + replay), so the resize cost is
#: a priced fact the bench's ``elastic`` phase compares against restart.
#: ``rebalance`` (r15) is the heterogeneity balancer's own overhead — the
#: rate allgather + assignment derivation at each rebalance boundary
#: (train/balance.py) — priced separately so the balancing win the bench
#: ``hetero`` phase claims is net of what the balancer itself costs.
GOODPUT_BUCKETS = ("productive", "stalled", "recovering", "checkpoint",
                   "resize", "rebalance")


class _NullSpan:
    """The disabled path's shared no-op span: reentrant, allocation-free."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

_tracer: Optional["Tracer"] = None

# process-level trace metadata (rank / world_size / clock_offset_s, ...):
# survives configure()/clear() cycles and is snapshotted into every
# export's otherData, whichever side of the arming it was stamped on
_meta: Dict[str, Any] = {}


def set_meta(**kv) -> None:
    """Stamp process-level metadata into every later trace export."""
    _meta.update(kv)


def get_meta() -> Dict[str, Any]:
    return dict(_meta)


class _Span:
    """One live span: clock read on enter, record appended on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, *exc):
        t = self._tracer
        t.complete(self._name, self._args, self._t0, t._clock())
        return False


class Tracer:
    """Buffers trace events + per-span rollups; thread-safe.

    ``trace_dir`` is where :meth:`export` writes ``trace.json`` (None =
    in-memory only, export takes an explicit path). Memory is bounded
    on BOTH sides of a run longer than the buffers: the event buffer is
    capped at ``max_events`` — beyond it events are DROPPED (loudly,
    once, with the drop count recorded in the export's ``otherData``)
    — while the rollup aggregates keep exact count/total/max forever
    (three scalars per span name) and bound the percentile sample at
    ``sample_cap`` recent durations per name, so a day-long traced
    serve run cannot grow host memory without limit.
    """

    def __init__(
        self,
        trace_dir: Optional[str] = None,
        *,
        max_events: int = 200_000,
        sample_cap: int = 8192,
        clock=time.perf_counter,
    ):
        self.trace_dir = trace_dir
        self.max_events = int(max_events)
        self.sample_cap = int(sample_cap)
        self._clock = clock
        self._t0 = clock()
        self._wall0 = time.time()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self.dropped = 0
        self._stats: Dict[str, list] = {}  # name -> [count, total_s, max_s]
        self._samples: Dict[str, Any] = {}  # name -> bounded recent durations
        self._bytes: Dict[str, int] = {}  # name -> exact wire-byte total
        self._compiles: Dict[str, int] = {}  # last observed compile count
        self.recompiles: Dict[str, int] = {}  # compiles AFTER warm-up

    # -- recording ---------------------------------------------------------
    def span(self, name: str, args: Optional[dict] = None) -> _Span:
        return _Span(self, name, args)

    def _ts_us(self, t: float) -> float:
        return (t - self._t0) * 1e6

    def _append(self, ev: Dict[str, Any]) -> None:
        # caller holds self._lock
        if len(self._events) >= self.max_events:
            if self.dropped == 0:
                logger.warning(
                    "trace buffer full (%d events) — dropping further "
                    "events; rollup aggregates keep counting",
                    self.max_events,
                )
            self.dropped += 1
            return
        self._events.append(ev)

    def complete(self, name: str, args, t0: float, t1: float) -> None:
        """Record a finished span (also the hook tests feed directly)."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": round(self._ts_us(t0), 3),
            "dur": round((t1 - t0) * 1e6, 3),
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        dur = t1 - t0
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = [0, 0.0, 0.0]
                self._samples[name] = collections.deque(
                    maxlen=self.sample_cap
                )
            st[0] += 1
            st[1] += dur
            if dur > st[2]:
                st[2] = dur
            self._samples[name].append(dur)
            if args:
                wb = args.get("wire_bytes")
                if wb:  # exact like count/total: scalars, never sampled
                    self._bytes[name] = self._bytes.get(name, 0) + int(wb)
            self._append(ev)

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "s": "p",  # process-scoped marker line
            "ts": round(self._ts_us(self._clock()), 3),
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._append(ev)

    def name_thread(self, name: str) -> None:
        """Label the CALLING thread's track in the export (Chrome "M"
        thread_name metadata) — the grad-sync comm thread names its own
        lane so ``comm.*`` spans issued off the main thread read as
        "grad-sync-comm" in Perfetto, not a bare thread id."""
        ev = {
            "name": "thread_name",
            "ph": "M",
            "pid": self._pid,
            "tid": threading.get_ident(),
            "args": {"name": name},
        }
        with self._lock:
            self._append(ev)

    def counter(self, name: str, value: float) -> None:
        ev = {
            "name": name,
            "ph": "C",
            "ts": round(self._ts_us(self._clock()), 3),
            "pid": self._pid,
            "tid": threading.get_ident(),
            "args": {"value": value},
        }
        with self._lock:
            self._append(ev)

    # -- recompile sentinel ------------------------------------------------
    def note_compiles(self, name: str, n: int) -> None:
        """Report a callable's cumulative compile count.

        The first report is the warm-up baseline (compiling once is the
        contract, not a bug); every later increase is a steady-state
        recompile — counted, marked on the timeline, and logged loudly.
        """
        with self._lock:
            prev = self._compiles.get(name)
            self._compiles[name] = n
            if prev is None or n <= prev:
                return
            new = n - prev
            self.recompiles[name] = self.recompiles.get(name, 0) + new
        logger.warning(
            "RECOMPILE detected: %r compiled %d more time(s) after "
            "warm-up (now %d total) — a steady-state loop that "
            "recompiles is the classic silent 100x regression; look for "
            "changing shapes/dtypes/weak types/static args",
            name, new, n,
        )
        self.instant("recompile", {"callable": name, "total_compiles": n})

    # -- aggregates --------------------------------------------------------
    def rollups(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name aggregate: count/total/mean/p50/p95/p99/max.

        count/total/mean/max are exact over the whole run; percentiles
        come from the ``sample_cap`` most recent durations per name.
        Spans that recorded ``wire_bytes`` args (the ``comm.*`` sites)
        also report the exact byte total and achieved GB/s.
        """
        with self._lock:
            items = {
                k: (list(st), list(self._samples[k]))
                for k, st in self._stats.items()
            }
            byte_totals = dict(self._bytes)
        out: Dict[str, Dict[str, float]] = {}
        for name in sorted(items):
            (count, total, mx), sample = items[name]
            out[name] = {
                "count": count,
                "total_ms": total * 1e3,
                "mean_ms": total / count * 1e3,
                "p50_ms": percentile(sample, 50) * 1e3,
                "p95_ms": percentile(sample, 95) * 1e3,
                "p99_ms": percentile(sample, 99) * 1e3,
                "max_ms": mx * 1e3,
            }
            nbytes = byte_totals.get(name)
            if nbytes:
                out[name]["bytes_total"] = nbytes
                if total > 0:
                    out[name]["gb_per_s"] = nbytes / total / 1e9
        return out

    def write_rollups(self, writer, step: int = 0) -> None:
        """Emit rollups through the MetricsWriter JSONL protocol — one
        ``event="span_rollup"`` record per span name plus one
        ``event="recompiles"`` record, all under ``split="trace"``."""
        for name, roll in self.rollups().items():
            writer.write(
                step, {"event": "span_rollup", "span": name, **roll},
                split="trace",
            )
        rec = {
            "event": "recompiles",
            "recompiles_total": sum(self.recompiles.values()),
        }
        for name, n in sorted(self.recompiles.items()):
            rec[f"recompiles.{name}"] = n
        writer.write(step, rec, split="trace")

    # -- export ------------------------------------------------------------
    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write Chrome trace_event JSON, loadable in Perfetto and
        chrome://tracing. Default path: ``<trace_dir>/trace.json``."""
        if path is None:
            if self.trace_dir is None:
                return None
            path = os.path.join(self.trace_dir, "trace.json")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
            recompiles = dict(self.recompiles)
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_start_unix_s": self._wall0,
                "pid": self._pid,
                "dropped_events": dropped,
                "recompiles": recompiles,
                "meta": dict(_meta),
            },
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # a killed export never leaves a torn file
        return path


# -- module-level sites (the is-None fast path) ----------------------------
def span(name: str, **args):
    """Span context manager; shared no-op when tracing is disarmed."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return _Span(t, name, args or None)


def instant(name: str, **args) -> None:
    t = _tracer
    if t is None:
        return
    t.instant(name, args or None)


def name_thread(name: str) -> None:
    """Name the calling thread's trace track; no-op when disarmed."""
    t = _tracer
    if t is None:
        return
    t.name_thread(name)


def counter(name: str, value) -> None:
    t = _tracer
    if t is None:
        return
    t.counter(name, value)


def note_compiles(name: str, n: Optional[int]) -> None:
    """Recompile-sentinel site; no-op when disarmed or ``n`` unknown."""
    t = _tracer
    if t is None or n is None:
        return
    t.note_compiles(name, int(n))


def active() -> bool:
    return _tracer is not None


def get() -> Optional[Tracer]:
    return _tracer


def configure(trace_dir: Optional[str] = None, **kw) -> Tracer:
    """Arm the process-wide tracer (replacing any active one)."""
    global _tracer
    _tracer = Tracer(trace_dir, **kw)
    return _tracer


def clear() -> None:
    """Disarm: every later site check is the single is-None test again."""
    global _tracer
    _tracer = None


@contextlib.contextmanager
def enabled(trace_dir: Optional[str] = None, **kw):
    """Scoped arming for tests; restores the previous tracer on exit."""
    global _tracer
    prev = _tracer
    t = configure(trace_dir, **kw)
    try:
        yield t
    finally:
        _tracer = prev


# -- goodput accounting ----------------------------------------------------
class GoodputAccount:
    """Wall-time classifier: productive / stalled / recovering / checkpoint.

    ``productive`` is compiled train/eval step execution (dispatch + the
    syncs that block on it); ``recovering`` is restore, stranded-
    checkpoint recovery, and resume batch replay; ``checkpoint`` is
    proactive save/swing time; ``stalled`` is watchdog-detected idle.
    Everything unattributed is reported as ``other_s`` (data wait,
    logging, python glue), so the buckets ALWAYS sum to wall:

        productive + stalled + recovering + checkpoint + other == wall_s

    ``goodput_pct`` — the headline number chaos drills track — is
    productive seconds over wall seconds since construction.
    """

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.started_at = clock()
        self._lock = threading.Lock()
        self.buckets: Dict[str, float] = {}

    def add(self, bucket: str, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._lock:
            self.buckets[bucket] = self.buckets.get(bucket, 0.0) + seconds

    def retract(self, bucket: str, seconds: float) -> None:
        """Withdraw seconds mistakenly attributed to ``bucket`` (clamped
        at its balance). The consumer is stall reclassification: a
        watchdog 'stall' that RESOLVES inside an attributed section was
        a slow op, not a hang — its wall time is already covered by the
        section's own add(), and leaving it in ``stalled`` too would
        break the buckets-sum-to-wall invariant."""
        if seconds <= 0:
            return
        with self._lock:
            cur = self.buckets.get(bucket, 0.0)
            self.buckets[bucket] = max(cur - seconds, 0.0)

    def wall_s(self) -> float:
        return max(self._clock() - self.started_at, 1e-9)

    def goodput_pct(self) -> float:
        return min(
            self.buckets.get("productive", 0.0) / self.wall_s(), 1.0
        ) * 100.0

    def summary(self) -> Dict[str, float]:
        with self._lock:
            buckets = dict(self.buckets)
        wall = self.wall_s()
        out: Dict[str, float] = {
            "wall_s": wall,
            "goodput_pct": min(
                buckets.get("productive", 0.0) / wall, 1.0
            ) * 100.0,
        }
        for b in sorted(set(GOODPUT_BUCKETS) | set(buckets)):
            out[f"{b}_s"] = buckets.get(b, 0.0)
        out["other_s"] = max(wall - sum(buckets.values()), 0.0)
        return out


def summarize_goodput(records, wall_s: Optional[float] = None) -> dict:
    """Aggregate ``split="goodput"`` MetricsWriter records — possibly
    several attempts of a killed/restarted run — into one account.

    ``wall_s`` overrides the denominator: a chaos drill passes its OWN
    wall clock (including restart gaps and killed attempts whose
    records never flushed), so the headline ``goodput_pct`` charges
    everything the drill lived through, not just what survived to disk.
    """
    g = [r for r in records if r.get("split") == "goodput"]
    out: Dict[str, Any] = {"attempts_recorded": len(g)}
    keys = set()
    for r in g:
        keys.update(k for k in r if k.endswith("_s"))
    for k in sorted(keys | {f"{b}_s" for b in GOODPUT_BUCKETS}
                    | {"other_s", "wall_s"}):
        out[k] = sum(float(r.get(k, 0.0)) for r in g)
    wall = wall_s if wall_s is not None else out.get("wall_s", 0.0)
    out["goodput_pct"] = (
        round(100.0 * out.get("productive_s", 0.0) / wall, 2)
        if wall > 0 else 0.0
    )
    if wall_s is not None:
        out["wall_s"] = wall_s
    return out
