"""Process-group-shaped facade over XLA collectives.

The reference's recipes call ``torch.distributed.init_process_group('nccl')``
then use rank-centric collectives (BASELINE.json:5). Under single-controller
SPMD there are no ranks — one Python process drives every chip, and
collectives are compiler-inserted ops over the mesh. This module keeps the
*texture* of that API so recipe scripts read like the originals, with honest
single-controller semantics:

* ``init_process_group`` builds the device mesh ("the world") and picks a
  backend: ``"ici"`` — XLA collectives over ICI/DCN on TPU (the NCCL
  equivalent), ``"gloo"``/``"cpu"`` — the same XLA collectives on host CPU
  devices (smoke-test path, matching the reference's gloo recipe,
  BASELINE.json:7).
* Eager collectives (``all_reduce`` & co) take an array whose leading
  dimension is the participant axis — "each participant's tensor" — and
  reduce/gather across it on-device via ``shard_map``. Inside a jitted
  step you don't call these: you call ``jax.lax.psum`` et al. directly (or
  let sharding propagation insert them).
* ``get_rank()`` is the controller process index (0 on a single host) —
  used by recipes only to gate logging/checkpointing, which is exactly what
  it still means here.
* ``backend="hostring"`` — the genuine multi-process path: when launched
  one-process-per-rank (``pytorch_distributed_tpu.run`` / ``spawn``, the
  torchrun/mp.spawn texture of BASELINE.json:5), ranks rendezvous over the
  native shared-memory collectives library (``native/hostring.cpp``, the
  gloo equivalent) and the eager collectives below take *this rank's local
  tensor* — exact torch.distributed semantics. Selected automatically when
  ``RANK``/``WORLD_SIZE`` env vars are present (set by the launcher).
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from pytorch_distributed_tpu.runtime.compat import shard_map

from pytorch_distributed_tpu.runtime import device as _device
from pytorch_distributed_tpu.runtime import mesh as _mesh


class ReduceOp(enum.Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


@dataclasses.dataclass
class ProcessGroup:
    mesh: Mesh
    backend: str
    ring: Optional[object] = None  # HostRingGroup in multi-process mode
    ring_name: Optional[str] = None  # the ring's shm name (subgroup prefix)

    @property
    def size(self) -> int:
        if self.ring is not None:
            return self.ring.world_size
        return int(np.prod(list(self.mesh.shape.values())))


_GROUP: Optional[ProcessGroup] = None
_INIT_GENERATION = 0  # per-init shm-name suffix; see hostring re-init guard

_BACKENDS = ("ici", "cpu")


def init_process_group(
    backend: Optional[str] = None,
    *,
    mesh_spec: Optional[_mesh.MeshSpec] = None,
    world_size: Optional[int] = None,
    rank: Optional[int] = None,
    group_name: Optional[str] = None,
    timeout_s: float = 120.0,
) -> ProcessGroup:
    """Create the global "world": a mesh over all addressable devices.

    ``backend=None`` auto-selects ``"ici"`` on TPU and ``"cpu"`` otherwise.
    ``world_size`` may restrict to the first N devices (smoke tests).

    When this process was launched one-per-rank (``rank`` given, or
    ``RANK``/``WORLD_SIZE`` in the env — the launcher sets them), the group
    joins the native shared-memory backend instead: real multi-process
    collectives, matching the reference's gloo smoke path.
    """
    global _GROUP
    if rank is None and "RANK" in os.environ:
        rank = int(os.environ["RANK"])
    # Multi-host (pod) launch: one controller per host. Rendezvous first so
    # jax.devices() spans the pod, then fall through to the single-controller
    # path — RANK here is the host index, not a per-device rank.
    if os.environ.get("PTD_MULTIHOST") == "1":
        _ensure_multihost_init()
        rank = None
    if backend == "hostring" or (
        rank is not None and backend in (None, "gloo", "cpu")
    ):
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        if world_size is None and "WORLD_SIZE" in os.environ:
            world_size = int(os.environ["WORLD_SIZE"])
        if world_size is None:
            raise ValueError("multi-process init needs world_size (or env)")
        if rank is None:
            raise ValueError(
                "multi-process init needs this process's rank (arg or RANK "
                "env) — every rank defaulting to 0 would corrupt the group"
            )
        if mesh_spec is not None:
            # Recipes pass MeshSpec(dp=-1) unconditionally; under the
            # launcher each rank drives ONE device, so specs that resolve
            # to a single device are fine (wildcards collapse to 1). Only
            # an explicit multi-device request is a conflict.
            if any(s > 1 for s in mesh_spec.sizes()):
                raise ValueError(
                    f"mesh_spec {mesh_spec} requests multiple devices but "
                    "this process was launched one-rank-per-process "
                    "(RANK/WORLD_SIZE set): each rank drives one device. "
                    "Unset RANK/WORLD_SIZE to run single-controller SPMD "
                    "with a mesh."
                )
        if _GROUP is not None and _GROUP.ring is not None:
            _GROUP.ring.close()  # re-init: release the old shm membership
        if group_name is None:
            # the launcher hands every worker a per-rendezvous group name
            group_name = os.environ.get("PTD_GROUP_NAME", "ptd_world")
        # Re-init race guard: after close(), a fast peer's fresh hr_init
        # could attach the OLD segment before rank 0 unlinks/recreates it
        # (its magic is still set), splitting the group until timeout. A
        # per-init generation suffix gives every rendezvous a fresh shm
        # name; all ranks tear down and re-init in lockstep (collectives
        # are group-wide), so the counter stays in step across processes.
        global _INIT_GENERATION
        _INIT_GENERATION += 1
        ring_name = f"{group_name}_g{_INIT_GENERATION}"
        # clock_sync: the WORLD ring measures per-rank wall-clock offsets
        # at init (barrier handshake) and stamps them into the trace
        # metadata so scripts/trace_merge.py can align per-rank
        # timelines. Subgroups skip it — their ranks are renumbered and
        # the world's offsets already cover every process.
        ring = HostRingGroup(
            ring_name, rank, world_size, timeout_s=timeout_s,
            clock_sync=True,
        )
        # Each rank still gets a local 1-device mesh so jit/sharding code
        # paths work unchanged within the rank.
        mesh = _mesh.make_mesh(
            _mesh.MeshSpec(dp=1), devices=jax.devices("cpu")[:1]
        )
        _GROUP = ProcessGroup(
            mesh=mesh, backend="hostring", ring=ring, ring_name=ring_name
        )
        return _GROUP
    if backend is None:
        backend = "ici" if _device.is_tpu() else "cpu"
    if backend in ("nccl", "xla"):
        # Reference recipes say init_process_group('nccl') (BASELINE.json:5)
        # and the torch-xla port spelling is 'xla'; the TPU equivalent of
        # both fast paths is XLA collectives over ICI.
        backend = "ici" if _device.is_tpu() else "cpu"
    elif backend == "gloo":
        backend = "cpu"
    if backend not in _BACKENDS:
        raise ValueError(f"Unknown backend {backend!r}; expected one of {_BACKENDS}")
    if backend == "ici" and not _device.is_tpu():
        raise RuntimeError(
            "backend='ici' requires TPU devices; use 'cpu' (gloo-equivalent) "
            "for the host smoke path"
        )
    # The cpu/gloo path asks the CPU backend for its devices explicitly:
    # the default platform may be TPU (and on axon images the plugin
    # registration pins it), but jax.devices("cpu") still yields the host
    # devices, honouring --xla_force_host_platform_device_count.
    devices = jax.devices("cpu") if backend == "cpu" else jax.devices()
    if world_size is not None:
        if world_size > len(devices):
            raise ValueError(f"world_size {world_size} > {len(devices)} devices")
        devices = devices[:world_size]
    mesh = _mesh.make_mesh(mesh_spec, devices=devices)
    _GROUP = ProcessGroup(mesh=mesh, backend=backend)
    return _GROUP


_MULTIHOST_DONE = False


def _ensure_multihost_init() -> None:
    global _MULTIHOST_DONE
    if not _MULTIHOST_DONE:
        from pytorch_distributed_tpu.launch import init_multihost

        init_multihost()
        _MULTIHOST_DONE = True


def rebuild_process_group(
    *,
    ring=None,
    mesh_spec: Optional[_mesh.MeshSpec] = None,
    world_size: Optional[int] = None,
) -> ProcessGroup:
    """Re-mesh the world IN PROCESS — the elastic resize path.

    Where ``destroy_process_group`` + ``init_process_group`` is the
    die-and-restore shape (everything rebuilt from scratch), this swaps
    only what a membership change invalidates and keeps the process —
    its jit caches, host state, and page cache — alive:

    * ``ring=...`` (hostring backend): adopt an already-committed epoch
      ring from :class:`runtime.membership.WorldMembership` — the old
      ring is closed, open subgroups (which indexed the OLD rank space)
      are closed, and the rank-local 1-device mesh is kept.
    * ``mesh_spec``/``world_size`` (single-controller SPMD): rebuild the
      mesh over the surviving device set via :func:`runtime.mesh.remesh`
      (e.g. a pod slice shrank); callers then re-place state through the
      Strategy / checkpoint machinery.

    Raises unless a group already exists — rebuilding nothing is a
    caller bug, not a bootstrap path.
    """
    global _GROUP
    if _GROUP is None:
        raise RuntimeError(
            "rebuild_process_group needs a live group; call "
            "init_process_group first"
        )
    for sub in _SUBGROUPS:  # subgroup ranks indexed the old world
        sub.close()
    _SUBGROUPS.clear()
    _collective.cache_clear()
    if ring is not None:
        if _GROUP.ring is not None and _GROUP.ring is not ring:
            _GROUP.ring.close()
        _GROUP = ProcessGroup(
            mesh=_GROUP.mesh, backend="hostring", ring=ring,
            ring_name=getattr(ring, "name", None),
        )
        return _GROUP
    if _GROUP.ring is not None:
        raise ValueError(
            "hostring groups rebuild around a committed membership "
            "ring; pass ring=..."
        )
    devices = list(_GROUP.mesh.devices.flat)
    if world_size is not None:
        if world_size > len(devices):
            raise ValueError(
                f"world_size {world_size} > {len(devices)} devices in "
                "the current mesh — a grown device set needs a fresh "
                "init_process_group"
            )
        devices = devices[:world_size]
    mesh = _mesh.remesh(mesh_spec, devices=devices)
    _GROUP = ProcessGroup(mesh=mesh, backend=_GROUP.backend)
    return _GROUP


def multiprocess_ring():
    """The HostRingGroup when running one-process-per-rank, else None.

    The public accessor for "is this the true multi-process path" — data
    loaders, samplers, and the DDP grad sync all key off it.
    """
    g = _GROUP
    return g.ring if g is not None else None


def destroy_process_group() -> None:
    global _GROUP
    for sub in _SUBGROUPS:  # torch destroys all groups, not just the world
        sub.close()
    _SUBGROUPS.clear()
    if _GROUP is not None and _GROUP.ring is not None:
        _GROUP.ring.close()
    _GROUP = None
    _mesh.set_current_mesh(None)
    _collective.cache_clear()


def is_initialized() -> bool:
    return _GROUP is not None


def _group() -> ProcessGroup:
    if _GROUP is None:
        init_process_group()
    return _GROUP  # type: ignore[return-value]


_SUBGROUP_SEQ = 0
_SUBGROUPS: list = []  # open subgroups; destroy_process_group closes them


class Subgroup:
    """Handle from :func:`new_group` — collectives over a rank subset.

    ``ring`` is a member-only dedicated shm ring under the hostring
    backend; single-controller SPMD needs no extra state (subgroup
    collectives select the member rows of the participant dim).
    """

    def __init__(self, ranks, *, ring=None, member: bool):
        self.ranks = ranks
        self.ring = ring
        self.is_member = member

    @property
    def size(self) -> int:
        return len(self.ranks)

    def close(self) -> None:
        if self.ring is not None:
            self.ring.close()
            self.ring = None


def new_group(ranks, *, timeout_s: float = 60.0) -> Subgroup:
    """``torch.distributed.new_group``: a subgroup of the world.

    torch's contract carries over: EVERY process must call ``new_group``
    with the same ``ranks`` in the same order (bystanders included —
    under the hostring backend the call sequence number names the
    subgroup's shm segment, so out-of-order creation would cross-wire
    groups). Member ranks of a hostring world rendezvous a dedicated shm
    ring; bystanders get a handle whose collectives refuse loudly. Under
    single-controller SPMD any process may use the handle — a subgroup
    collective reduces/gathers only the member rows of the leading
    participant dim.
    """
    global _SUBGROUP_SEQ
    g = _group()
    rs = tuple(sorted(int(r) for r in ranks))
    if not rs:
        raise ValueError("new_group needs at least one rank")
    if len(set(rs)) != len(rs):
        raise ValueError(f"ranks must be unique, got {rs}")  # like torch —
        # silently deduplicating would mask a buggy rank list (AVG would
        # divide by the wrong size)
    if rs[0] < 0 or rs[-1] >= g.size:
        raise ValueError(f"ranks {rs} out of range for world size {g.size}")
    _SUBGROUP_SEQ += 1
    if g.ring is not None:
        member = g.ring.rank in rs
        ring = None
        if member:
            from pytorch_distributed_tpu.runtime.hostring import (
                HostRingGroup,
            )

            # prefixed with the WORLD ring's per-launch/per-generation shm
            # name: concurrent launches can't cross-wire, and the
            # launcher's teardown glob ('<name>_g*') reaps crashed
            # subgroup segments along with the world's
            name = (
                f"{g.ring_name}_sub{_SUBGROUP_SEQ}_"
                + "_".join(map(str, rs))
            )
            ring = HostRingGroup(
                name, rs.index(g.ring.rank), len(rs), timeout_s=timeout_s
            )
        sub = Subgroup(rs, ring=ring, member=member)
        _SUBGROUPS.append(sub)
        return sub
    sub = Subgroup(rs, member=True)
    _SUBGROUPS.append(sub)
    return sub


def _subgroup_rows(x, group: Subgroup):
    x = jnp.asarray(x)
    if x.shape[0] != _group().size:
        raise ValueError(
            f"subgroup collectives take the FULL participant dim "
            f"(world={_group().size}), got leading dim {x.shape[0]}"
        )
    return x[jnp.asarray(group.ranks)]


def _require_member(group: Subgroup, what: str):
    if not group.is_member:
        raise RuntimeError(
            f"{what} on a subgroup this rank is not a member of "
            f"(ranks={group.ranks})"
        )
    if group.ring is None:
        raise RuntimeError(f"{what} on a closed subgroup")


def _no_axis_with_group(axis):
    if axis is not None:
        raise ValueError(
            "axis and group are mutually exclusive: subgroup ranks index "
            "the flattened world, not a mesh axis"
        )


_SUB_REDUCE = {
    ReduceOp.SUM: jnp.sum,
    ReduceOp.AVG: jnp.mean,
    ReduceOp.MAX: jnp.max,
    ReduceOp.MIN: jnp.min,
    ReduceOp.PRODUCT: jnp.prod,
}


def get_world_size() -> int:
    """Total devices in the world — the SPMD analogue of nranks."""
    return _group().size


def get_rank() -> int:
    """Controller process index; gates logging/checkpoint like rank==0.

    Under the hostring (multi-process) backend this is the real rank."""
    g = _GROUP
    if g is not None and g.ring is not None:
        return g.ring.rank
    return _device.process_index()


def get_backend() -> str:
    return _group().backend


# --------------------------------------------------------------------------
# Eager collectives.
#
# Convention: the input's leading dimension indexes participants (size must
# equal the product of the mesh axes being reduced over). This is the
# single-controller translation of "every rank passes its tensor".
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _collective(kind: str, op: ReduceOp, axes: tuple, mesh: Mesh):
    in_spec = P(axes)

    def reduce_fn(v):  # v: this participant's tensor (leading dim stripped)
        if op is ReduceOp.SUM:
            return lax.psum(v, axes)
        if op is ReduceOp.AVG:
            return lax.pmean(v, axes)
        if op is ReduceOp.MAX:
            return lax.pmax(v, axes)
        if op is ReduceOp.MIN:
            return lax.pmin(v, axes)
        if op is ReduceOp.PRODUCT:
            g = lax.all_gather(v, axes)  # [participants, ...]
            return jnp.prod(g, axis=0)
        raise ValueError(op)

    if kind == "all_reduce":

        def f(x):  # x: [1, ...] per-shard slice of the participant dim
            return reduce_fn(x[0])

        out_spec = P()
    elif kind == "all_to_all":

        def f(x):
            # participant p sends chunk j of its [W*c, ...] row to j and
            # concatenates what it receives — torch all_to_all_single
            return lax.all_to_all(
                x[0], axes, split_axis=0, concat_axis=0, tiled=True
            )[None]

        out_spec = P(axes)
    elif kind == "permute":
        # op smuggles the perm tuple (hashable) through the lru_cache key
        perm = op

        def f(x):
            return lax.ppermute(x, axes, perm=perm)

        out_spec = P(axes)
    elif kind == "all_gather":

        def f(x):
            return lax.all_gather(x, axes, tiled=True)

        out_spec = P()
    elif kind == "reduce_scatter":

        def f(x):
            # x per-shard: [1, participants * chunk, ...]; sum across
            # participants, each keeps its chunk -> global result is the
            # reduced vector, sharded over the axis.
            return lax.psum_scatter(x[0], axes, scatter_dimension=0, tiled=True)

        out_spec = P(axes)
    else:
        raise ValueError(kind)

    fn = shard_map(
        f, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )
    return jax.jit(fn)


def _participant_axes(axis) -> tuple:
    if axis is None:
        return tuple(a for a in _mesh.AXES)
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def _check_leading(x, axes, mesh) -> int:
    size = int(np.prod([mesh.shape[a] for a in axes]))
    if x.shape[0] != size:
        raise ValueError(
            f"leading dim {x.shape[0]} must equal participant count {size} "
            f"for axes {axes}"
        )
    return size


def all_reduce(x, op: ReduceOp = ReduceOp.SUM, *, axis=None, group=None):
    """Reduce across the leading (participant) dim; returns shape x[0].

    ``axis=None`` reduces over the whole mesh. Under the hostring backend
    ``x`` is this rank's local tensor (torch semantics) and the result has
    the same shape. ``group`` (from :func:`new_group`) restricts the
    collective to a rank subset.
    """
    g = _group()
    if group is not None:
        _no_axis_with_group(axis)
        if g.ring is not None:
            _require_member(group, "all_reduce")
            return jnp.asarray(
                group.ring.all_reduce(np.asarray(x), op=op.value)
            )
        return _SUB_REDUCE[op](_subgroup_rows(x, group), axis=0)
    if g.ring is not None:
        return jnp.asarray(g.ring.all_reduce(np.asarray(x), op=op.value))
    axes = _participant_axes(axis)
    x = jnp.asarray(x)
    _check_leading(x, axes, g.mesh)
    fn = _collective("all_reduce", op, axes, g.mesh)
    return fn(jax.device_put(x, NamedSharding(g.mesh, P(axes))))


def all_gather(x, *, axis=None, group=None):
    """Gather participant slices; identity values, replicated layout.

    Under hostring: gathers each rank's local tensor into [world, ...].
    With ``group``: [len(group.ranks), ...] in member order."""
    g = _group()
    if group is not None:
        _no_axis_with_group(axis)
        if g.ring is not None:
            _require_member(group, "all_gather")
            return jnp.asarray(group.ring.all_gather(np.asarray(x)))
        return _subgroup_rows(x, group)
    if g.ring is not None:
        return jnp.asarray(g.ring.all_gather(np.asarray(x)))
    axes = _participant_axes(axis)
    x = jnp.asarray(x)
    _check_leading(x, axes, g.mesh)
    fn = _collective("all_gather", ReduceOp.SUM, axes, g.mesh)
    return fn(jax.device_put(x, NamedSharding(g.mesh, P(axes))))


def reduce_scatter(x, op: ReduceOp = ReduceOp.SUM, *, axis=None):
    """Reduce across participants, scatter chunks of dim 1 back over them.

    Input: [participants, participants * chunk, ...] — returns the
    reduced array of shape [participants * chunk, ...], sharded over the axis.
    """
    if op is not ReduceOp.SUM:
        raise NotImplementedError("reduce_scatter supports SUM")
    g = _group()
    if g.ring is not None:
        return jnp.asarray(g.ring.reduce_scatter(np.asarray(x), op="sum"))
    axes = _participant_axes(axis)
    x = jnp.asarray(x)
    _check_leading(x, axes, g.mesh)
    fn = _collective("reduce_scatter", op, axes, g.mesh)
    return fn(jax.device_put(x, NamedSharding(g.mesh, P(axes))))


def all_gather_into_tensor(x, *, axis=None, group=None):
    """torch >= 1.13 flat-tensor all_gather: participants' tensors are
    CONCATENATED along dim 0 — :func:`all_gather` stacks them on a new
    leading dim; this flattens the first two dims to match torch."""
    g = all_gather(x, axis=axis, group=group)
    if g.ndim <= 1:
        return g  # scalar participants: stacked == concatenated
    return g.reshape((-1,) + tuple(g.shape[2:]))


def reduce_scatter_tensor(x, op: ReduceOp = ReduceOp.SUM, *, axis=None):
    """torch >= 1.13 flat-tensor reduce_scatter.

    Under hostring (real multi-process ranks) this is torch-exact: this
    rank's flat ``[world*n, ...]`` input returns its reduced ``[n, ...]``
    chunk. Under single-controller SPMD it reduces to
    :func:`reduce_scatter`'s facade semantics — the returned array holds
    EVERY chunk (reduced, sharded over the axis), this module's usual
    "SPMD produces the value everywhere" convention.
    """
    g = _group()
    if g.ring is not None:
        arr = np.asarray(x)
        w = g.ring.world_size
        if arr.shape[0] % w:
            raise ValueError(
                f"reduce_scatter_tensor input dim 0 ({arr.shape[0]}) must "
                f"divide by world_size {w}"
            )
        return jnp.asarray(
            g.ring.reduce_scatter(
                arr.reshape((w, arr.shape[0] // w) + arr.shape[1:]),
                op=op.value,
            )
        )
    return reduce_scatter(x, op, axis=axis)


def broadcast(x, src: int = 0, *, axis=None, group=None):
    """Replicate participant ``src``'s slice to everyone (shape x[0]).

    Under hostring: replicates rank ``src``'s local tensor (torch shape).
    With ``group``: ``src`` is a GLOBAL rank and must be a member."""
    g = _group()
    if group is not None:
        _no_axis_with_group(axis)
        if src not in group.ranks:
            raise ValueError(f"src {src} not in group ranks {group.ranks}")
        if g.ring is not None:
            _require_member(group, "broadcast")
            return jnp.asarray(
                group.ring.broadcast(
                    np.asarray(x), src=group.ranks.index(src)
                )
            )
        return _subgroup_rows(x, group)[group.ranks.index(src)]
    if g.ring is not None:
        return jnp.asarray(g.ring.broadcast(np.asarray(x), src=src))
    axes = _participant_axes(axis)
    x = jnp.asarray(x)
    size = _check_leading(x, axes, g.mesh)
    if not 0 <= src < size:
        raise ValueError(f"src {src} out of range for {size} participants")
    return jax.device_put(x[src], NamedSharding(g.mesh, P()))


def all_to_all(x, *, axis=None):
    """Each participant splits its row into per-peer chunks and exchanges.

    Input [participants, participants * chunk, ...]; output the same shape
    where ``out[p] = concat_j x[j][p-th chunk]`` — the facade translation
    of ``torch.distributed.all_to_all_single`` (the Ulysses/expert-parallel
    exchange). Rides the ICI as one XLA AllToAll.
    """
    g = _group()
    if g.ring is not None:
        return jnp.asarray(g.ring.all_to_all(np.asarray(x)))
    axes = _participant_axes(axis)
    x = jnp.asarray(x)
    size = _check_leading(x, axes, g.mesh)
    if x.ndim < 2 or x.shape[1] % size != 0:
        raise ValueError(
            f"all_to_all needs dim 1 divisible by participant count {size}, "
            f"got shape {x.shape}"
        )
    fn = _collective("all_to_all", ReduceOp.SUM, axes, g.mesh)
    return fn(jax.device_put(x, NamedSharding(g.mesh, P(axes))))


def permute(x, perm, *, axis=None):
    """Point-to-point block exchange: ``out[dst] = x[src]`` per (src, dst).

    The TPU-native replacement for NCCL send/recv pairs — a ``ppermute``
    whose transfers ride the ICI torus concurrently (neighbor exchanges,
    halo swaps, pipeline handoffs). Destinations no pair names receive
    zeros. For true host-side P2P under the multi-process backend, use
    ``HostRingGroup.send``/``recv``.
    """
    g = _group()
    if g.ring is not None:
        raise NotImplementedError(
            "permute is an SPMD collective; under the hostring backend use "
            "HostRingGroup.send/recv"
        )
    axes = _participant_axes(axis)
    x = jnp.asarray(x)
    size = _check_leading(x, axes, g.mesh)
    perm = tuple((int(s), int(d)) for s, d in perm)
    for s, d in perm:
        if not (0 <= s < size and 0 <= d < size):
            raise ValueError(f"perm pair ({s},{d}) out of range for {size}")
    fn = _collective("permute", perm, axes, g.mesh)
    return fn(jax.device_put(x, NamedSharding(g.mesh, P(axes))))


def gather(x, dst: int = 0, *, axis=None, group=None):
    """Gather participant slices to ``dst`` (torch.distributed.gather).

    Single-controller SPMD has no per-rank host to collect *to* — the
    controller addresses every shard — so this is ``all_gather`` with the
    torch call shape; ``dst`` is accepted for recipe-script parity.
    """
    del dst
    return all_gather(x, axis=axis, group=group)


def reduce(x, dst: int = 0, op: ReduceOp = ReduceOp.SUM, *, axis=None,
           group=None):
    """Reduce to ``dst`` (torch.distributed.reduce).

    In torch only rank ``dst``'s output is defined; under single-controller
    SPMD (and over the hostring, where the shm ring computes the full
    reduction anyway) producing the reduced value everywhere costs nothing
    extra, so this is ``all_reduce`` with the torch call shape.
    """
    del dst
    return all_reduce(x, op=op, axis=axis, group=group)


def monitored_barrier(timeout_s: Optional[float] = None) -> None:
    """torch.distributed.monitored_barrier: a barrier that fails loudly.

    Under the hostring backend the native barrier already enforces the
    group's init-time deadline and poisons the group with a timeout error
    when a rank never arrives — exactly monitored_barrier's job, so this
    is that barrier; a per-call ``timeout_s`` differing from the compiled
    group deadline (tighter OR looser) cannot be honored and is rejected
    rather than silently ignored. Under single-controller SPMD there are
    no peer processes to straggle.
    """
    g = _group()
    if timeout_s is not None and g.ring is not None and (
        timeout_s != g.ring.timeout_s
    ):
        raise NotImplementedError(
            f"per-call timeout {timeout_s}s differs from the compiled "
            f"group deadline ({g.ring.timeout_s}s), which cannot be "
            "overridden per call in either direction; pass timeout_s at "
            "init_process_group instead"
        )
    barrier()


def scatter(x, src: int = 0, *, axis=None):
    """Scatter ``src``'s per-participant slices (torch.distributed.scatter).

    Input [participants, ...] (the list rank ``src`` would pass in torch);
    participant p's slice is row p — returned sharded over ``axis`` so each
    device holds exactly its row.
    """
    g = _group()
    if g.ring is not None:
        return jnp.asarray(g.ring.scatter(np.asarray(x), src=src))
    axes = _participant_axes(axis)
    x = jnp.asarray(x)
    size = _check_leading(x, axes, g.mesh)
    if not 0 <= src < size:
        raise ValueError(f"src {src} out of range for {size} participants")
    return jax.device_put(x, NamedSharding(g.mesh, P(axes)))


def barrier(group=None) -> None:
    """Synchronize: run a whole-mesh psum and block on the result.

    With ``group``: only the member ranks synchronize (hostring); a
    single controller is trivially synchronized already."""
    g = _group()
    if group is not None:
        if g.ring is not None:
            _require_member(group, "barrier")
            group.ring.barrier()
        return
    if g.ring is not None:
        g.ring.barrier()
        return
    n = g.size
    x = jnp.ones((n,), jnp.int32)
    out = all_reduce(x.reshape(n, 1), ReduceOp.SUM)
    jax.block_until_ready(out)


# --------------------------------------------------------------------------
# Object collectives (torch.distributed.all_gather_object /
# broadcast_object_list). Objects live on HOSTS, so the participant set is
# the PROCESS world, not the device mesh: hostring ranks, pod controllers,
# or the single controller (for which these are identities — there is one
# process, so its object list is already "every process's objects").
# --------------------------------------------------------------------------


def _pickle_bytes(obj) -> np.ndarray:
    import pickle

    return np.frombuffer(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL), dtype=np.uint8
    )


def _unpickle(buf: np.ndarray):
    import pickle

    return pickle.loads(buf.tobytes())


def _gather_padded(gather_fn, world: int, payload: np.ndarray) -> list:
    """Two-phase variable-size object gather over a fixed-size transport:
    gather lengths, max-pad payloads, gather, unpickle each row."""
    lens = np.asarray(gather_fn(np.array([len(payload)], np.int64)))
    lens = lens.reshape(world)
    buf = np.zeros(int(lens.max()), np.uint8)
    buf[: len(payload)] = payload
    rows = np.asarray(gather_fn(buf)).reshape(world, -1)
    return [_unpickle(rows[r, : int(lens[r])]) for r in range(world)]


def all_gather_object(obj) -> list:
    """Gather one picklable object per process; returns the rank-ordered list.

    Ranks may contribute different-sized (or different-typed) objects.
    """
    g = _group()
    if g.ring is not None:
        return _gather_padded(
            g.ring.all_gather, g.ring.world_size, _pickle_bytes(obj)
        )
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return _gather_padded(
            multihost_utils.process_allgather,
            jax.process_count(),
            _pickle_bytes(obj),
        )
    return [obj]


def _process_world_size(g) -> int:
    if g.ring is not None:
        return g.ring.world_size
    return jax.process_count()


def scatter_object_list(objs: Optional[list], src: int = 0):
    """torch.distributed.scatter_object_list: process ``src`` supplies one
    object per process; each process receives its own. Non-src ranks may
    pass None. Single controller: returns ``objs[0]`` (a one-process
    world's scatter is the identity on its own slot).

    Failure mode (same as torch): the src-side length check below raises
    only on ``src`` — by then non-src ranks are already waiting in the
    broadcast, and they sit there until the group deadline poisons the
    group. A malformed src list is therefore an immediate error on src
    but a delayed group-timeout on its peers.
    """
    g = _group()
    world = _process_world_size(g)
    if not 0 <= src < world:
        raise ValueError(f"src {src} out of range for {world}-process world")
    rank = get_rank()  # ring rank under hostring, process index otherwise
    is_src = rank == src
    if is_src:
        if objs is None or len(objs) != world:
            raise ValueError(
                f"src must pass exactly {world} objects, got "
                f"{None if objs is None else len(objs)}"
            )
    if world == 1:
        return objs[0]
    # route through the object broadcast: src ships the whole list once
    # (object payloads are small control-plane data by contract; a
    # byte-exact per-rank scatter would save bandwidth, not semantics)
    return broadcast_object_list(
        objs if is_src else [None] * world, src=src
    )[rank]


def broadcast_object_list(objs: list, src: int = 0) -> list:
    """Replace every element with process ``src``'s list (torch semantics,
    but returned rather than mutated in place)."""
    g = _group()
    world = _process_world_size(g)
    if not 0 <= src < world:
        raise ValueError(
            f"src {src} out of range for {world}-process world"
        )
    # only src serializes (torch semantics): non-src ranks may hold
    # unpicklable placeholders and still participate
    if g.ring is not None:
        is_src = g.ring.rank == src
        payload = (
            _pickle_bytes(objs) if is_src else np.zeros(0, np.uint8)
        )
        n = int(
            np.asarray(
                g.ring.broadcast(np.array([len(payload)], np.int64), src=src)
            )[0]
        )
        buf = payload if is_src else np.zeros(n, np.uint8)
        return _unpickle(np.asarray(g.ring.broadcast(buf, src=src)))
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # broadcast_one_to_all ships process 0's value; for src != 0 route
        # through an allgather (non-src contributes None, so only src's
        # payload is ever pickled) and pick the source's row
        is_src = jax.process_index() == src
        if src == 0:
            payload = (
                _pickle_bytes(objs) if is_src else np.zeros(0, np.uint8)
            )
            n = int(
                np.asarray(
                    multihost_utils.broadcast_one_to_all(
                        np.array([len(payload)], np.int64)
                    )
                )[0]
            )
            buf = np.zeros(n, np.uint8)
            if is_src:
                buf[:] = payload
            out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
            return _unpickle(out)
        return all_gather_object(objs if is_src else None)[src]
    return list(objs)
