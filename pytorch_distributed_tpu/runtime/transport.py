"""Pluggable byte-moving transports under :class:`HostRingGroup`.

Through r15 the ring WAS the shm segment: every collective in
``runtime/hostring.py`` called straight into ``native/hostring.cpp``,
which hard-wired "distributed" to "N processes on one host". This module
splits the group into two layers:

* **The group** (``hostring.HostRingGroup``) keeps everything that makes
  the collectives torch-shaped and safe: dtype/op validation, copy-vs-
  inplace semantics, the DETAIL fingerprint handshakes, integer-avg
  floor division, the half-precision reduce_scatter round trip, the
  ``comm.*`` tracing spans, and the composed ops (all_to_all/scatter).
* **The transport** (this module) moves the bytes. It takes contiguous
  numpy arrays and implements the collective *algorithm*:
  :class:`ShmTransport` is the existing native shm ring verbatim (one
  ctypes call per op — the default, and byte-for-byte the pre-r16
  behaviour); :class:`TcpTransport` runs the SAME algorithm over a full
  socket mesh, for ranks that do not share a host.

Bit-identity contract (the load-bearing property): ``TcpTransport``
replicates ``hr_allreduce``'s exact reduction structure — payloads chunk
by ``slot_bytes``; within a chunk of ``n`` elements rank ``r`` owns
segment ``[r*seg, r*seg+sn)`` with ``seg = n // world`` (the last rank
takes the tail); the owner folds its own contribution first and then
peers in rotated rank order ``(owner+1) % world, ...``; halves
accumulate in an f32 scratch and round ONCE; AVG divides in the
accumulator before that rounding. Because owner, segmentation, and fold
order are all pure functions of ``(count, world, slot_bytes, rank)``,
the same inputs produce the same float-addition sequence on either
transport — ``tcp`` vs ``shm`` results are bit-identical at ANY world
size, which is what lets :class:`~pytorch_distributed_tpu.runtime.
hierarchy.HierarchicalGroup` swap its inter-host leg freely
(tests/test_transport.py pins the full matrix). The q8 path replicates
``quantize_block`` (256-elem blocks, scale ``amax/127``, round half
away, NaN/inf blocks poison to NaN) in numpy with the owner keeping its
exact f32 base, same as the native side.

Wire accounting: ``bytes_sent`` counts the DATA bytes this rank pushed
into its sockets (control tokens — barrier handshakes, setup frames —
excluded), so the bench's bytes-over-the-slow-link assertion is an exact
integer equality, not an estimate. ``ShmTransport`` reports the
NCCL-convention algorithmic bytes instead (a memcpy has no wire), and
says so via ``bytes_exact``.

Fault sites (``runtime/faults.py``): ``transport.link_lost`` fires at
every TCP exchange (``mode=kill`` severs the link mid-collective — the
chaos drill's injected partition; ``mode=raise`` poisons this endpoint
loudly), and ``transport.slow_link`` (``mode=throttle, factor=F``)
prices each exchange's bytes at an F-times-slower simulated link —
the deterministic "the DCN is slow" knob the bench multihost phase arms
identically under both compared paths.

Like hostring.py, this module is deliberately jax-free: spawned workers
must be able to import it without dragging in a TPU runtime.
"""

from __future__ import annotations

import ctypes
import json
import selectors
import socket
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.runtime import faults, flightrec

#: simulated slow-link bandwidth for the ``transport.slow_link`` throttle:
#: an armed factor F sleeps ``bytes * (F - 1) / SLOW_LINK_BYTES_PER_S``
#: after each data exchange — i.e. the link behaves as if it ran at
#: ``SLOW_LINK_BYTES_PER_S / F``. 1 GB/s baseline ≈ a 10 GbE DCN hop.
SLOW_LINK_BYTES_PER_S = 1e9

_CONNECT_POLL_S = 0.01

# hostring.algo_wire_bytes, bound lazily (the hostring <-> transport
# import cycle is one-directional at import time) and cached so the
# always-on flight record below costs no repeated module lookup
_algo_wire_bytes = None


def _flight_start(t: "Transport", kind: str, op: str, count: int, dtype,
                  payload_bytes: int) -> int:
    """Begin one transport-level flight record, already STARTED (the
    transport is the wire: there is no enqueued-but-not-started window
    at this layer). Always-on by design — see runtime/flightrec.py."""
    global _algo_wire_bytes
    if _algo_wire_bytes is None:
        from pytorch_distributed_tpu.runtime.hostring import algo_wire_bytes

        _algo_wire_bytes = algo_wire_bytes
    seq = flightrec.RECORDER.begin(
        kind, op, dtype, int(count),
        _algo_wire_bytes(kind, payload_bytes, t.world_size),
        t.kind, t.name,
    )
    flightrec.RECORDER.start(seq)
    return seq


class Transport:
    """The byte-moving contract under a :class:`HostRingGroup`.

    All array arguments are C-contiguous numpy arrays already validated
    by the group layer; reductions are IN PLACE on the given array.
    Implementations must be deterministic and lockstep: the same call
    sequence on every rank, no data-dependent control flow.

    Attributes: ``kind`` ("shm"/"tcp" — the per-transport label the
    ``comm.*`` spans and cost models carry), ``rank``, ``world_size``,
    ``slot_bytes`` (the chunking quantum — identical values are REQUIRED
    for cross-transport bit-identity), ``timeout_s``, ``name``,
    ``bytes_sent`` (cumulative data bytes; see ``bytes_exact``).
    """

    kind: str = "?"
    #: True when ``bytes_sent`` counts real bytes pushed to a peer
    #: (tcp); False when it is the NCCL-convention algorithmic estimate
    #: (shm — a memcpy has no wire)
    bytes_exact: bool = False

    def barrier(self) -> None:
        raise NotImplementedError

    def allreduce(self, a: np.ndarray, op: str) -> None:
        raise NotImplementedError

    def allreduce_q8(self, a: np.ndarray, op: str) -> None:
        raise NotImplementedError

    def allgather(self, src: np.ndarray, out: np.ndarray) -> None:
        raise NotImplementedError

    def reduce_scatter(self, src: np.ndarray, out: np.ndarray,
                       op: str) -> None:
        raise NotImplementedError

    def broadcast(self, buf: np.ndarray, src: int) -> None:
        raise NotImplementedError

    def sendrecv(self, buf: np.ndarray, src: int, dst: int) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


# --------------------------------------------------------------------------
# ShmTransport: the native ring, extracted verbatim.
# --------------------------------------------------------------------------
class ShmTransport(Transport):
    """The POSIX-shm native ring (``native/hostring.cpp``) behind the
    :class:`Transport` interface — one ctypes call per op, the exact
    pre-r16 code path. Default transport of :class:`HostRingGroup`; shm
    users see zero behavioural change."""

    kind = "shm"
    bytes_exact = False

    def __init__(self, name: str, rank: int, world_size: int, *,
                 slot_bytes: int = 4 << 20, timeout_s: float = 120.0):
        # imported here (not at module top) to keep the hostring <->
        # transport import cycle one-directional at import time
        from pytorch_distributed_tpu.runtime import hostring

        self._hr = hostring
        lib = hostring._load()
        handle = ctypes.c_void_p()
        # shm names must start with '/' and contain no further slashes
        shm = "/" + name.strip("/").replace("/", "_")
        rc = lib.hr_init(
            shm.encode(), rank, world_size, slot_bytes, timeout_s,
            ctypes.byref(handle),
        )
        hostring._check(rc, "init")
        self._h = handle
        self._lib = lib
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.slot_bytes = int(slot_bytes)
        self.timeout_s = float(timeout_s)
        self.bytes_sent = 0

    def _count(self, kind: str, payload_bytes: int) -> None:
        self.bytes_sent += self._hr.algo_wire_bytes(
            kind, payload_bytes, self.world_size
        )

    def barrier(self) -> None:
        seq = _flight_start(self, "barrier", "", 0, "", 0)
        self._hr._check(self._lib.hr_barrier(self._h), "barrier")
        flightrec.RECORDER.complete(seq)

    def allreduce(self, a: np.ndarray, op: str) -> None:
        seq = _flight_start(self, "all_reduce", op, a.size, a.dtype,
                            a.nbytes)
        rc = self._lib.hr_allreduce(
            self._h, a.ctypes.data_as(ctypes.c_void_p), a.size,
            self._hr._DTYPES[a.dtype], self._hr._OPS[op],
        )
        self._hr._check(rc, "all_reduce")
        flightrec.RECORDER.complete(seq)
        self._count("all_reduce", a.nbytes)

    def allreduce_q8(self, a: np.ndarray, op: str) -> None:
        seq = _flight_start(self, "all_reduce_q8", op, a.size, a.dtype,
                            self._hr.q8_wire_payload(a.size))
        rc = self._lib.hr_allreduce_q8(
            self._h, a.ctypes.data_as(ctypes.c_void_p), a.size,
            self._hr._OPS[op],
        )
        self._hr._check(rc, "all_reduce_q8")
        flightrec.RECORDER.complete(seq)
        self._count("all_reduce_q8", self._hr.q8_wire_payload(a.size))

    def allgather(self, src: np.ndarray, out: np.ndarray) -> None:
        # native dtypes gather as elements, anything else as raw bytes
        # (identical copies either way — this preserves the exact
        # pre-r16 call shape)
        if src.dtype in self._hr._DTYPES:
            count, dt = src.size, self._hr._DTYPES[src.dtype]
        else:
            count, dt = src.nbytes, self._hr._U8
        seq = _flight_start(self, "all_gather", "", src.size, src.dtype,
                            out.nbytes)
        rc = self._lib.hr_allgather(
            self._h, src.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p), count, dt,
        )
        self._hr._check(rc, "all_gather")
        flightrec.RECORDER.complete(seq)
        self._count("all_gather", out.nbytes)

    def reduce_scatter(self, src: np.ndarray, out: np.ndarray,
                       op: str) -> None:
        seq = _flight_start(self, "reduce_scatter", op, src.size,
                            src.dtype, src.nbytes)
        rc = self._lib.hr_reduce_scatter(
            self._h, src.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p), out.size,
            self._hr._DTYPES[src.dtype], self._hr._OPS[op],
        )
        self._hr._check(rc, "reduce_scatter")
        flightrec.RECORDER.complete(seq)
        self._count("reduce_scatter", src.nbytes)

    def broadcast(self, buf: np.ndarray, src: int) -> None:
        seq = _flight_start(self, "broadcast", str(src), buf.size,
                            buf.dtype, buf.nbytes)
        rc = self._lib.hr_broadcast(
            self._h, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes, src
        )
        self._hr._check(rc, "broadcast")
        flightrec.RECORDER.complete(seq)
        self._count("broadcast", buf.nbytes)

    def sendrecv(self, buf: np.ndarray, src: int, dst: int) -> None:
        kind = "send" if self.rank == src else "recv"
        seq = _flight_start(self, kind, f"{src}->{dst}", buf.size,
                            buf.dtype, buf.nbytes)
        rc = self._lib.hr_sendrecv(
            self._h, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
            src, dst,
        )
        self._hr._check(rc, "sendrecv")
        flightrec.RECORDER.complete(seq)
        if self.rank == src:
            self._count("send", buf.nbytes)

    def close(self) -> None:
        if self._h:
            self._lib.hr_finalize(self._h)
            self._h = None


# --------------------------------------------------------------------------
# The native reduction structure as pure functions (shared with tests,
# the hierarchy pricing, and anyone proving the bit-identity argument).
# --------------------------------------------------------------------------
def allreduce_ranges(count: int, world: int, chunk_elems: int,
                     *, q8: bool = False) -> List[List[Tuple[int, int]]]:
    """Per-rank owned element ranges, replicating ``hr_allreduce``'s
    per-chunk segmentation (``hr_allreduce_q8``'s with ``q8=True``:
    segments round down to 256-element blocks, last rank takes the
    tail). Returns ``ranges[rank] = [(start, length), ...]`` in global
    element offsets — the complete ownership map the owner-computes
    exchange below is built from."""
    from pytorch_distributed_tpu.runtime.hostring import Q8_BLOCK

    ranges: List[List[Tuple[int, int]]] = [[] for _ in range(world)]
    off = 0
    while off < count:
        n = min(count - off, chunk_elems)
        seg = n // world
        if q8:
            seg &= ~(Q8_BLOCK - 1)
        for r in range(world):
            s0 = r * seg
            sn = (n - s0) if r == world - 1 else seg
            if sn > 0:
                ranges[r].append((off + s0, sn))
        off += n
    return ranges


def q8_chunk_elems(slot_bytes: int) -> int:
    """Elements per q8 chunk — ``q_chunk_elems`` capped by the reduce
    scratch, exactly as ``hr_allreduce_q8`` computes it."""
    n = slot_bytes * 256 // (256 + 4)
    n = n - 8 if n > 8 else n
    return min(n, slot_bytes // 2)


def q8_quantize(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """numpy replication of ``native/hostring.cpp``'s ``quantize``:
    per-256-block scale ``amax/127``, ``x * (1/scale)`` in f32, clamp
    ±127, round half away from zero; zero blocks quantize to (0, 0);
    non-finite blocks to (1s, NaN scale). Returns ``(q int8[n],
    scales f32[ceil(n/256)])``. Same arithmetic as
    ``parallel/overlap.q8_local_roundtrip`` (pinned against the C
    output there), split into its quantize half."""
    from pytorch_distributed_tpu.runtime.hostring import Q8_BLOCK

    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    n = x.size
    pad = (-n) % Q8_BLOCK
    xp = np.pad(x, (0, pad)).reshape(-1, Q8_BLOCK)
    amax = np.max(np.abs(xp), axis=1)
    bad = ~(amax <= np.float32(3.4e38))  # False for NaN/inf, like the C
    s = (amax / np.float32(127.0)).astype(np.float32)
    safe = np.where(s > 0, s, np.float32(1.0))
    inv = (np.float32(1.0) / safe).astype(np.float32)
    v = np.clip(xp * inv[:, None], np.float32(-127.0), np.float32(127.0))
    v = np.trunc(v + np.copysign(np.float32(0.5), v))
    q = np.where(np.isfinite(v), v, np.float32(0)).astype(np.int8)
    q[s == 0] = 0
    q[bad] = 1
    s[bad] = np.float32("nan")
    return q.reshape(-1)[:n], s


def q8_dequantize(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """``float(q[i]) * scale(block of i)`` in f32 — ``dequant_copy``.
    NaN scales (non-finite source blocks) dequantize to NaN; zero
    scales to 0, both without special cases, exactly like the C."""
    from pytorch_distributed_tpu.runtime.hostring import Q8_BLOCK

    n = q.size
    pad = (-n) % Q8_BLOCK
    qp = np.pad(q.astype(np.float32), (0, pad)).reshape(-1, Q8_BLOCK)
    out = qp * scales.astype(np.float32)[:, None]
    return out.reshape(-1)[:n]


def _combine(acc: np.ndarray, src: np.ndarray, op: str) -> np.ndarray:
    """One fold step, matching the native ``combine`` exactly — incl.
    the comparison-based max/min (NaN loses, whichever side it is on;
    ``np.maximum`` would propagate it instead)."""
    if op in ("sum", "avg"):
        acc += src
    elif op in ("prod", "product"):
        acc *= src
    elif op == "max":
        acc = np.where(acc < src, src, acc)
    elif op == "min":
        acc = np.where(src < acc, src, acc)
    else:
        raise ValueError(f"unknown reduce op {op!r}")
    return acc


def _byte_view(a: np.ndarray) -> memoryview:
    # reinterpret as uint8 first: bf16 has no buffer-protocol format
    # char, so memoryview(a) would raise on it
    return memoryview(a.view(np.uint8))


_HELLO = "hello"


class TcpTransport(Transport):
    """Socket-mesh transport: the native ring's collectives over TCP.

    Rendezvous: rank 0 listens at ``addr`` (``host:port``); every other
    rank connects, sends a hello carrying ``(name, world, slot_bytes)``
    plus its own ephemeral listener port, and rank 0 — after validating
    the parameters exactly like ``hr_init`` validates the segment
    header — replies with the full rank->address map. The non-zero
    ranks then pairwise-connect (higher rank dials lower rank's
    listener) with the same validating handshake, yielding a full mesh:
    ``world * (world-1) / 2`` sockets, TCP_NODELAY, one per unordered
    pair.

    Collectives are owner-computes exchanges over the mesh (see
    :func:`allreduce_ranges` for the ownership math and the module
    docstring for the bit-identity argument). Every exchange interleaves
    non-blocking sends and receives through one selector loop, so
    mutually-saturating payloads cannot deadlock on socket buffers. A
    peer that dies severs the stream; this endpoint then POISONS itself
    (every later call raises immediately) and closes its sockets, which
    cascades the failure to the rest of the group within one exchange —
    the loud-failure contract the elastic re-mesh path recovers from.
    """

    kind = "tcp"
    bytes_exact = True

    def __init__(self, name: str, rank: int, world_size: int,
                 addr: str, *, slot_bytes: int = 4 << 20,
                 timeout_s: float = 120.0):
        if world_size <= 0 or not 0 <= rank < world_size:
            raise ValueError(f"bad rank {rank} / world {world_size}")
        if slot_bytes <= 0:
            raise ValueError("slot_bytes must be positive")
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.slot_bytes = int(slot_bytes)
        self.timeout_s = float(timeout_s)
        self.addr = addr
        self.bytes_sent = 0
        self._poisoned: Optional[str] = None
        self._socks: Dict[int, socket.socket] = {}
        if world_size == 1:
            return
        host, _, port = addr.rpartition(":")
        try:
            self._connect_mesh(host or "127.0.0.1", int(port))
        except BaseException:
            self._close_all()
            raise

    # -- mesh setup --------------------------------------------------------
    def _params(self) -> dict:
        return {"name": self.name, "world": self.world_size,
                "slot_bytes": self.slot_bytes}

    def _check_params(self, theirs: dict) -> Optional[str]:
        mine = self._params()
        for k, v in mine.items():
            if theirs.get(k) != v:
                return (f"{k} mismatch: peer rank {theirs.get('rank')} "
                        f"has {theirs.get(k)!r}, this rank has {v!r}")
        return None

    def _connect_mesh(self, host: str, port: int) -> None:
        deadline = time.monotonic() + self.timeout_s
        if self.rank == 0:
            lsock = socket.socket()
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lsock.settimeout(self.timeout_s)
            lsock.bind((host, port))
            lsock.listen(self.world_size)
            peers: Dict[int, Tuple[str, int]] = {}
            try:
                while len(self._socks) < self.world_size - 1:
                    conn, peer_addr = lsock.accept()
                    conn.settimeout(max(deadline - time.monotonic(), 0.1))
                    hello = _recv_json(conn)
                    err = self._check_params(hello)
                    r = int(hello.get("rank", -1))
                    if err is None and (
                        not 0 < r < self.world_size or r in self._socks
                    ):
                        err = f"bad or duplicate rank {r} in hello"
                    if err is not None:
                        _send_json(conn, {"error": err})
                        conn.close()
                        raise RuntimeError(
                            f"tcp transport handshake failed: {err}"
                        )
                    peers[r] = (peer_addr[0], int(hello["port"]))
                    self._socks[r] = conn
                peers[0] = (host, port)
                for r, conn in self._socks.items():
                    _send_json(conn, {"map": {
                        str(k): list(v) for k, v in peers.items()
                    }})
            finally:
                lsock.close()
        else:
            lsock = socket.socket()
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lsock.settimeout(self.timeout_s)
            lsock.bind(("", 0))
            lsock.listen(self.world_size)
            my_port = lsock.getsockname()[1]
            try:
                root = self._dial((host, port), deadline)
                _send_json(root, {**self._params(), "rank": self.rank,
                                  "port": my_port, "type": _HELLO})
                reply = _recv_json(root)
                if "error" in reply:
                    root.close()
                    raise RuntimeError(
                        f"tcp transport handshake rejected: "
                        f"{reply['error']}"
                    )
                self._socks[0] = root
                peers = {int(k): (v[0], int(v[1]))
                         for k, v in reply["map"].items()}
                # lower ranks listen, higher ranks dial — a fixed
                # direction per pair, so the mesh completes without a
                # connection cycle
                for r in range(1, self.rank):
                    s = self._dial(peers[r], deadline)
                    _send_json(s, {**self._params(), "rank": self.rank})
                    ack = _recv_json(s)
                    if "error" in ack:
                        s.close()
                        raise RuntimeError(
                            f"tcp transport handshake rejected by rank "
                            f"{r}: {ack['error']}"
                        )
                    self._socks[r] = s
                while len(self._socks) < self.world_size - 1:
                    conn, _ = lsock.accept()
                    conn.settimeout(max(deadline - time.monotonic(), 0.1))
                    hello = _recv_json(conn)
                    err = self._check_params(hello)
                    r = int(hello.get("rank", -1))
                    if err is None and (
                        not self.rank < r < self.world_size
                        or r in self._socks
                    ):
                        err = f"bad or duplicate rank {r} in hello"
                    if err is not None:
                        _send_json(conn, {"error": err})
                        conn.close()
                        raise RuntimeError(
                            f"tcp transport handshake failed: {err}"
                        )
                    _send_json(conn, {"ok": True})
                    self._socks[r] = conn
            finally:
                lsock.close()
        for s in self._socks.values():
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.setblocking(False)

    def _dial(self, addr: Tuple[str, int], deadline: float) -> socket.socket:
        while True:
            s = socket.socket()
            s.settimeout(max(deadline - time.monotonic(), 0.1))
            try:
                s.connect(addr)
                return s
            except OSError:
                s.close()
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"tcp transport rendezvous timed out connecting "
                        f"to {addr} (peer never listened; -110-style)"
                    ) from None
                time.sleep(_CONNECT_POLL_S)

    # -- the exchange workhorse --------------------------------------------
    def _poison(self, reason: str) -> None:
        self._poisoned = reason
        # autopsy-ready evidence before the sockets go away: the record
        # still STARTED at the head of the ring is the hung exchange
        flightrec.dump(f"tcp transport {self.name} poisoned: {reason}")
        self._close_all()

    def _close_all(self) -> None:
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._socks.clear()

    def _guard(self, what: str) -> None:
        if self._poisoned is not None:
            raise RuntimeError(
                f"tcp transport {what} failed: group poisoned "
                f"({self._poisoned}; -5-style peer death — re-mesh via "
                f"the elastic membership path)"
            )

    def _exchange(self, send: Dict[int, List[memoryview]],
                  recv: Dict[int, List[memoryview]],
                  *, control: bool = False) -> None:
        """Move every ``send`` buffer to its peer and fill every ``recv``
        buffer from its peer, interleaved through one selector loop.
        Buffer sizes are agreed by the collective's own math on both
        ends, so no framing is needed (and ``bytes_sent`` is exactly the
        data payload). ``control=True`` marks protocol tokens (barrier):
        excluded from ``bytes_sent`` and from the slow-link throttle."""
        self._guard("exchange")
        try:
            faults.check("transport.link_lost")
        except faults.InjectedFault:
            self._poison("transport.link_lost injected")
            raise
        sendq = {r: [_as_bytes(v) for v in views if v.nbytes]
                 for r, views in send.items()}
        recvq = {r: [_as_bytes(v) for v in views if v.nbytes]
                 for r, views in recv.items()}
        sendq = {r: q for r, q in sendq.items() if q}
        recvq = {r: q for r, q in recvq.items() if q}
        moved = 0
        if sendq or recvq:
            moved = self._drain(sendq, recvq)
        if not control:
            self.bytes_sent += moved
            fac = faults.throttle("transport.slow_link")
            if fac > 1.0:
                time.sleep(moved * (fac - 1.0) / SLOW_LINK_BYTES_PER_S)

    def _drain(self, sendq: Dict[int, List[memoryview]],
               recvq: Dict[int, List[memoryview]]) -> int:
        deadline = time.monotonic() + self.timeout_s
        sel = selectors.DefaultSelector()
        sent_bytes = 0
        try:
            for r in set(sendq) | set(recvq):
                sock = self._socks.get(r)
                if sock is None:
                    raise RuntimeError(f"no link to rank {r}")
                ev = 0
                if r in sendq:
                    ev |= selectors.EVENT_WRITE
                if r in recvq:
                    ev |= selectors.EVENT_READ
                sel.register(sock, ev, r)
            while sendq or recvq:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"tcp exchange timed out after "
                        f"{self.timeout_s:.0f}s (peer hung or died; "
                        f"-110-style)"
                    )
                for key, mask in sel.select(timeout=0.2):
                    r = key.data
                    if mask & selectors.EVENT_READ and r in recvq:
                        q = recvq[r]
                        n = key.fileobj.recv_into(q[0])
                        if n == 0:
                            raise RuntimeError(
                                f"rank {r} closed the link mid-exchange"
                            )
                        q[0] = q[0][n:]
                        if not q[0].nbytes:
                            q.pop(0)
                        if not q:
                            del recvq[r]
                            self._downgrade(sel, key, selectors.EVENT_READ)
                    if mask & selectors.EVENT_WRITE and r in sendq:
                        q = sendq[r]
                        n = key.fileobj.send(q[0])
                        sent_bytes += n
                        q[0] = q[0][n:]
                        if not q[0].nbytes:
                            q.pop(0)
                        if not q:
                            del sendq[r]
                            self._downgrade(sel, key, selectors.EVENT_WRITE)
        except (OSError, RuntimeError) as e:
            self._poison(str(e))
            raise RuntimeError(
                f"tcp transport exchange failed: {e} (group poisoned)"
            ) from e
        finally:
            sel.close()
        return sent_bytes

    @staticmethod
    def _downgrade(sel, key, done_event) -> None:
        remaining = key.events & ~done_event
        if remaining:
            sel.modify(key.fileobj, remaining, key.data)
        else:
            sel.unregister(key.fileobj)

    # -- collectives -------------------------------------------------------
    def barrier(self) -> None:
        if self.world_size == 1:
            return
        seq = _flight_start(self, "barrier", "", 0, "", 0)
        token = np.zeros(1, np.uint8)
        if self.rank == 0:
            gather = {r: np.zeros(1, np.uint8)
                      for r in range(1, self.world_size)}
            self._exchange({}, {r: [_byte_view(b)]
                                for r, b in gather.items()}, control=True)
            self._exchange({r: [_byte_view(token)]
                            for r in gather}, {}, control=True)
        else:
            self._exchange({0: [_byte_view(token)]}, {}, control=True)
            got = np.zeros(1, np.uint8)
            self._exchange({}, {0: [_byte_view(got)]}, control=True)
        flightrec.RECORDER.complete(seq)

    def allreduce(self, a: np.ndarray, op: str) -> None:
        if op == "avg" and a.dtype.kind not in "f" and a.dtype not in (
            np.dtype(np.float16),
        ) and str(a.dtype) != "bfloat16":
            raise ValueError(
                "op='avg' over tcp needs a float dtype (integers "
                "sum + floor-divide in the group layer, like the native "
                "ring)"
            )
        if self.world_size == 1:
            return
        esize = a.itemsize
        chunk = self.slot_bytes // esize
        if chunk == 0:
            raise ValueError("slot_bytes smaller than one element")
        fseq = _flight_start(self, "all_reduce", op, a.size, a.dtype,
                             a.nbytes)
        flat = a.reshape(-1)
        w, me = self.world_size, self.rank
        ranges = allreduce_ranges(flat.size, w, chunk)
        half = str(a.dtype) in ("float16", "bfloat16")
        # phase A: ship my copy of each owner's segments to the owner
        send = {r: [_byte_view(flat[s:s + n]) for s, n in ranges[r]]
                for r in range(w) if r != me}
        mylen = sum(n for _, n in ranges[me])
        inbox = {r: np.empty(mylen, a.dtype) for r in range(w) if r != me}
        self._exchange(send, {r: [_byte_view(b)]
                              for r, b in inbox.items()})
        if mylen:
            own = (np.concatenate([flat[s:s + n] for s, n in ranges[me]])
                   if len(ranges[me]) > 1
                   else flat[ranges[me][0][0]:
                             ranges[me][0][0] + ranges[me][0][1]].copy())
            # the native fold: own contribution first, then peers in
            # rotated rank order — the same float-addition sequence the
            # shm ring runs, hence bit-identical results
            acc = own.astype(np.float32) if half else own
            for k in range(1, w):
                src = (me + k) % w
                peer = inbox[src]
                acc = _combine(
                    acc, peer.astype(np.float32) if half else peer, op
                )
            if op == "avg":
                # divide in the accumulator BEFORE the single half
                # rounding (a rounded half sum can overflow to inf)
                acc /= acc.dtype.type(w)
            red = acc.astype(a.dtype) if half else acc
            pos = 0
            for s, n in ranges[me]:
                flat[s:s + n] = red[pos:pos + n]
                pos += n
        # phase B: ship my reduced segments to every peer; receive each
        # owner's reduced segments straight into their home slices
        send = {r: [_byte_view(flat[s:s + n]) for s, n in ranges[me]]
                for r in range(w) if r != me}
        recv = {r: [_byte_view(flat[s:s + n]) for s, n in ranges[r]]
                for r in range(w) if r != me}
        self._exchange(send, recv)
        flightrec.RECORDER.complete(fseq)

    def allreduce_q8(self, a: np.ndarray, op: str) -> None:
        from pytorch_distributed_tpu.runtime.hostring import (
            Q8_BLOCK,
            q8_wire_payload,
        )

        if op not in ("sum", "avg"):
            raise ValueError(f"q8 allreduce supports sum/avg, got {op!r}")
        if self.world_size == 1:
            return
        w, me = self.world_size, self.rank
        chunk = q8_chunk_elems(self.slot_bytes)
        if chunk < Q8_BLOCK * w:
            raise ValueError(
                f"slot_bytes {self.slot_bytes} too small for a q8 "
                f"allreduce at world {w} (needs >= {Q8_BLOCK} elems "
                "per rank per chunk, like the native ring)"
            )
        fseq = _flight_start(self, "all_reduce_q8", op, a.size, a.dtype,
                             q8_wire_payload(a.size))
        flat = a.reshape(-1)
        ranges = allreduce_ranges(flat.size, w, chunk, q8=True)

        def nsc(n: int) -> int:  # scales per n-element range
            return (n + Q8_BLOCK - 1) // Q8_BLOCK

        # phase A: quantize my copy of each owner's segments, ship
        # (q, scales) per range; owners keep their own exact f32 base
        send: Dict[int, List[memoryview]] = {}
        for r in range(w):
            if r == me:
                continue
            views: List[memoryview] = []
            for s, n in ranges[r]:
                q, sc = q8_quantize(flat[s:s + n])
                views.append(_byte_view(q))
                views.append(_byte_view(sc))
            send[r] = views
        inbox: Dict[int, List[Tuple[np.ndarray, np.ndarray]]] = {}
        recv: Dict[int, List[memoryview]] = {}
        for r in range(w):
            if r == me:
                continue
            bufs = [(np.empty(n, np.int8), np.empty(nsc(n), np.float32))
                    for _, n in ranges[me]]
            inbox[r] = bufs
            recv[r] = [v for q, sc in bufs
                       for v in (_byte_view(q), _byte_view(sc))]
        self._exchange(send, recv)
        # owner fold per range: exact own f32 base + peers dequantized
        # in rotated order; AVG divides; the reduced segment REQUANTIZES
        # and the owner takes the dequantized value too (DDP lockstep:
        # every rank must see the same bits). The fold itself runs the
        # native dequant_add kernel — the compiler contracts its
        # acc += q*s to an FMA, so only the shared compiled kernel can
        # match the shm ring bit-for-bit.
        from pytorch_distributed_tpu.runtime.hostring import _load

        dequant_add = _load().hr_q8_dequant_add
        reduced: List[Tuple[np.ndarray, np.ndarray]] = []
        for i, (s, n) in enumerate(ranges[me]):
            acc = flat[s:s + n].astype(np.float32)
            for k in range(1, w):
                src = (me + k) % w
                q, sc = inbox[src][i]
                dequant_add(
                    acc.ctypes.data_as(ctypes.c_void_p),
                    q.ctypes.data_as(ctypes.c_void_p),
                    sc.ctypes.data_as(ctypes.c_void_p), n,
                )
            if op == "avg":
                acc /= np.float32(w)
            q, sc = q8_quantize(acc)
            flat[s:s + n] = q8_dequantize(q, sc)
            reduced.append((q, sc))
        # phase B: ship the requantized segments; peers dequantize
        send = {r: [v for q, sc in reduced
                    for v in (_byte_view(q), _byte_view(sc))]
                for r in range(w) if r != me}
        recv = {}
        peer_red: Dict[int, List[Tuple[np.ndarray, np.ndarray]]] = {}
        for r in range(w):
            if r == me:
                continue
            bufs = [(np.empty(n, np.int8), np.empty(nsc(n), np.float32))
                    for _, n in ranges[r]]
            peer_red[r] = bufs
            recv[r] = [v for q, sc in bufs
                       for v in (_byte_view(q), _byte_view(sc))]
        self._exchange(send, recv)
        for r in range(w):
            if r == me:
                continue
            for (s, n), (q, sc) in zip(ranges[r], peer_red[r]):
                flat[s:s + n] = q8_dequantize(q, sc)
        flightrec.RECORDER.complete(fseq)

    def allgather(self, src: np.ndarray, out: np.ndarray) -> None:
        out_rows = out.reshape(self.world_size, -1)
        flat = src.reshape(-1)
        out_rows[self.rank] = flat
        if self.world_size == 1:
            return
        fseq = _flight_start(self, "all_gather", "", src.size, src.dtype,
                             out.nbytes)
        send = {r: [_byte_view(flat)]
                for r in range(self.world_size) if r != self.rank}
        recv = {r: [_byte_view(out_rows[r])]
                for r in range(self.world_size) if r != self.rank}
        self._exchange(send, recv)
        flightrec.RECORDER.complete(fseq)

    def reduce_scatter(self, src: np.ndarray, out: np.ndarray,
                       op: str) -> None:
        if op == "avg":
            raise ValueError("op='avg' is only supported for all_reduce")
        w, me = self.world_size, self.rank
        rows = src.reshape(w, -1)
        flat_out = out.reshape(-1)
        flat_out[...] = rows[me]
        if w == 1:
            return
        fseq = _flight_start(self, "reduce_scatter", op, src.size,
                             src.dtype, src.nbytes)
        send = {r: [_byte_view(rows[r])] for r in range(w) if r != me}
        inbox = {r: np.empty(flat_out.size, src.dtype)
                 for r in range(w) if r != me}
        self._exchange(send, {r: [_byte_view(b)]
                              for r, b in inbox.items()})
        acc = flat_out
        # same fold order as hr_reduce_scatter: own row first, then
        # peers rotated from this rank
        for k in range(1, w):
            acc = _combine(acc, inbox[(me + k) % w], op)
        flat_out[...] = acc
        flightrec.RECORDER.complete(fseq)

    def broadcast(self, buf: np.ndarray, src: int) -> None:
        if not 0 <= src < self.world_size:
            raise ValueError(f"bad broadcast src {src}")
        if self.world_size == 1:
            return
        fseq = _flight_start(self, "broadcast", str(src), buf.size,
                             buf.dtype, buf.nbytes)
        flat = buf.reshape(-1)
        if self.rank == src:
            self._exchange({r: [_byte_view(flat)]
                            for r in range(self.world_size) if r != src},
                           {})
        else:
            self._exchange({}, {src: [_byte_view(flat)]})
        flightrec.RECORDER.complete(fseq)

    def sendrecv(self, buf: np.ndarray, src: int, dst: int) -> None:
        if src == dst or not (0 <= src < self.world_size
                              and 0 <= dst < self.world_size):
            raise ValueError(f"bad p2p pair {src}->{dst}")
        if self.rank not in (src, dst):
            raise ValueError(
                f"rank {self.rank} is a bystander of p2p {src}->{dst}"
            )
        fseq = _flight_start(self, "send" if self.rank == src else "recv",
                             f"{src}->{dst}", buf.size, buf.dtype,
                             buf.nbytes)
        flat = buf.reshape(-1)
        if self.rank == src:
            self._exchange({dst: [_byte_view(flat)]}, {})
        else:
            self._exchange({}, {src: [_byte_view(flat)]})
        flightrec.RECORDER.complete(fseq)

    def close(self) -> None:
        self._close_all()


def _as_bytes(v: memoryview) -> memoryview:
    return v if v.format == "B" else v.cast("B")


# -- blocking JSON-line frames for the setup handshake ---------------------
def _send_json(sock: socket.socket, obj: dict) -> None:
    sock.sendall(json.dumps(obj).encode() + b"\n")


def _recv_json(sock: socket.socket) -> dict:
    buf = bytearray()
    while not buf.endswith(b"\n"):
        # one byte at a time: a peer that finishes ITS mesh first may
        # already have data-plane bytes queued right behind the ack on
        # this stream — a chunked read would swallow them (seen live as
        # "oversized tcp handshake frame" under the 4 MB bench payload).
        # Handshakes run once per socket and are ~100 bytes; the syscall
        # cost is irrelevant.
        b = sock.recv(1)
        if not b:
            raise RuntimeError("peer closed during tcp handshake")
        buf += b
        if len(buf) > 1 << 20:
            raise RuntimeError("oversized tcp handshake frame")
    return json.loads(buf.decode())
